#!/bin/bash
# The build gate, as one command — the analog of the reference's
# error-prone -Werror + findbugs + checkstyle Maven phase (root pom.xml,
# build-common/): static checks first, then the full suite on the virtual
# 8-device CPU mesh, then the driver gates. CI or a pre-push hook runs this.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== static checks (AST lint + resolution tier + compiled-program gate) =="
# test_hlo_gate.py first: it compiles the registered engine entrypoints
# ONCE per session — including the 2-D ('cohort','nodes') mesh wave
# (sharded2d_wave; the 2-D step is deliberately unregistered, see
# device_program._build_registry), the multi-tenant fleet pair on the
# 3-D ('tenant','cohort','nodes') mesh (fleet3d_step/fleet3d_wave, the
# zero-cross-tenant-collective budget), and the compact-state step
# (step_compact — the memory budget that freezes the dtype-narrowing
# saving; one representative per the PR-9 compile-cost convention) —
# so the lint/staticcheck tree
# sweeps in the same session reuse the facts instead of recompiling.
#
# Memory-budget regen after a compaction-policy change: run
#   python tools/staticcheck.py --update-hlo-lock
# (under XLA_FLAGS=--xla_force_host_platform_device_count=8). It refuses
# while the wide<->compact state differential disagrees — a compact layout
# that drifted from its oracle must be fixed, never frozen into the lock.
#
# test_cost_model.py rides immediately after the HLO gate: the scaling-law
# cost ladder (ISSUE 18, cost.lock.json) reuses the gate's session-cached
# base compiles, and the tree sweeps in test_lint/test_staticcheck then
# fit over the cached ladder instead of recompiling. Scaling-class regen
# after an intentional asymptotics change:
#   python tools/staticcheck.py --update-cost-lock
# It refuses while any fit is unexplained or any fact exceeds its O(N*K)
# ceiling — an unexplained or superlinear cost must be fixed, never frozen.
#
# test_dataflow.py rides immediately after the cost-model gate: the jaxpr
# provenance proofs (ISSUE 19, dataflow.lock.json) trace compile-free and
# their byte-pricing join reuses the same session-cached compiles. Regen
# after an intentional influence-structure change:
#   python tools/staticcheck.py --update-dataflow-lock
# It refuses while any proof fails — an observer leak, a cross-tenant
# edge, or an opportunity map that stops explaining the quiescent bytes
# must be fixed, never frozen.
python -m pytest tests/test_hlo_gate.py tests/test_cost_model.py tests/test_dataflow.py tests/test_lint.py tests/test_staticcheck.py -q -p no:randomly

echo "== full suite (CPU, 8 virtual devices) =="
# The static gates just ran above; the resolution tier re-imports and
# re-analyzes the whole tree, so don't pay it twice in one invocation.
python -m pytest tests/ -q \
  --ignore=tests/test_lint.py --ignore=tests/test_staticcheck.py \
  --ignore=tests/test_hlo_gate.py --ignore=tests/test_cost_model.py \
  --ignore=tests/test_dataflow.py

echo "== driver gates =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as g; fn, a = g.entry(); fn(*a); g.dryrun_multichip(8)"

echo "ALL CHECKS PASSED"
