#!/bin/bash
# Probe the axon TPU tunnel until it answers, then exit 0.
#
# The tunnel wedges for hours at a time (bench.py watchdog docstring); any
# jax.devices() call blocks forever while wedged, so each probe is timeboxed.
# Run this in the background for the whole session; the moment it exits 0,
# kick off tools/capture_tpu_evidence.sh — a live window may be short.
#
#   bash tools/tunnel_probe.sh [interval_s] [probe_timeout_s]
set -u
INTERVAL="${1:-120}"
PROBE_TIMEOUT="${2:-90}"
cd "$(dirname "$0")/.."
n=0
while true; do
  n=$((n + 1))
  out=$(timeout "$PROBE_TIMEOUT" python -c "
import jax
ds = jax.devices()
print(ds[0].platform, len(ds))
" 2>&1)
  rc=$?
  plat=$(echo "$out" | tail -1)
  echo "$(date -u +%H:%M:%S) probe $n rc=$rc [$plat]"
  if [ $rc -eq 0 ] && ! echo "$plat" | grep -q '^cpu'; then
    echo "$(date -u +%H:%M:%S) TUNNEL ALIVE: $plat"
    exit 0
  fi
  sleep "$INTERVAL"
done
