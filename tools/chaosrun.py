"""Run, fuzz, and replay deterministic chaos scenarios (rapid_tpu/sim).

Three subcommands:

``run``     one named scenario family at one seed (or a schedule JSON file),
            through the full oracle battery, writing the repro artifact
            directory (schedule + per-node flight recordings + outcome) and,
            with ``--chrome``, a Chrome trace-event file of the merged
            timeline with fault-injection annotations (via tools/traceview).

``fuzz``    N seeded random schedules; every oracle violation is shrunk to a
            minimal repro and written under the output directory. With
            ``--fleet B`` the round instead compiles B mixed scenarios —
            honest, adversarial (Byzantine false alerts against the H/L
            watermarks), and hier cross-product families — onto one batched
            engine fleet (rapid_tpu/tenancy/chaos.py), resolves them in wave
            dispatches plus the stability soak, and prints wall clock,
            first-class scenarios/sec, and per-family violation tallies;
            a violating tenant is shrunk (quiescent-filler probes at the
            same fleet shape) and written as a single-tenant fleet repro.

``replay``  re-run a written repro directory; exits nonzero iff the recorded
            violations reproduce (they must — a repro that stops failing is
            itself news worth printing). Fleet repros (the ``fleet.json``
            marker) replay through the engine fleet path with the recorded
            per-tenant knobs; quarantine exports (``fleet.json`` carrying
            ``kind: "quarantine"`` — the serving supervisor's poisoned-
            tenant artifact, rapid_tpu/serving/recovery.py) reload the
            captured state slice and re-run the deterministic health scan;
            sim repros replay through the host runner. Fleet repros written
            with a ``trace.json`` artifact (the verify run's decoded
            round-trace ring) additionally get a round-granular diff: a
            divergent replay names the FIRST round where the two engine
            histories fork, not just that the verdicts changed.

Usage:

    python tools/chaosrun.py run partition_heal --seed 3 --artifacts /tmp/r
    python tools/chaosrun.py run --schedule repro/schedule.json
    python tools/chaosrun.py fuzz --seeds 20 --out /tmp/fuzz
    python tools/chaosrun.py fuzz --fleet 256 --out /tmp/fleet
    python tools/chaosrun.py replay /tmp/fuzz/seed7
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path
from typing import List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.utils.platform import force_platform  # noqa: E402

force_platform("cpu")  # chaos simulation is a host workload; never touch a tunnel

from rapid_tpu.sim import fuzz as simfuzz  # noqa: E402
from rapid_tpu.sim.faults import FaultSchedule, ScheduleError  # noqa: E402
from rapid_tpu.sim.oracles import check_all  # noqa: E402


def _write_chrome(artifacts: Path, out: str) -> None:
    import traceview

    events = traceview.merge_events(traceview.scenario_snapshots(artifacts))
    traceview.write_chrome(events, out)
    print(f"wrote {out} ({len(events)} events)")


def cmd_run(args: argparse.Namespace) -> int:
    if args.schedule:
        schedule = FaultSchedule.from_json(Path(args.schedule).read_text())
    else:
        if not args.family:
            print("chaosrun run: need a family name or --schedule", file=sys.stderr)
            return 2
        schedule = simfuzz.scenario_family(args.family, args.seed)
    result = simfuzz.run_schedule(schedule)
    violations = check_all(result)
    artifacts = Path(
        args.artifacts
        or tempfile.mkdtemp(prefix=f"chaosrun-{schedule.name.replace('/', '-')}-")
    )
    simfuzz.write_repro(result, violations, artifacts)
    print(f"scenario {schedule.name or '(file)'}: {len(result.cuts)} cut(s), "
          f"converged={result.final_converged}, artifacts in {artifacts}")
    if args.chrome:
        _write_chrome(artifacts, args.chrome)
    for v in violations:
        print(f"VIOLATION {v}")
    return 1 if violations else 0


def cmd_fuzz(args: argparse.Namespace) -> int:
    out = Path(args.out) if args.out else Path(tempfile.mkdtemp(prefix="chaosfuzz-"))
    if args.fleet:
        return _fuzz_fleet(args, out)
    seeds = range(args.base_seed, args.base_seed + args.seeds)
    summaries = simfuzz.fuzz(seeds, out_dir=out)
    failing = [s for s in summaries if s["violations"]]
    for s in summaries:
        status = "FAIL" if s["violations"] else "ok"
        extra = (
            f" -> shrunk {s['events']}->{s['shrunk_events']} events, "
            f"repro {s.get('repro', '(not written)')}"
            if s["violations"]
            else ""
        )
        print(f"seed {s['seed']}: {status}{extra}")
        for v in s["violations"]:
            print(f"  {v}")
    print(f"{len(summaries) - len(failing)}/{len(summaries)} seeds clean; "
          f"repros under {out}" if failing else
          f"{len(summaries)}/{len(summaries)} seeds clean")
    return 1 if failing else 0


def _fuzz_fleet(args: argparse.Namespace, out: Path) -> int:
    """The batched adversarial round: B scenarios per dispatch through the
    tenancy fleet, scenarios/sec as the headline, per-family tallies."""
    from rapid_tpu.tenancy import chaos as tchaos

    summary = tchaos.fuzz_fleet(
        args.fleet, base_seed=args.base_seed, out_dir=out
    )
    for family in sorted(summary["families"]):
        total = summary["families"][family]
        bad = summary["family_violations"].get(family, 0)
        print(f"family {family}: {total - bad}/{total} clean"
              + (f" ({bad} violating)" if bad else ""))
    for v in summary["violations"]:
        print(f"VIOLATION {v}")
    if "shrunk_tenant" in summary:
        print(f"shrunk tenant {summary['shrunk_tenant']} to "
              f"{summary['shrunk_events']} event(s) in "
              f"{summary['shrink_runs']} probe run(s); repro "
              f"{summary.get('repro', '(not written)')}")
    print(f"{summary['tenants']} scenarios in {summary['dispatches']} "
          f"dispatch(es), {summary['total_cuts']} view changes, "
          f"{summary['wall_ms']:.0f} ms wall — "
          f"{summary['scenarios_per_sec']:.1f} scenarios/sec")
    return 1 if summary["violations"] else 0


def cmd_replay(args: argparse.Namespace) -> int:
    if (Path(args.repro) / "fleet.json").exists():
        return _replay_fleet(args)
    recorded_path = Path(args.repro) / "violations.txt"
    recorded = (
        [line for line in recorded_path.read_text().splitlines()
         if line and line != "(none)"]
        if recorded_path.exists()
        else []
    )
    result, violations = simfuzz.replay(args.repro)
    for v in violations:
        print(f"VIOLATION {v}")
    if recorded and sorted(map(str, violations)) != sorted(recorded):
        print("chaosrun replay: violations DIVERGED from the recorded repro:",
              file=sys.stderr)
        for line in recorded:
            print(f"  recorded: {line}", file=sys.stderr)
        return 1
    if args.chrome:
        with tempfile.TemporaryDirectory() as fresh:
            simfuzz.write_repro(result, violations, fresh)
            _write_chrome(Path(fresh), args.chrome)
    return 1 if violations else 0


def _replay_fleet(args: argparse.Namespace) -> int:
    """Replay a single-tenant FLEET repro through the engine fleet path:
    shrinker artifacts (the per-tenant quiescent-filler repro) re-run the
    recorded schedule with the recorded knobs; quarantine exports (the
    serving supervisor's ``kind: "quarantine"`` marker) reload the captured
    poisoned state slice and re-run the deterministic health scan."""
    from rapid_tpu.tenancy import chaos as tchaos

    recorded_path = Path(args.repro) / "violations.txt"
    recorded = (
        [line for line in recorded_path.read_text().splitlines()
         if line and line != "(none)"]
        if recorded_path.exists()
        else []
    )
    recipe = json.loads((Path(args.repro) / "fleet.json").read_text())
    if recipe.get("kind") == "quarantine":
        from rapid_tpu.serving import recovery

        violations = recovery.replay_quarantine_repro(args.repro)
    else:
        _result, violations = tchaos.replay_fleet_repro(args.repro)
    for v in violations:
        print(f"VIOLATION {v}")
    diverged = recorded and sorted(map(str, violations)) != sorted(recorded)
    if recipe.get("kind") != "quarantine":
        # Round-granular divergence instrument: diff the replayed engine's
        # decoded trace ring against the write-time trace.json. Pre-trace
        # repro dirs (no artifact) skip this silently — they stay
        # replayable on verdicts alone.
        trace_diff = tchaos.replay_trace_divergence(args.repro)
        if trace_diff is not None:
            fork = trace_diff["first_divergent_round"]
            if fork is None:
                print(
                    f"trace: rings agree record-for-record "
                    f"({trace_diff['replayed_rounds']} round(s) recorded)"
                )
            else:
                print(
                    f"trace: round histories FORK at round {fork} "
                    f"(written {trace_diff['written_rounds']} round(s), "
                    f"replayed {trace_diff['replayed_rounds']})",
                    file=sys.stderr,
                )
                diverged = True
    if diverged:
        print("chaosrun replay: violations DIVERGED from the recorded repro:",
              file=sys.stderr)
        for line in recorded:
            print(f"  recorded: {line}", file=sys.stderr)
        return 1
    return 1 if violations else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="chaosrun",
        description="deterministic chaos scenarios: run, fuzz, replay",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    run_p = sub.add_parser("run", help="run one named scenario or schedule file")
    # choices= comes straight from the FAMILIES registry (never a re-typed
    # list): a typo'd family errors with the real vocabulary, and the
    # chaosvocab lint pins that this wiring cannot drift.
    run_p.add_argument("family", nargs="?", default=None,
                       choices=sorted(simfuzz.FAMILIES),
                       help="scenario family (hier-profile families boot the "
                            "two-level hierarchical protocol, rapid_tpu/hier; "
                            "traceview lanes their artifacts by cohort)")
    run_p.add_argument("--seed", type=int, default=0)
    run_p.add_argument("--schedule", default=None, metavar="JSON",
                       help="run this schedule file instead of a named family")
    run_p.add_argument("--artifacts", default=None, metavar="DIR",
                       help="repro artifact directory (default: a fresh tmpdir)")
    run_p.add_argument("--chrome", default=None, metavar="OUT.json",
                       help="also write a Chrome trace of the merged timeline")
    run_p.set_defaults(fn=cmd_run)

    fuzz_p = sub.add_parser("fuzz", help="fuzz N random schedules, shrink failures")
    fuzz_p.add_argument("--seeds", type=int, default=10)
    fuzz_p.add_argument("--base-seed", type=int, default=0)
    fuzz_p.add_argument("--out", default=None, metavar="DIR")
    fuzz_p.add_argument("--fleet", type=int, default=0, metavar="B",
                        help="instead of host-runner seeds, compile B mixed "
                             "scenarios (honest + adversarial + hier "
                             "cross-product families, independent seeds) "
                             "onto one batched engine fleet and report "
                             "scenarios/sec + per-family violation tallies; "
                             "violating tenants shrink to single-tenant "
                             "fleet repros")
    fuzz_p.set_defaults(fn=cmd_fuzz)

    replay_p = sub.add_parser("replay", help="re-run a written repro directory")
    replay_p.add_argument("repro")
    replay_p.add_argument("--chrome", default=None, metavar="OUT.json")
    replay_p.set_defaults(fn=cmd_replay)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except (ScheduleError, FileNotFoundError, json.JSONDecodeError) as exc:
        print(f"chaosrun: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
