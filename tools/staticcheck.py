"""Static-analysis tier: scope resolution + call-signature conformance.

The reference fails its build on error-prone (-Werror), findbugs, and
checkstyle findings (root pom.xml + build-common/); the AST style gate in
tests/test_lint.py covers the checkstyle analog, but nothing played the
error-prone role — the class of checks that needs RESOLUTION, not just
syntax: does this name exist, does this call match the callee's signature.
This environment ships no ruff/mypy/pyflakes, so this module implements
that tier on the stdlib:

1. **Undefined names** (`check_undefined_names`) — compiler-grade scope
   analysis via ``symtable``: every name a scope reads through the global
   scope must be bound at module level (import/assign/def/class), declared
   ``global`` and assigned in some function, or a builtin. Catches typos in
   rarely-executed paths (the error branch that NameErrors only when the
   error happens), which no test-coverage gate can promise to reach.

2. **Call conformance** (`check_call_signatures`) — for call sites whose
   callee statically resolves to a module-level object of an imported
   module (``f(...)`` where ``f`` is module-global in the calling module,
   or ``mod.f(...)`` where ``mod`` is a module-level module import), bind
   the call's shape (positional arity + keyword names) against
   ``inspect.signature`` of the real runtime object. Catches wrong-arity
   calls, typo'd keywords, and stale references to renamed module
   attributes — the highest-value slice of what a type checker does for a
   dynamically-typed codebase. Resolution is deliberately conservative:
   names shadowed in any enclosing function scope, call sites using
   ``*args``/``**kwargs``, and objects whose signature is undiscoverable
   are all skipped, so every finding is a real defect, never a maybe.

Run as a CLI (``python tools/staticcheck.py [paths...]``; nonzero exit on
findings) or via the build gate in tests/test_staticcheck.py. Importing a
module to inspect its runtime surface follows the import-time platform
rules: under pytest, tests/conftest.py has already forced the CPU backend.
"""

from __future__ import annotations

import ast
import builtins
import importlib
import inspect
import re
import symtable
import sys
import types
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

REPO = Path(__file__).resolve().parent.parent

# Module-scope dunders the compiler binds implicitly.
_IMPLICIT_GLOBALS = {
    "__name__", "__file__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__path__", "__dict__", "__class__",
}


@dataclass(frozen=True)
class Finding:
    path: str
    lineno: int
    check: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.lineno}: [{self.check}] {self.message}"


# ---------------------------------------------------------------------------
# Check 1: undefined names (symtable scope resolution)
# ---------------------------------------------------------------------------


def _global_assigned_names(table: symtable.SymbolTable) -> set:
    """Names any nested scope both declares ``global`` and assigns — those
    are module-bound at runtime even if never assigned at module scope."""
    names = set()
    for sym in table.get_symbols():
        if sym.is_global() and sym.is_assigned():
            names.add(sym.get_name())
    for child in table.get_children():
        names |= _global_assigned_names(child)
    return names


def _undefined_in_table(
    table: symtable.SymbolTable,
    bound: set,
    rel: str,
    load_lines: dict,
    findings: List[Finding],
) -> None:
    for sym in table.get_symbols():
        if not (sym.is_global() and sym.is_referenced()):
            continue
        name = sym.get_name()
        if name in bound or hasattr(builtins, name) or name in _IMPLICIT_GLOBALS:
            continue
        # Point at the offending READ, not the enclosing def: the first
        # load site at or after the scope's start line (falling back to the
        # first in the file — scope start is a lower bound, good enough to
        # land inside the right function).
        scope_start = table.get_lineno()
        lines = load_lines.get(name, [])
        lineno = next((ln for ln in lines if ln >= scope_start),
                      lines[0] if lines else scope_start)
        findings.append(
            Finding(
                rel,
                lineno,
                "undefined-name",
                f"{name!r} (read in {table.get_type()} "
                f"{table.get_name()!r}) is bound nowhere at module scope "
                "and is not a builtin",
            )
        )
    for child in table.get_children():
        _undefined_in_table(child, bound, rel, load_lines, findings)


def check_undefined_names(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Every name resolving through the global scope must exist there."""
    src = source if source is not None else path.read_text()
    rel = _rel(path)
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            # A star import makes the global namespace statically unknowable;
            # flag the import itself rather than silently skipping the file.
            return [
                Finding(rel, node.lineno, "star-import",
                        "wildcard import defeats scope analysis")
            ]
    table = symtable.symtable(src, str(path), "exec")
    bound = {s.get_name() for s in table.get_symbols() if s.is_local()}
    bound |= _global_assigned_names(table)
    load_lines: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            load_lines.setdefault(node.id, []).append(node.lineno)
    for lines in load_lines.values():
        lines.sort()
    findings: List[Finding] = []
    _undefined_in_table(table, bound, rel, load_lines, findings)
    return findings


# ---------------------------------------------------------------------------
# Check 2: call-signature conformance against imported runtime modules
# ---------------------------------------------------------------------------


class _ScopeStack:
    """Tracks, per enclosing function/lambda/comprehension scope, the names
    bound locally — so a module-global resolution is only trusted when no
    enclosing scope shadows the name."""

    def __init__(self) -> None:
        self.stack: List[set] = []

    def shadowed(self, name: str) -> bool:
        return any(name in scope for scope in self.stack)


def _local_bindings(node: ast.AST) -> set:
    """Names bound in THIS function scope (params, assignments, imports,
    inner defs) — without descending into nested function scopes."""
    names = set()
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = node.args
        for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
    body = getattr(node, "body", [])
    stack = list(body) if isinstance(body, list) else []
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(cur.name)
            continue  # nested scope: its internals don't bind here
        if isinstance(cur, ast.Lambda):
            continue
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, (ast.Store, ast.Del)):
            names.add(cur.id)
        # Bindings whose target is a plain str, not a Name node:
        if isinstance(cur, ast.ExceptHandler) and cur.name:
            names.add(cur.name)
        if isinstance(cur, (ast.MatchAs, ast.MatchStar)) and cur.name:
            names.add(cur.name)
        if isinstance(cur, ast.MatchMapping) and cur.rest:
            names.add(cur.rest)
        if isinstance(cur, (ast.Import, ast.ImportFrom)):
            for alias in cur.names:
                if alias.name != "*":
                    names.add(alias.asname or alias.name.split(".")[0])
        if isinstance(cur, (ast.Global, ast.Nonlocal)):
            # Declared non-local: reads go to the outer binding — but for
            # shadow-tracking, treating as local only SKIPS checks (safe).
            names.update(cur.names)
        stack.extend(ast.iter_child_nodes(cur))
    return names


def _module_name_for(path: Path) -> Optional[str]:
    """Import path for a repo file, or None if it isn't importable as a
    module of this repo (scripts are importable top-level: bench, etc.)."""
    try:
        rel = path.resolve().relative_to(REPO)
    except ValueError:
        return None
    parts = rel.with_suffix("").parts
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def _bindable(sig: inspect.Signature) -> bool:
    """Signatures with *args/**kwargs accept almost anything; checking them
    would only ever produce noise."""
    return not any(
        p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        for p in sig.parameters.values()
    )


def _try_signature(obj) -> Optional[inspect.Signature]:
    try:
        return inspect.signature(obj)
    except (ValueError, TypeError):
        return None


def _check_one_call(
    call: ast.Call, obj, dotted: str, rel: str, findings: List[Finding]
) -> None:
    if any(isinstance(a, ast.Starred) for a in call.args):
        return
    if any(kw.arg is None for kw in call.keywords):  # **kwargs at site
        return
    sig = _try_signature(obj)
    if sig is None or not _bindable(sig):
        return
    # Bound methods/classmethods accessed via instance aren't resolved here
    # (module-level objects only), so no self-adjustment is needed.
    placeholders = [object()] * len(call.args)
    kwargs = {kw.arg: object() for kw in call.keywords}
    try:
        sig.bind(*placeholders, **kwargs)
    except TypeError as exc:
        findings.append(
            Finding(rel, call.lineno, "call-signature",
                    f"{dotted}{sig} cannot bind this call: {exc}")
        )


def check_call_signatures(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Arity/keyword conformance for statically-resolvable call sites, plus
    existence of ``mod.attr`` references on module-level module imports."""
    src = source if source is not None else path.read_text()
    rel = _rel(path)
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    mod_name = _module_name_for(path)
    if mod_name is None:
        return []
    try:
        module = importlib.import_module(mod_name)
    except BaseException as exc:  # noqa: BLE001 — any import failure is a finding
        # BaseException, not Exception: pytest.importorskip raises Skipped,
        # which subclasses BaseException so that test code can't swallow it
        # by accident — but here it must not propagate and skip/abort the
        # whole gate.
        if type(exc).__name__ == "Skipped":
            # Module-level importorskip: the module declares an optional
            # dependency this environment lacks (e.g. hypothesis).
            # Un-analyzable here, not broken — pytest skips it the same way.
            return []
        if not isinstance(exc, Exception):
            raise  # KeyboardInterrupt / SystemExit stay fatal
        return [Finding(rel, 1, "import-error", f"cannot import {mod_name}: {exc}")]

    findings: List[Finding] = []
    scopes = _ScopeStack()

    def resolve(expr: ast.AST) -> Tuple[Optional[object], Optional[str]]:
        """(object, dotted-name) for Name / module-attribute chains bound at
        module level and unshadowed; (None, None) when not resolvable."""
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
            if scopes.shadowed(expr.id):
                return None, None
            if expr.id in vars(module):
                return vars(module)[expr.id], expr.id
            return None, None
        if isinstance(expr, ast.Attribute) and isinstance(expr.ctx, ast.Load):
            base, dotted = resolve(expr.value)
            if not isinstance(base, types.ModuleType):
                return None, None  # instance attrs are dynamic; modules aren't
            if getattr(base, "__getattr__", None) is not None:
                return None, None  # module-level __getattr__: unknowable
            if not hasattr(base, expr.attr):
                findings.append(
                    Finding(rel, expr.lineno, "missing-attribute",
                            f"module {dotted!r} has no attribute {expr.attr!r}")
                )
                return None, None
            return getattr(base, expr.attr), f"{dotted}.{expr.attr}"
        return None, None

    def visit(node: ast.AST) -> None:
        is_scope = isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef,
             ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        )
        if is_scope:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
            ):
                # Class bodies execute like function bodies: a name bound
                # earlier in the body shadows the module level for later
                # body-level references. (For functions NESTED in the class
                # the class scope is not on the lookup chain, so treating it
                # as shadowing there only skips a check — never misjudges.)
                scopes.stack.append(_local_bindings(node))
            else:
                targets = set()
                for gen in node.generators:
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name):
                            targets.add(n.id)
                scopes.stack.append(targets)
        if isinstance(node, ast.Call):
            obj, dotted = resolve(node.func)
            if obj is not None:
                _check_one_call(node, obj, dotted, rel, findings)
        elif isinstance(node, ast.Attribute):
            resolve(node)  # existence check on bare module-attr reads
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            scopes.stack.pop()

    visit(tree)
    # Attribute chains nest (resolve recurses), so the same missing
    # attribute can be recorded through both the Call and Attribute hooks.
    return sorted(set(findings), key=lambda f: (f.lineno, f.message))


# ---------------------------------------------------------------------------
# Check 3: clock injection discipline in rapid_tpu/protocol/
# ---------------------------------------------------------------------------

#: Wall-clock readers banned inside the protocol package. Every timing
#: consumer there must go through the injected Clock (utils/clock.py) /
#: Metrics ``now_ms`` source, or simulated-time tests silently measure wall
#: time (and phase SLO histograms record garbage under ManualClock).
_BANNED_CLOCK_ATTRS = frozenset(
    {"time", "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns"}
)

#: The tree this discipline applies to (posix-style relative prefix).
CLOCK_DISCIPLINE_PREFIX = "rapid_tpu/protocol/"


def check_clock_injection(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """No direct wall-clock reads (``time.time``/``time.perf_counter``/...)
    in rapid_tpu/protocol/: the clock is injected there, and this check
    keeps it that way. Both spellings are caught — attribute access on the
    ``time`` module and ``from time import perf_counter``."""
    rel = _rel(path)
    if not rel.replace("\\", "/").startswith(CLOCK_DISCIPLINE_PREFIX):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "time"
            and node.attr in _BANNED_CLOCK_ATTRS
        ):
            findings.append(
                Finding(rel, node.lineno, "clock-injection",
                        f"direct wall-clock read time.{node.attr} in the "
                        "protocol package — use the injected Clock / Metrics "
                        "now_ms source")
            )
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            banned = [a.name for a in node.names if a.name in _BANNED_CLOCK_ATTRS]
            if banned:
                findings.append(
                    Finding(rel, node.lineno, "clock-injection",
                            f"importing {', '.join(banned)} from time in the "
                            "protocol package — use the injected Clock / "
                            "Metrics now_ms source")
                )
    return findings


# ---------------------------------------------------------------------------
# Check 4: dead module-level definitions (tree-wide liveness)
# ---------------------------------------------------------------------------

DEFAULT_ROOTS = (
    "rapid_tpu", "tests", "examples", "tools", "bench.py", "__graft_entry__.py"
)

_DEF_ALLOW_PREFIXES = ("test_", "Test", "pytest_", "__")
_DEF_ALLOW_NAMES = {"main", "entry", "dryrun_multichip"}  # external entry points
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _collect_definitions(tree: ast.AST, rel: str):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield node.name, rel, node.lineno
        # Simple module constants too (plain Name targets only: tuple
        # unpacking legitimately discards elements, so it is out of scope;
        # dunders like __all__ fall to the allowlist).
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    yield target.id, rel, node.lineno
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            yield node.target.id, rel, node.lineno


def _collect_references(tree: ast.AST) -> set:
    """Every way a module-level definition can be consumed: name loads,
    attribute accesses, function parameter names (pytest fixtures are used
    by naming them as parameters), and identifiers inside CODE-LOOKING
    string constants (multi-line or call-shaped — subprocess job scripts,
    ``python -c`` payloads). Single-word strings deliberately do NOT count:
    an ``__all__`` entry must not keep an otherwise-unreferenced export
    alive — re-export padding is exactly what this check exists to catch.

    A module-level definition's OWN subtree never contributes its own name:
    a dead recursive helper, a class naming itself in a method, or a
    constant whose initializer/mutation mentions itself must not keep
    itself alive.
    """

    def walk(node, self_name):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id != self_name:
                refs.add(node.id)
        elif isinstance(node, ast.Attribute):
            if node.attr != self_name:
                refs.add(node.attr)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = node.args
            for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
                refs.add(arg.arg)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "\n" in node.value or "(" in node.value:
                refs.update(w for w in _IDENT.findall(node.value) if w != self_name)
        for child in ast.iter_child_nodes(node):
            walk(child, self_name)

    refs: set = set()
    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            for child in ast.iter_child_nodes(stmt):
                walk(child, stmt.name)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
            stmt.targets[0], ast.Name
        ):
            walk(stmt.value, stmt.targets[0].id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            walk(stmt.annotation, None)  # the type names ARE references
            if stmt.value is not None:
                walk(stmt.value, stmt.target.id)
        else:
            walk(stmt, None)
    return refs


def check_dead_definitions(
    contributions: "List[Tuple[ast.AST, str]]",
) -> List[Finding]:
    """Module-level functions/classes/constants referenced NOWHERE in the tree.

    Takes (tree, relpath) pairs for the WHOLE analyzed tree — liveness is
    only meaningful over the full root set, so run() skips this check when
    the CLI narrows the roots. Tree-wide, name-based (not resolution-based):
    a name collision anywhere keeps a definition alive, so every finding is
    a definition no file could be using. The repo's standard is that
    unconsumed code is deleted, not exported (the Mosaic watermark kernel
    precedent)."""
    defs: List[Tuple[str, str, int]] = []
    refs: set = set()
    for tree, rel in contributions:
        defs.extend(_collect_definitions(tree, rel))
        refs |= _collect_references(tree)
    findings = []
    for name, rel, lineno in defs:
        if name.startswith(_DEF_ALLOW_PREFIXES) or name in _DEF_ALLOW_NAMES:
            continue
        if name not in refs:
            findings.append(
                Finding(rel, lineno, "dead-definition",
                        f"module-level {name!r} is referenced nowhere in the tree")
            )
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def iter_files(roots: Sequence[str] = DEFAULT_ROOTS) -> Iterable[Path]:
    for root in roots:
        path = (REPO / root) if not Path(root).is_absolute() else Path(root)
        if path.is_file():
            yield path
        elif path.is_dir():
            yield from sorted(path.rglob("*.py"))
        else:
            # A typo'd or since-renamed root must fail the gate, not
            # silently exempt that tree from analysis.
            raise FileNotFoundError(f"staticcheck root does not exist: {path}")


def _rel(path: Path) -> str:
    try:
        return str(path.resolve().relative_to(REPO))
    except ValueError:
        return str(path)


def run(roots: Sequence[str] = DEFAULT_ROOTS) -> List[Finding]:
    # Mirror pytest's rootdir behavior: test modules import suite-local
    # helpers both as `tests.helpers` and bare `helpers`. Insert at the
    # FRONT: `tools`/`tests` are common top-level names, and a foreign
    # package earlier on sys.path would shadow this repo's namespace
    # packages and produce spurious import-error findings.
    for entry in (str(REPO), str(REPO / "tests")):
        if entry in sys.path:
            sys.path.remove(entry)
        sys.path.insert(0, entry)
    findings: List[Finding] = []
    trees: List[Tuple[ast.AST, str]] = []  # one parse per file, shared
    for path in iter_files(roots):
        src = path.read_text()
        tree = ast.parse(src, filename=str(path))
        trees.append((tree, _rel(path)))
        findings.extend(check_undefined_names(path, src, tree))
        findings.extend(check_call_signatures(path, src, tree))
        findings.extend(check_clock_injection(path, src, tree))
    if tuple(roots) == DEFAULT_ROOTS:
        # Liveness is only meaningful over the FULL tree: with narrowed CLI
        # roots, code consumed from outside the subset would be reported as
        # dead — so the check runs only on complete invocations.
        findings.extend(check_dead_definitions(trees))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    roots = list(argv or DEFAULT_ROOTS)
    findings = run(roots)
    for f in findings:
        print(f)
    print(f"staticcheck: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    from rapid_tpu.utils.platform import force_platform

    force_platform("cpu")  # imports must never touch a (possibly wedged) tunnel
    sys.exit(main(sys.argv[1:]))
