"""Static-analysis tier — compatible CLI/entry shim over tools/analysis/.

The analyzers grew from two check families into six and moved into the
``tools/analysis/`` package (core driver + Finding model + one module per
family — see its docstring for the catalog). This module stays as the
stable entry point: ``python tools/staticcheck.py [--json] [--select ...]
[--ignore ...] [paths...]`` and ``import staticcheck`` both keep working,
re-exporting the package API unchanged.

Tests that retarget the analysis at a temporary tree patch
``staticcheck.core.REPO`` (the package reads it at call time).
"""

from __future__ import annotations

import sys
from pathlib import Path

# The package lives next to this shim. Resolve it regardless of how the
# shim itself was imported (`staticcheck` with tools/ on sys.path, or
# `tools.staticcheck` during the gate's own call-signature pass).
_TOOLS_DIR = str(Path(__file__).resolve().parent)
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import core  # noqa: E402
from analysis import (  # noqa: E402,F401 — re-exported API surface
    ALL_CHECK_NAMES,
    CLOCK_DISCIPLINE_PREFIXES,
    CONCURRENCY_PREFIXES,
    DEFAULT_ROOTS,
    Finding,
    TRACE_SAFETY_PREFIXES,
    check_call_signatures,
    check_clock_injection,
    check_concurrency,
    check_dead_definitions,
    check_trace_safety,
    check_undefined_names,
    iter_files,
    main,
    run,
)

#: Snapshot for path construction by callers; behavior-affecting resolution
#: reads ``core.REPO`` at call time (patch that one in tests).
REPO = core.REPO

__all__ = [
    "ALL_CHECK_NAMES",
    "CLOCK_DISCIPLINE_PREFIXES",
    "CONCURRENCY_PREFIXES",
    "DEFAULT_ROOTS",
    "Finding",
    "REPO",
    "TRACE_SAFETY_PREFIXES",
    "check_call_signatures",
    "check_clock_injection",
    "check_concurrency",
    "check_dead_definitions",
    "check_trace_safety",
    "check_undefined_names",
    "core",
    "iter_files",
    "main",
    "run",
]

if __name__ == "__main__":
    sys.path.insert(0, str(core.REPO))
    from rapid_tpu.utils.platform import force_platform

    force_platform("cpu")  # imports must never touch a (possibly wedged) tunnel
    sys.exit(main(sys.argv[1:]))
