"""Static-analysis tier — compatible CLI/entry shim over tools/analysis/.

The analyzers grew from two check families into ten and moved into the
``tools/analysis/`` package (core driver + Finding model + one module per
family — see its docstring for the catalog, or ``--families``). This
module stays as the stable entry point: ``python tools/staticcheck.py
[--json] [--select ...] [--ignore ...] [--families] [--update-wire-lock]
[paths...]`` and ``import staticcheck`` both keep working, re-exporting
the package API unchanged.

Tests that retarget the analysis at a temporary tree patch
``staticcheck.core.REPO`` (the package reads it at call time).
"""

from __future__ import annotations

import sys
from pathlib import Path

# The package lives next to this shim. Resolve it regardless of how the
# shim itself was imported (`staticcheck` with tools/ on sys.path, or
# `tools.staticcheck` during the gate's own call-signature pass).
_TOOLS_DIR = str(Path(__file__).resolve().parent)
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import core  # noqa: E402
from analysis import (  # noqa: E402,F401 — re-exported API surface
    ALL_CHECK_NAMES,
    CLOCK_DISCIPLINE_PREFIXES,
    CONCURRENCY_PREFIXES,
    COST_LOCK_REL,
    DATAFLOW_LOCK_REL,
    DEFAULT_ROOTS,
    DETERMINISM_PREFIXES,
    DISPATCH_PREFIXES,
    FAMILIES,
    Finding,
    HLO_LOCK_REL,
    LEDGER_PREFIXES,
    LOCK_REL,
    SHARDING_PREFIXES,
    STREAM_PREFIXES,
    TASKFLOW_PREFIXES,
    TELEMETRY_LANE_FIELDS,
    TELEMETRY_PREFIXES,
    TRACE_SAFETY_PREFIXES,
    WIRE_FILES,
    check_call_signatures,
    check_chaosvocab,
    check_clock_injection,
    check_concurrency,
    check_cost_lock,
    check_cost_model,
    check_dataflow,
    check_dataflow_lock,
    check_dead_definitions,
    check_determinism,
    check_device_program,
    check_dispatch,
    check_hlo_lock,
    check_lane_mirror,
    check_ledger,
    check_partition_specs,
    check_sharding,
    check_taskflow,
    check_telemetry,
    check_trace_safety,
    check_undefined_names,
    check_wire_lock,
    check_wire_schema,
    collect_dataflow,
    collect_facts,
    collect_ladder,
    fit_scaling,
    iter_files,
    main,
    run,
    update_cost_lock,
    update_dataflow_lock,
    update_hlo_lock,
    update_wire_lock,
)

#: Snapshot for path construction by callers; behavior-affecting resolution
#: reads ``core.REPO`` at call time (patch that one in tests).
REPO = core.REPO

__all__ = [
    "ALL_CHECK_NAMES",
    "CLOCK_DISCIPLINE_PREFIXES",
    "CONCURRENCY_PREFIXES",
    "COST_LOCK_REL",
    "DATAFLOW_LOCK_REL",
    "DEFAULT_ROOTS",
    "DETERMINISM_PREFIXES",
    "DISPATCH_PREFIXES",
    "FAMILIES",
    "Finding",
    "HLO_LOCK_REL",
    "LEDGER_PREFIXES",
    "LOCK_REL",
    "REPO",
    "SHARDING_PREFIXES",
    "STREAM_PREFIXES",
    "TASKFLOW_PREFIXES",
    "TELEMETRY_LANE_FIELDS",
    "TELEMETRY_PREFIXES",
    "TRACE_SAFETY_PREFIXES",
    "WIRE_FILES",
    "check_call_signatures",
    "check_chaosvocab",
    "check_clock_injection",
    "check_concurrency",
    "check_cost_lock",
    "check_cost_model",
    "check_dataflow",
    "check_dataflow_lock",
    "check_dead_definitions",
    "check_determinism",
    "check_device_program",
    "check_dispatch",
    "check_hlo_lock",
    "check_lane_mirror",
    "check_ledger",
    "check_partition_specs",
    "check_sharding",
    "check_taskflow",
    "check_telemetry",
    "check_trace_safety",
    "check_undefined_names",
    "check_wire_lock",
    "check_wire_schema",
    "collect_dataflow",
    "collect_facts",
    "collect_ladder",
    "core",
    "fit_scaling",
    "iter_files",
    "main",
    "run",
    "update_cost_lock",
    "update_dataflow_lock",
    "update_hlo_lock",
    "update_wire_lock",
]

if __name__ == "__main__":
    sys.path.insert(0, str(core.REPO))
    from rapid_tpu.utils.platform import force_platform

    # Imports must never touch a (possibly wedged) tunnel — and the
    # device_program family compiles the registered engine entrypoints
    # under the same forced 8-device CPU mesh the test session uses.
    force_platform("cpu", n_host_devices=8)
    sys.exit(main(sys.argv[1:]))
