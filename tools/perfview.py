"""Render bench run ledgers and the BENCH_r* perf trajectory.

``tools/traceview.py`` answers "show me this one view change";
``tools/clustertop.py`` answers "how is the cluster doing right now"; this
tool answers the third operator question — "what happened to my benchmark
runs, and can I trust the numbers". Two input kinds, freely mixed:

- **Run ledgers** (``*.jsonl``, what ``bench.py --ledger`` appends — see
  rapid_tpu/utils/ledger.py): rendered as a stage timeline — every stage's
  begin/duration/status, compile + device-memory stats, heartbeat gaps,
  watchdog kills, snapshot replays, and the run outcome with the last
  completed stage. A wedged run reads as "died in <stage>", not a mystery.

- **Bench metric JSON** (``*.json``, the one-line artifact each bench round
  emits — BENCH_r01.json ...): rendered as a perf trajectory table, one row
  per round, flagging every point that is NOT a live measurement of the
  code it claims to measure: ``snapshot`` (replayed evidence), ``stale``
  (snapshot measured different code than HEAD), ``wedged`` (live attempt
  died), ``hole`` (explicit accelerator-unavailable marker),
  ``suspect-rate`` (a derived rate outside plausibility bounds — the
  alert_deliveries_per_sec ≈ 5e10 class of bug), ``headline-missing``
  (an audited round that carries neither the ``n1M_crash1pct_ms``
  headline nor its explicit ``n1M_status`` marker — the 1M scale number
  must never be silently absent), ``fleet-missing`` (same discipline
  for the multi-tenant point: an audited round omitting BOTH
  ``tenant_view_changes_per_sec`` and ``tenant_fleet_status``), and
  ``stream-missing`` (same discipline for the streaming-serving point:
  an audited round omitting BOTH ``stream_view_changes_per_sec`` and
  ``stream_status``), ``chaos-missing`` (same discipline for the
  adversarial-chaos point: an audited round omitting BOTH
  ``chaos_scenarios_per_sec`` and ``chaos_status``), ``mem-missing``
  (same discipline for the state-compaction memory point: an audited
  round omitting BOTH ``bytes_per_member`` and ``mem_status``), and
  ``recovery-missing`` (same discipline for the self-healing drill: an
  audited round omitting BOTH ``recovery_mttr_ms`` and
  ``recovery_status``), and ``activity-missing`` (same discipline for the
  device telemetry plane: an audited round omitting BOTH
  ``stream_active_fraction`` and ``activity_status`` — a zero-churn soak
  must publish ``activity=0`` explicitly, never silence), and
  ``cost-missing`` (same discipline for the scaling-law cost model: an
  audited round omitting the ``cost_fit`` table AND its status marker).
  The N1M, FLEET, STREAM, CHAOS, MEM, RECOVERY, ACTIVITY, and COSTFIT
  columns render the headline / fleet / sustained-stream /
  chaos-throughput / bytes-per-member / resume-MTTR / active-fraction /
  worst-fitted-scaling-class values (or their status markers) per round.

``--chrome out.json`` additionally writes Chrome trace-event JSON (the same
envelope tools/traceview.py emits — Perfetto/chrome://tracing load it):
ledger stages as duration events, point events as instants, one process
lane per ledger.

Usage:

    python tools/perfview.py bench_ledger.jsonl
    python tools/perfview.py BENCH_r0*.json
    python tools/perfview.py bench_ledger.jsonl BENCH_r0*.json --chrome t.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.utils.ledger import (  # noqa: E402
    LedgerEvent,
    last_completed_stage,
    open_stage,
    read_ledger,
)

#: A derived per-second rate above this is treated as implausible for this
#: workload class and flagged ``suspect-rate`` (no network or chip moves
#: 1e9+ distinct alert deliveries a second at these Ns — the historical
#: 4.96e10 figure came from multiplying by all N members instead of the
#: engine's C-cohort delivery grain).
SUSPECT_RATE_PER_SEC = 1e9

_POINT_EVENTS = (
    LedgerEvent.ATTEMPT_BEGIN.value,
    LedgerEvent.ATTEMPT_END.value,
    LedgerEvent.HEARTBEAT_GAP.value,
    LedgerEvent.WATCHDOG_KILL.value,
    LedgerEvent.SNAPSHOT_REPLAY.value,
    LedgerEvent.COMPILE_STATS.value,
    LedgerEvent.DEVICE_MEMORY.value,
    # Self-healing serving runtime (ISSUE 15): the recovery timeline —
    # retries, wedges, checkpoints (and corrupt-checkpoint fallbacks),
    # resumes, quarantines — renders as point events on the stage line.
    LedgerEvent.RECOVERY_RETRY.value,
    LedgerEvent.RECOVERY_WEDGED.value,
    LedgerEvent.RECOVERY_CHECKPOINT.value,
    LedgerEvent.RECOVERY_CHECKPOINT_CORRUPT.value,
    LedgerEvent.RECOVERY_RESUME.value,
    LedgerEvent.RECOVERY_QUARANTINE.value,
)


class PerfviewError(RuntimeError):
    """An input could not be read/parsed; the CLI exits 2 with the message."""


def split_runs(events: List[Dict[str, Any]]) -> List[Tuple[str, List[Dict[str, Any]]]]:
    """Group a ledger file's events by ``run_id``, in order of first
    appearance — the default bench_ledger.jsonl is append-only across
    invocations, and mixing two runs into one timeline would pin the wrong
    provenance (and the wrong outcome) on both."""
    order: List[str] = []
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for record in events:
        run_id = str(record.get("run_id", "?"))
        if run_id not in groups:
            order.append(run_id)
            groups[run_id] = []
        groups[run_id].append(record)
    return [(run_id, groups[run_id]) for run_id in order]


# ---------------------------------------------------------------------------
# Ledger rendering
# ---------------------------------------------------------------------------


def render_table(header: Tuple[str, ...],
                 rows: List[Tuple[str, ...]]) -> List[str]:
    """Fixed-width text table (header + rows, columns padded to the widest
    cell) — the one table renderer both report kinds share."""
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows else len(header[i])
        for i in range(len(header))
    ]
    return [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        for row in (header, *rows)
    ]


def stage_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Pair stage begin/end(or fail) events into timeline rows, in begin
    order. An unpaired begin renders as OPEN — exactly what a wedged run
    looks like."""
    rows: List[Dict[str, Any]] = []
    open_rows: List[Dict[str, Any]] = []
    for record in events:
        kind = record.get("event")
        if kind == LedgerEvent.STAGE_BEGIN.value:
            row = {
                "stage": record.get("stage", "?"),
                "n": record.get("n"),
                "begin_s": record.get("t_s", 0.0),
                "pid": record.get("pid"),
                "timeout_s": record.get("timeout_s"),
                "duration_ms": None,
                "status": "OPEN",
                "error": None,
            }
            rows.append(row)
            open_rows.append(row)
        elif kind in (LedgerEvent.STAGE_END.value, LedgerEvent.STAGE_FAIL.value):
            match = next(
                (r for r in reversed(open_rows)
                 if r["stage"] == record.get("stage")
                 and r["pid"] == record.get("pid")),
                None,
            )
            if match is None:
                continue  # end without begin (pre-ledger writer): skip
            open_rows.remove(match)
            match["duration_ms"] = record.get("duration_ms")
            match["status"] = (
                "ok" if kind == LedgerEvent.STAGE_END.value else "FAIL"
            )
            match["error"] = record.get("error")
    return rows


def _fmt_duration(ms: Optional[float]) -> str:
    if ms is None:
        return "-"
    if ms >= 60_000:
        return f"{ms / 60_000.0:.1f}m"
    if ms >= 1000:
        return f"{ms / 1000.0:.2f}s"
    return f"{ms:.0f}ms"


def render_ledger(path: str, events: List[Dict[str, Any]], skipped: int) -> str:
    lines: List[str] = []
    begin = next(
        (e for e in events if e.get("event") == LedgerEvent.RUN_BEGIN.value), None
    )
    lines.append(f"== run ledger {path} ==")
    if begin:
        lines.append(
            f"run {begin.get('run_id', '?')} mode={begin.get('mode', '?')}"
            f" git_rev={begin.get('git_rev')} code_hash={begin.get('code_hash')}"
        )
    header = ("T+", "STAGE", "N", "DURATION", "BUDGET", "STATUS")
    rows: List[Tuple[str, ...]] = []
    for row in stage_rows(events):
        rows.append((
            f"{row['begin_s']:.1f}s",
            str(row["stage"]),
            "-" if row["n"] is None else str(row["n"]),
            _fmt_duration(row["duration_ms"]),
            "-" if row["timeout_s"] is None else f"{row['timeout_s']:.0f}s",
            row["status"] + (f" ({row['error']})" if row["error"] else ""),
        ))
    lines.extend(render_table(header, rows))
    if not rows:
        lines.append("(no stage events)")

    for record in events:
        kind = record.get("event")
        if kind not in _POINT_EVENTS:
            continue
        fields = {
            k: v for k, v in record.items()
            if k not in ("event", "seq", "pid", "t_s", "wall", "run_id")
        }
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        lines.append(f"! {record.get('t_s', 0.0):.1f}s {kind}: {detail}")

    terminal = [
        e for e in events
        if e.get("event") in (LedgerEvent.RUN_FAIL.value, LedgerEvent.RUN_END.value)
    ]
    fails = [e for e in terminal if e.get("event") == LedgerEvent.RUN_FAIL.value]
    stuck = open_stage(events)
    # The LATEST terminal event wins: a --cpu-fallback/--allow-snapshot run
    # records the wedge (run_fail) and THEN closes successfully (run_end) —
    # event order, not event kind, decides the outcome.
    if terminal and terminal[-1]["event"] == LedgerEvent.RUN_FAIL.value:
        last = terminal[-1].get("last_completed_stage") or last_completed_stage(events)
        where = f"; wedged in {stuck['stage']!r}" if stuck else ""
        lines.append(
            f"outcome: FAILED ({terminal[-1].get('outcome') or terminal[-1].get('error')})"
            f" — last completed stage: {last or 'none'}{where}"
        )
    elif terminal:
        note = (
            f" (after run_fail: {fails[-1].get('outcome') or fails[-1].get('error')})"
            if fails else ""
        )
        lines.append(f"outcome: {terminal[-1].get('outcome', 'completed')}{note}")
    else:
        where = f" (in {stuck['stage']!r})" if stuck else ""
        lines.append(f"outcome: still running or killed mid-run{where}")
    if skipped:
        lines.append(f"({skipped} unparseable line(s) skipped)")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Trajectory rendering
# ---------------------------------------------------------------------------


def hlo_audit_table(data: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The point's per-entrypoint compiled-program audit (bench.py's
    ``hlo_audit`` key), or None when the round predates the audit or it
    errored — absence never flags, only a measured difference does."""
    table = data.get("hlo_audit")
    if not isinstance(table, dict) or "error" in table:
        return None
    return table


def hlo_drift(prev: Optional[Dict[str, Any]],
              cur: Optional[Dict[str, Any]]) -> bool:
    """True when two audited rounds disagree on any shared entrypoint's
    collective counts — the compiled communication budget moved between
    rounds (intentionally or not: the trajectory must show it either way)."""
    if not prev or not cur:
        return False
    for name in set(prev) & set(cur):
        for key in ("collectives", "hot_loop_collectives"):
            if prev[name].get(key) != cur[name].get(key):
                return True
    return False


def point_flags(
    data: Dict[str, Any], prev: Optional[Dict[str, Any]] = None
) -> List[str]:
    """The trust flags of one bench-round JSON artifact. ``prev`` is the
    nearest EARLIER round that carried an hlo_audit table (trajectory
    rendering threads it); a collective-count difference against it flags
    ``hlo-drift``."""
    flags: List[str] = []
    if "error" in data:
        flags.append("hole")
        return flags
    if data.get("live_attempt") == "wedged":
        flags.append("wedged")
    if data.get("capture") == "session_snapshot":
        flags.append("snapshot")
    if data.get("stale_code"):
        flags.append("stale")
    for key, value in data.items():
        if key.endswith("_per_sec") and isinstance(value, (int, float)):
            if value > SUSPECT_RATE_PER_SEC:
                flags.append("suspect-rate")
                break
    # Headline discipline (ISSUE 9): an AUDITED round (it carries the
    # hlo_audit table, i.e. post-promotion bench code produced it) must
    # carry the 1M headline value or its explicit n1M_status marker.
    # Pre-audit historical rounds are exempt — absence there is history,
    # not a silent drop.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(data.get("n1M_crash1pct_ms"), (int, float))
        and not data.get("n1M_status")
    ):
        flags.append("headline-missing")
    # Fleet discipline (ISSUE 10): the same rule for the multi-tenant
    # point — an audited round must carry tenant_view_changes_per_sec or
    # its explicit tenant_fleet_status marker; the fleet metric must never
    # be silently absent. Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(
            data.get("tenant_view_changes_per_sec"), (int, float)
        )
        and not data.get("tenant_fleet_status")
    ):
        flags.append("fleet-missing")
    # Streaming discipline (ISSUE 11): same rule for the sustained-serving
    # point — an audited round must carry stream_view_changes_per_sec or
    # its explicit stream_status marker; the streaming metric must never be
    # silently absent. Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(
            data.get("stream_view_changes_per_sec"), (int, float)
        )
        and not data.get("stream_status")
    ):
        flags.append("stream-missing")
    # Chaos discipline (ISSUE 12): same rule for the adversarial-chaos
    # point — an audited round must carry chaos_scenarios_per_sec or its
    # explicit chaos_status marker; the chaos throughput metric must never
    # be silently absent. Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(data.get("chaos_scenarios_per_sec"), (int, float))
        and not data.get("chaos_status")
    ):
        flags.append("chaos-missing")
    # Memory discipline (ISSUE 13): same rule for the state-compaction
    # point — an audited round must carry bytes_per_member or its explicit
    # mem_status marker; the memory-footprint metric must never be
    # silently absent. Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(data.get("bytes_per_member"), (int, float))
        and not data.get("mem_status")
    ):
        flags.append("mem-missing")
    # Recovery discipline (ISSUE 15): same rule for the self-healing drill
    # — an audited round must carry recovery_mttr_ms or its explicit
    # recovery_status marker; the resume-MTTR metric must never be
    # silently absent. Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(data.get("recovery_mttr_ms"), (int, float))
        and not data.get("recovery_status")
    ):
        flags.append("recovery-missing")
    # Activity discipline (ISSUE 16): same rule for the device telemetry
    # plane — an audited round must carry stream_active_fraction or its
    # explicit activity_status marker. A quiet cluster reads activity=0,
    # so absence is always instrumentation loss, never "nothing happened".
    # Pre-audit historical rounds are exempt.
    if (
        hlo_audit_table(data) is not None
        and not isinstance(data.get("stream_active_fraction"), (int, float))
        and not data.get("activity_status")
    ):
        flags.append("activity-missing")
    # Trace discipline (ISSUE 17): same rule for the round-trace ring — an
    # audited round must carry the round_trajectory digest's
    # rounds-to-decision p99 or its explicit trace_status marker. The ring
    # is zero-minted at attach, so absence is instrumentation loss, never
    # "nothing decided". Pre-audit historical rounds are exempt.
    trajectory = data.get("round_trajectory") or {}
    if (
        hlo_audit_table(data) is not None
        and not isinstance(
            trajectory.get("rounds_to_decision_p99"), (int, float)
        )
        and not data.get("trace_status")
    ):
        flags.append("trace-missing")
    # Cost-model discipline (ISSUE 18): same rule for the scaling-law
    # axis — an audited round must carry the cost_fit table (fitted
    # per-entrypoint scaling classes) or its explicit status marker
    # (suppressed ladder / unavailable backend). Pre-audit historical
    # rounds are exempt.
    if hlo_audit_table(data) is not None and not data.get("cost_fit"):
        flags.append("cost-missing")
    # Dataflow-provenance discipline (ISSUE 19): same rule for the jaxpr
    # proof axis — an audited round must carry the dataflow block (proof
    # verdicts + opportunity coverage, or its explicit suppressed/
    # unavailable status inside it). Pre-provenance historical rounds are
    # exempt.
    if hlo_audit_table(data) is not None and not data.get("dataflow"):
        flags.append("dataflow-missing")
    if hlo_drift(prev, hlo_audit_table(data)):
        flags.append("hlo-drift")
    if not flags:
        flags.append("live")
    return flags


def load_trajectory_point(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as exc:
        raise PerfviewError(f"{path}: cannot read: {exc}") from exc
    except json.JSONDecodeError as exc:
        raise PerfviewError(f"{path}: invalid JSON: {exc}") from exc
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        # Driver round artifact (BENCH_rNN.json): the bench's emitted JSON
        # line lives under "parsed", beside the harness's cmd/rc/tail.
        data = data["parsed"]
    if not isinstance(data, dict) or ("metric" not in data):
        raise PerfviewError(
            f"{path}: not a bench metric artifact (expected a JSON object "
            "with a 'metric' key, or a driver round file with 'parsed')"
        )
    return data


def headline_cell(data: Dict[str, Any]) -> str:
    """The N1M column: the measured 1M headline, else its explicit status
    marker, else '-' (pre-promotion rounds)."""
    value = data.get("n1M_crash1pct_ms")
    if isinstance(value, (int, float)):
        return f"{float(value):.1f}ms"
    status = data.get("n1M_status")
    return str(status) if status else "-"


def fleet_cell(data: Dict[str, Any]) -> str:
    """The FLEET column: tenant_view_changes_per_sec (with the fleet shape
    when present), else its explicit tenant_fleet_status marker, else '-'
    (pre-fleet rounds)."""
    value = data.get("tenant_view_changes_per_sec")
    if isinstance(value, (int, float)):
        return f"{float(value):.1f}/s"
    status = data.get("tenant_fleet_status")
    return str(status) if status else "-"


def stream_cell(data: Dict[str, Any]) -> str:
    """The STREAM column: sustained stream_view_changes_per_sec with the
    p99 alert->commit beside it when present, else the explicit
    stream_status marker, else '-' (pre-stream rounds)."""
    value = data.get("stream_view_changes_per_sec")
    if isinstance(value, (int, float)):
        p99 = data.get("stream_p99_alert_to_commit_ms")
        suffix = (
            f" p99={float(p99):.1f}ms" if isinstance(p99, (int, float)) else ""
        )
        return f"{float(value):.1f}/s{suffix}"
    status = data.get("stream_status")
    return str(status) if status else "-"


def mem_cell(data: Dict[str, Any]) -> str:
    """The MEM column: compact bytes/member (with the wide figure beside
    it when present), else the explicit mem_status marker, else '-'
    (pre-compaction rounds)."""
    value = data.get("bytes_per_member")
    if isinstance(value, (int, float)):
        wide = data.get("bytes_per_member_wide")
        suffix = (
            f" (wide {float(wide):.0f})" if isinstance(wide, (int, float)) else ""
        )
        return f"{float(value):.0f}B/m{suffix}"
    status = data.get("mem_status")
    return str(status) if status else "-"


def recovery_cell(data: Dict[str, Any]) -> str:
    """The RECOVERY column: the drill's resume MTTR (with the bit-identity
    verdict beside it — a resume that diverged is worse than no resume),
    else the explicit recovery_status marker, else '-' (pre-supervision
    rounds)."""
    value = data.get("recovery_mttr_ms")
    if isinstance(value, (int, float)):
        identical = data.get("recovery_bit_identical")
        suffix = "" if identical in (True, None) else " DIVERGED"
        return f"{float(value):.1f}ms mttr{suffix}"
    status = data.get("recovery_status")
    return str(status) if status else "-"


def chaos_cell(data: Dict[str, Any]) -> str:
    """The CHAOS column: adversarial scenarios resolved (and oracle-checked
    clean) per second of batched fleet dispatch, with the tenant count when
    present, else the explicit chaos_status marker, else '-' (pre-chaos
    rounds)."""
    value = data.get("chaos_scenarios_per_sec")
    if isinstance(value, (int, float)):
        tenants = data.get("chaos_tenants")
        suffix = f" B={int(tenants)}" if isinstance(tenants, int) else ""
        return f"{float(value):.1f}/s{suffix}"
    status = data.get("chaos_status")
    return str(status) if status else "-"


def activity_cell(data: Dict[str, Any]) -> str:
    """The ACTIVITY column: the stream soak's mean active-subject fraction
    (with the fast-path share beside it when present), else the explicit
    activity_status marker, else '-' (pre-telemetry rounds). A zero-churn
    soak renders '0.0%', not a dash — zero is a measurement."""
    value = data.get("stream_active_fraction")
    if isinstance(value, (int, float)):
        share = data.get("stream_fast_path_share")
        suffix = (
            f" fast={100.0 * float(share):.0f}%"
            if isinstance(share, (int, float)) else ""
        )
        return f"{100.0 * float(value):.1f}%{suffix}"
    status = data.get("activity_status")
    return str(status) if status else "-"


def trace_cell(data: Dict[str, Any]) -> str:
    """The TRACE column: the round-trajectory digest's rounds-to-decision
    p99 (with the worst wave beside it when present), else the explicit
    trace_status marker, else '-' (pre-trace rounds)."""
    trajectory = data.get("round_trajectory") or {}
    value = trajectory.get("rounds_to_decision_p99")
    if isinstance(value, (int, float)):
        worst = trajectory.get("rounds_to_decision_max")
        suffix = (
            f" max={int(worst)}" if isinstance(worst, (int, float)) else ""
        )
        return f"p99={float(value):.1f}r{suffix}"
    status = data.get("trace_status")
    return str(status) if status else "-"


#: Scaling-class vocabulary, weakest to strongest — mirrors
#: tools/analysis/cost_model.CLASSES (perfview stays import-light; the
#: spelling is part of the bench artifact contract). Classes this tool
#: does not know sort WORST — a future stronger class must never render
#: as better than the ones it replaced.
_COST_CLASS_ORDER = ("O(1)", "O(log N)", "O(N)", "O(N*K)", "O(N^2)")


def cost_cell(data: Dict[str, Any]) -> str:
    """The COSTFIT column: the WORST fitted scaling class across the
    round's audited entrypoints (with the quiescent round's collective
    payload beside it when measured), else the explicit cost_fit status
    marker, else '-' (pre-cost rounds)."""
    fit = data.get("cost_fit")
    if isinstance(fit, dict) and "status" in fit:
        return str(fit["status"])
    if isinstance(fit, dict) and fit:
        classes = [
            cls for per in fit.values() if isinstance(per, dict)
            for cls in per.values()
        ]
        if classes:
            worst = max(
                classes,
                key=lambda cls: (
                    _COST_CLASS_ORDER.index(cls)
                    if cls in _COST_CLASS_ORDER else len(_COST_CLASS_ORDER)
                ),
            )
            quiescent = data.get("quiescent_round_cost") or {}
            payload = quiescent.get("collective_payload_bytes")
            suffix = (
                f" q={int(payload)}B" if isinstance(payload, (int, float))
                else ""
            )
            return f"worst={worst}{suffix}"
    return "-"


def oppty_cell(data: Dict[str, Any]) -> str:
    """The OPPTY column: the sparse-opportunity map's coverage of the
    quiescent payload bytes with the proof verdicts beside it (ok = both
    observer-silence and tenant-isolation proven), else the explicit
    dataflow status marker, else '-' (pre-provenance rounds)."""
    df = data.get("dataflow")
    if not isinstance(df, dict):
        return "-"
    coverage = df.get("opportunity_coverage_pct")
    if isinstance(coverage, (int, float)):
        proofs = (
            "ok" if df.get("observer_silent")
            and df.get("tenant_isolated") is not False
            else "LEAK"
        )
        return f"{float(coverage):.0f}%/{proofs}"
    status = df.get("opportunity_status") or df.get("status")
    return str(status) if status else "-"


def render_trajectory(points: List[Tuple[str, Dict[str, Any]]]) -> str:
    lines = ["== perf trajectory =="]
    header = ("ROUND", "METRIC", "VALUE", "N1M", "FLEET", "STREAM", "CHAOS",
              "MEM", "RECOVERY", "ACTIVITY", "TRACE", "COSTFIT", "OPPTY",
              "PLATFORM", "VSBASE", "FLAGS")
    rows: List[Tuple[str, ...]] = []
    flag_rows: List[Tuple[str, List[str]]] = []
    prev_audit: Optional[Dict[str, Any]] = None
    for path, data in sorted(points, key=lambda p: p[0]):
        value = data.get("value")
        vs = data.get("vs_baseline", data.get("vs_baseline_at_capture"))
        flags = point_flags(data, prev=prev_audit)
        # The drift baseline is the nearest earlier AUDITED round: a hole
        # or pre-audit round in between must not reset the comparison.
        prev_audit = hlo_audit_table(data) or prev_audit
        rows.append((
            Path(path).stem,
            str(data.get("metric", "?")),
            "-" if value is None else f"{float(value):.1f}ms",
            headline_cell(data),
            fleet_cell(data),
            stream_cell(data),
            chaos_cell(data),
            mem_cell(data),
            recovery_cell(data),
            activity_cell(data),
            trace_cell(data),
            cost_cell(data),
            oppty_cell(data),
            str(data.get("platform", "-")),
            "-" if vs is None else f"{float(vs):.2f}x"
            + ("@capture" if "vs_baseline_at_capture" in data else ""),
            ",".join(flags),
        ))
        flag_rows.append((Path(path).stem, flags))
    lines.extend(render_table(header, rows))
    flagged = [
        (name, kept) for name, flags in flag_rows
        if (kept := [f for f in flags if f != "live"])
    ]
    if flagged:
        lines.append(
            "untrusted points: "
            + "; ".join(f"{name} ({','.join(flags)})" for name, flags in flagged)
        )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Chrome trace output (same envelope as tools/traceview.py)
# ---------------------------------------------------------------------------


def chrome_trace(ledgers: List[Tuple[str, List[Dict[str, Any]]]]) -> Dict[str, Any]:
    """Ledger stages as complete ('X') duration events and point events as
    thread-scoped instants, one process lane per ledger — the trace-event
    envelope Perfetto and chrome://tracing load (identical to
    traceview.chrome_trace's)."""
    trace_events: List[Dict[str, Any]] = []
    for pid, (path, events) in enumerate(ledgers, start=1):
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": str(path)},
        })
        for row in stage_rows(events):
            duration_ms = row["duration_ms"] or 0.0
            trace_events.append({
                "name": row["stage"],
                "ph": "X",
                "ts": row["begin_s"] * 1e6,  # trace-event ts is µs
                "dur": duration_ms * 1000.0,
                "pid": pid,
                "tid": 1,
                "args": {
                    "n": row["n"], "status": row["status"],
                    "timeout_s": row["timeout_s"],
                },
            })
        for record in events:
            if record.get("event") not in _POINT_EVENTS:
                continue
            trace_events.append({
                "name": record["event"],
                "ph": "i",
                "s": "t",
                "ts": record.get("t_s", 0.0) * 1e6,
                "pid": pid,
                "tid": 1,
                "args": {
                    k: v for k, v in record.items()
                    if k not in ("event", "seq", "pid", "t_s", "wall", "run_id")
                },
            })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="render bench run ledgers (stage timelines) and the "
                    "BENCH_r* perf trajectory with trust flags"
    )
    parser.add_argument(
        "inputs", nargs="+",
        help="run-ledger .jsonl files (bench.py --ledger) and/or bench "
             "metric .json artifacts (BENCH_rNN.json)",
    )
    parser.add_argument(
        "--chrome", metavar="OUT.json", default=None,
        help="also write Chrome trace-event JSON of the ledger stages "
             "(open in Perfetto)",
    )
    args = parser.parse_args(argv)

    ledgers: List[Tuple[str, List[Dict[str, Any]], int]] = []
    points: List[Tuple[str, Dict[str, Any]]] = []
    try:
        for arg in args.inputs:
            if arg.endswith(".jsonl"):
                events, skipped = read_ledger(arg)
                if not events:
                    raise PerfviewError(
                        f"{arg}: no ledger events (missing file or not a "
                        "bench run ledger)"
                    )
                ledgers.append((arg, events, skipped))
            else:
                points.append((arg, load_trajectory_point(arg)))
    except PerfviewError as exc:
        print(f"perfview: {exc}", file=sys.stderr)
        return 2

    lanes: List[Tuple[str, List[Dict[str, Any]]]] = []
    for path, events, skipped in ledgers:
        runs = split_runs(events)
        for run_id, run_events in runs:
            label = path if len(runs) == 1 else f"{path} [{run_id}]"
            sys.stdout.write(render_ledger(label, run_events, skipped))
            sys.stdout.write("\n")
            skipped = 0  # unparseable-line count reported once per file
            lanes.append((label, run_events))
    if points:
        sys.stdout.write(render_trajectory(points))
    if args.chrome:
        trace = chrome_trace(lanes)
        with open(args.chrome, "w") as f:
            json.dump(trace, f, indent=1)
            f.write("\n")
        sys.stdout.write(
            f"wrote {args.chrome} ({len(trace['traceEvents'])} events)\n"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
