#!/bin/bash
# Capture the full TPU evidence set in one sitting, for when the axon tunnel
# is responsive (it wedges for hours at a time; see bench.py's watchdog
# docstring). Each stage is independently timeboxed so one wedge cannot eat
# the session. Results land in $OUT (default /tmp/tpu_evidence).
#
#   bash tools/capture_tpu_evidence.sh
#
# Stages:
#   1. bench.py            -> bench.json        (the driver artifact's twin)
#   2. pallas_microbench   -> microbench.json   (Mosaic vs jnp kernel timing)
#   3. TPU-gated pytest    -> pytest_tpu.log    (Mosaic/jnp equivalence on HW)
#   4. profiled convergence-> profile/          (op-level trace of one churn)
set -u
OUT="${OUT:-/tmp/tpu_evidence}"
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run_stage() { # name timeout_s command...
  local name="$1" tmo="$2"; shift 2
  echo "=== $name (timeout ${tmo}s) ==="
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "rc=$rc"
  tail -5 "$OUT/$name.log"
}

# Keep (accelerator attempt deadline) + (CPU fallback, ~10 min at N=100K)
# safely inside the stage timeout, or a wedged-tunnel day kills the fallback
# before its JSON line: one 1500s attempt + fallback < 3300s.
# CPU_FALLBACK=1 is this script's EXPLICIT authorization (bench.py's new
# default is loud failure): a wedged-tunnel day still yields a labeled
# platform=cpu measurement instead of an error artifact.
run_stage bench 3300 env RAPID_TPU_BENCH_DEADLINE_S=1500 RAPID_TPU_BENCH_ATTEMPTS=1 \
  RAPID_TPU_BENCH_NO_SNAPSHOT=1 RAPID_TPU_BENCH_CPU_FALLBACK=1 \
  RAPID_TPU_BENCH_LEDGER="$OUT/bench_ledger.jsonl" \
  python -u bench.py
grep -h '"metric"' "$OUT/bench.log" | tail -1 > "$OUT/bench.json"
# Stamp provenance into a capture so bench.py's snapshot fallback (and any
# reader) can tell when/what a measurement was taken from. One definition —
# both bench.json producers (default-width and tuned runs) use it.
stamp_json() {
  python - "$1" <<'EOF'
import json, subprocess, sys, time
path = sys.argv[1]
try:
    data = json.loads(open(path).read().strip() or "null")
except json.JSONDecodeError:
    data = None
if isinstance(data, dict):
    data["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        data["git_rev"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True, text=True
        ).stdout.strip()
    except OSError:
        pass
    open(path, "w").write(json.dumps(data) + "\n")
EOF
}
stamp_json "$OUT/bench.json"

run_stage microbench 1200 python -u examples/pallas_microbench.py
grep -h '"platform"' "$OUT/microbench.log" | tail -1 > "$OUT/microbench.json"

run_stage pytest_tpu 1200 env RAPID_TPU_TEST_PLATFORM=tpu \
  python -m pytest tests/test_pallas_kernels.py -v

run_stage profile 1800 python -u examples/pallas_microbench.py \
  --n 100000 --profile "$OUT/profile"

# .jsonl: one JSON line per shape (the sibling .json artifacts are single
# objects; keep that contract distinct).
run_stage autotune 1500 python -u examples/delivery_autotune.py
grep -h '"best_width"' "$OUT/autotune.log" > "$OUT/autotune.jsonl"

# Re-run the bench with the autotuned tile widths; keep whichever run is
# better as the headline bench.json (full provenance either way — the JSON
# carries lanes_100k, and lanes_1m when the 1M point ran). The first,
# default-width run already secured a capture in case the window dies
# mid-sweep.
read -r LANES_100K LANES_1M <<< "$(python - "$OUT/autotune.jsonl" <<'EOF' || echo "128 128"
import json, sys
best = {}
try:
    for line in open(sys.argv[1]):
        d = json.loads(line)
        best[d["shape"][1]] = d.get("best_width")
except (OSError, json.JSONDecodeError, KeyError, IndexError):
    pass
print(best.get(100_000) or 128, best.get(1_000_000) or 128)
EOF
)"
echo "autotuned lanes: 100K=$LANES_100K 1M=$LANES_1M"
run_stage bench_tuned 3300 env RAPID_TPU_BENCH_DEADLINE_S=1500 \
  RAPID_TPU_BENCH_ATTEMPTS=1 RAPID_TPU_BENCH_NO_SNAPSHOT=1 \
  RAPID_TPU_BENCH_CPU_FALLBACK=1 \
  RAPID_TPU_BENCH_LANES="$LANES_100K" RAPID_TPU_BENCH_LANES_1M="$LANES_1M" \
  RAPID_TPU_BENCH_LEDGER="$OUT/bench_tuned_ledger.jsonl" \
  python -u bench.py
grep -h '"metric"' "$OUT/bench_tuned.log" | tail -1 > "$OUT/bench_tuned.json"
stamp_json "$OUT/bench_tuned.json"
python - "$OUT/bench.json" "$OUT/bench_tuned.json" <<'EOF'
import json, sys
def load(p):
    try:
        d = json.loads(open(p).read().strip() or "null")
        return d if isinstance(d, dict) and d.get("platform") == "tpu" else None
    except (OSError, json.JSONDecodeError):
        return None
base, tuned = load(sys.argv[1]), load(sys.argv[2])
if tuned and (not base or tuned["value"] < base["value"]):
    # Never lose session evidence to the swap: if the tuned run skipped the
    # 1M point (XL budget on a slow-tunnel day) but the base run caught it,
    # the base measurement rides along with its own width provenance.
    if base and "n1M_crash1pct_ms" in base and "n1M_crash1pct_ms" not in tuned:
        tuned["n1M_crash1pct_ms"] = base["n1M_crash1pct_ms"]
        tuned["lanes_1m"] = base.get("lanes_1m", 128)
        tuned["n1M_from"] = "default_width_run"
    open(sys.argv[1], "w").write(json.dumps(tuned) + "\n")
    print("bench.json <- tuned run (better or only TPU capture)")
EOF

run_stage bootstrap 1200 python -u examples/bootstrap_bench.py --n 100000 --seed-size 1000
grep -h '"scenario"' "$OUT/bootstrap.log" | tail -1 > "$OUT/bootstrap.json"

# N-scaling curve, LAST (each point is a full bench run; a dying window
# truncates the curve, never the headline artifacts above). 5% churn at
# every N, same scenario as the headline; one JSON line per point. TPU
# only (NO_FALLBACK): a wedge mid-sweep writes an explicit
# accelerator_unavailable hole instead of burning the window on CPU
# minutes. The 100K point is the bench_tuned run, not a re-measurement.
: > "$OUT/sweep.jsonl"
for N in 10000 50000 500000 1000000; do
  # Nearest autotuned shape: the sweep widths were measured at 100K and 1M.
  if [ "$N" -ge 500000 ]; then LANES="$LANES_1M"; else LANES="$LANES_100K"; fi
  run_stage "sweep_$N" 900 env RAPID_TPU_BENCH_N="$N" RAPID_TPU_BENCH_NO_XL=1 \
    RAPID_TPU_BENCH_DEADLINE_S=600 RAPID_TPU_BENCH_ATTEMPTS=1 \
    RAPID_TPU_BENCH_NO_SNAPSHOT=1 RAPID_TPU_BENCH_NO_FALLBACK=1 \
    RAPID_TPU_BENCH_LANES="$LANES" \
    python -u bench.py
  grep -h '"metric"' "$OUT/sweep_$N.log" | tail -1 >> "$OUT/sweep.jsonl"
done
grep -h '"metric"' "$OUT/bench_tuned.json" >> "$OUT/sweep.jsonl" || true

echo "=== captured ==="
ls -la "$OUT"
cat "$OUT/bench.json" "$OUT/microbench.json" 2>/dev/null
