"""Merge per-node flight recordings into one causally-ordered timeline.

Each node's flight recorder (rapid_tpu/utils/flight_recorder.py) holds that
node's view of a membership change; the cluster-wide story only exists once
the recordings are merged. This tool takes one telemetry-snapshot JSON per
node (what ``MembershipService.telemetry_snapshot`` returns and the
standalone agent's ``--metrics-dump`` writes — a bare
``FlightRecorder.snapshot()`` dict works too) and merges them into a single
timeline ordered by (timestamp, causal phase rank, node, per-node sequence).
The phase rank breaks timestamp ties the way the protocol actually flows —
alert before proposal before decision before delivery — which matters under
simulated clocks that tick coarsely, and under real clocks when one batch of
events lands within scheduler jitter.

Events that share a ``trace_id`` are one membership change seen from every
node: ``--trace`` filters to a single change, and the Chrome trace output
(``--chrome out.json``, the trace-event format Perfetto and chrome://tracing
read) lanes events by node so the cross-node cascade is visible at a glance.

A chaos-scenario artifact directory (what ``tools/chaosrun.py`` and
``RunResult.write_repro`` emit: ``nodes/*.json`` snapshots plus a
``faultlog.json``) can be passed directly: the per-node recordings are
merged as usual and the fault-injection events (partition start/heal,
crash, restart, clock faults) are woven into the timeline as a synthetic
``(chaos)`` lane, so a repro reads end-to-end — injection, detection,
agreement, delivery. A directory carrying a ``trace.json`` (the decoded
device round-trace ring that ``tenancy/chaos.write_fleet_repro`` freezes)
additionally gets a synthetic ``(engine)`` lane: every recorded engine
round, its conflicts and its decisions, merged into the same timeline —
the compiled engine's own flight recording next to the host's.

Usage:

    python tools/traceview.py node1.json node2.json node3.json
    python tools/traceview.py dumps/*.json --trace 0x1b3 --chrome view.json
    python tools/traceview.py repro-dir/ --chrome view.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.utils.flight_recorder import EventName  # noqa: E402

#: Rank for event names outside the registered vocabulary (a newer recording
#: read by an older traceview): sorts after every known phase at the same
#: timestamp instead of crashing the merge.
_UNKNOWN_RANK = max(n.phase_rank for n in EventName) + 1


def _phase_rank(name: str) -> int:
    try:
        return EventName(name).phase_rank
    except ValueError:
        return _UNKNOWN_RANK


class SnapshotLoadError(RuntimeError):
    """A snapshot file could not be read or is not telemetry-snapshot JSON.
    Carries the offending path in its message; the CLI turns it into a clean
    nonzero exit instead of a traceback."""


def load_snapshots(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Read telemetry-snapshot (or bare recorder-snapshot) JSON files. A file
    holding a list is a convenience for single-file dumps of many nodes.
    Raises :class:`SnapshotLoadError` on unreadable files, invalid JSON, or
    JSON that is not a snapshot object."""
    snapshots: List[Dict[str, Any]] = []
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except OSError as exc:
            raise SnapshotLoadError(f"{path}: cannot read snapshot: {exc}") from exc
        except json.JSONDecodeError as exc:
            raise SnapshotLoadError(f"{path}: invalid JSON: {exc}") from exc
        entries = data if isinstance(data, list) else [data]
        for entry in entries:
            if not isinstance(entry, dict):
                raise SnapshotLoadError(
                    f"{path}: not a telemetry snapshot (expected a JSON "
                    f"object, got {type(entry).__name__})"
                )
        snapshots.extend(entries)
    return snapshots


def _recorder_of(snapshot: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    if "events" in snapshot:  # bare FlightRecorder.snapshot()
        return snapshot
    return snapshot.get("recorder")


#: Synthetic node name for fault-injection annotations: sorts apart from
#: real endpoints and renders as its own lane in the Chrome trace.
FAULT_LANE = "(chaos)"


def fault_snapshot(faultlog_path) -> Optional[Dict[str, Any]]:
    """The fault-injection events of a scenario ``faultlog.json`` (the
    ``ScenarioRunner`` capture: one ``{t_ms, kind, slots, args...}`` entry
    per applied schedule event) as a bare recorder-style snapshot for the
    synthetic :data:`FAULT_LANE` node, so :func:`merge_events` weaves the
    injections into the cluster timeline like any other recording. A
    missing file returns None — plain telemetry dumps have no fault log."""
    path = Path(faultlog_path)
    if not path.exists():
        return None
    try:
        entries = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise SnapshotLoadError(f"{path}: cannot read fault log: {exc}") from exc
    if not isinstance(entries, list):
        raise SnapshotLoadError(f"{path}: fault log is not a JSON list")
    events = []
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise SnapshotLoadError(
                f"{path}: fault-log entry {i} is not a JSON object "
                f"(got {type(entry).__name__})"
            )
        fields: Dict[str, Any] = {}
        if entry.get("slots"):
            fields["slots"] = entry["slots"]
        fields.update(entry.get("args") or {})
        events.append({
            "seq": i,
            "t_ms": entry.get("t_ms", 0.0),
            "node": FAULT_LANE,
            "name": f"fault:{entry.get('kind', '?')}",
            "config_id": None,
            "trace_id": None,
            "fields": fields,
        })
    return {"node": FAULT_LANE, "events": events}


#: Synthetic node name for the device round-trace ring: the compiled
#: engine's lane in the merged timeline, next to hosts and ``(chaos)``.
ENGINE_LANE = "(engine)"


def engine_trace_snapshot(trace_path) -> Optional[Dict[str, Any]]:
    """The decoded device round-trace ring of a repro directory
    (``trace.json``, frozen by ``tenancy/chaos.write_fleet_repro``) as a
    recorder-style snapshot for the synthetic :data:`ENGINE_LANE` node —
    ``engine_telemetry.trace_recorder_snapshot`` turns each held round into
    registered ``engine_round`` / ``engine_conflict`` / ``engine_decision``
    events, so :func:`merge_events` weaves device rounds into the timeline
    like any other recording. A missing file returns None — pre-trace
    repro directories merge exactly as before."""
    path = Path(trace_path)
    if not path.exists():
        return None
    try:
        summary = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise SnapshotLoadError(
            f"{path}: cannot read trace ring: {exc}"
        ) from exc
    if not isinstance(summary, dict) or "records" not in summary:
        raise SnapshotLoadError(
            f"{path}: not a decoded trace ring (no records section)"
        )
    from rapid_tpu.utils.engine_telemetry import trace_recorder_snapshot

    return trace_recorder_snapshot(summary, node=ENGINE_LANE)


def expand_scenario_dir(path: str) -> Tuple[List[str], Optional[Path]]:
    """A scenario artifact directory expands to its per-node snapshots plus
    its fault log: ``nodes/*.json`` when the ``write_repro`` layout is
    present, else any ``*.json`` directly inside (minus the scenario
    metadata files, which are not snapshots)."""
    root = Path(path)
    nodes_dir = root / "nodes"
    if nodes_dir.is_dir():
        snapshots = sorted(str(p) for p in nodes_dir.glob("*.json"))
    else:
        skip = {"schedule.json", "result.json", "faultlog.json",
                "fleet.json", "trace.json"}
        snapshots = sorted(
            str(p) for p in root.glob("*.json") if p.name not in skip
        )
    faultlog = root / "faultlog.json"
    return snapshots, faultlog if faultlog.exists() else None


def scenario_snapshots(path) -> List[Dict[str, Any]]:
    """Everything mergeable inside one scenario artifact directory: the
    per-node snapshots plus the fault-injection lane. THE loader for repro
    directories — traceview's own CLI and tools/chaosrun.py both go through
    it, so the two can never render the same repro differently."""
    paths, faultlog = expand_scenario_dir(str(path))
    snapshots = load_snapshots(paths)
    if faultlog is not None:
        lane = fault_snapshot(faultlog)
        if lane is not None:
            snapshots.append(lane)
    engine_lane = engine_trace_snapshot(Path(path) / "trace.json")
    if engine_lane is not None:
        snapshots.append(engine_lane)
    return snapshots


def write_chrome(events: List[Dict[str, Any]], out: str) -> None:
    """Dump a merged timeline as Chrome trace-event JSON (shared by the two
    CLIs for the same never-diverge reason as :func:`scenario_snapshots`)."""
    with open(out, "w") as f:
        json.dump(chrome_trace(events), f, indent=1)
        f.write("\n")


def merge_events(
    snapshots: Iterable[Dict[str, Any]], trace_id: Optional[int] = None
) -> List[Dict[str, Any]]:
    """One causally-ordered timeline from many per-node recordings.

    Sort key: (t_ms, phase rank, node, per-node seq). Timestamps order
    events whose clocks are comparable (one simulated clock, or one host's
    loop clock); the phase rank arbitrates ties so the merged order reads
    as the protocol executes even when a whole view change lands on one
    simulated-clock tick. ``trace_id`` filters to one membership change.
    """
    merged: List[Dict[str, Any]] = []
    for snapshot in snapshots:
        recorder = _recorder_of(snapshot)
        if not recorder:
            continue
        # Hierarchical-membership snapshots carry the node's cohort index
        # (HierMembershipService.telemetry_snapshot): stamp it onto the
        # events so the rendered timeline lanes by cohort.
        cohort = snapshot.get("cohort")
        for event in recorder.get("events", ()):
            if trace_id is not None and event.get("trace_id") != trace_id:
                continue
            if cohort is not None and "cohort_lane" not in event:
                event = dict(event)
                event["cohort_lane"] = cohort
            merged.append(event)
    merged.sort(
        key=lambda e: (
            e.get("t_ms", 0.0),
            _phase_rank(e.get("name", "")),
            str(e.get("node", "")),
            e.get("seq", 0),
        )
    )
    return merged


def render_text(events: List[Dict[str, Any]]) -> str:
    """The human-facing timeline: one line per event, time-left-aligned to
    the first event so a convergence run reads as elapsed milliseconds."""
    if not events:
        return "(no events)\n"
    t0 = events[0].get("t_ms", 0.0)
    width = max(len(_node_label(e)) for e in events)
    lines = []
    for e in events:
        fields = " ".join(f"{k}={v}" for k, v in (e.get("fields") or {}).items())
        trace = e.get("trace_id")
        lines.append(
            f"{e.get('t_ms', 0.0) - t0:>10.3f}ms  {_node_label(e):<{width}}  "
            f"{e.get('name', '?'):<22}"
            f" cfg={e.get('config_id')}"
            + (f" trace={trace:#x}" if trace is not None else "")
            + (f"  {fields}" if fields else "")
        )
    return "\n".join(lines) + "\n"


def _node_label(event: Dict[str, Any]) -> str:
    """The lane label for one event: ``c<cohort>:<node>`` for hierarchical
    recordings (so a merged timeline reads cohort-by-cohort), the bare node
    otherwise."""
    node = str(event.get("node", ""))
    cohort = event.get("cohort_lane")
    return node if cohort is None else f"c{cohort}:{node}"


def chrome_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the format chrome://tracing and Perfetto
    load): every flight event becomes a thread-scoped instant event, laned
    by node (pid) with the trace id as the thread so concurrent membership
    changes render as separate rows under each node."""
    pids: Dict[str, int] = {}
    tids: Dict[Any, int] = {}
    named_lanes: set = set()  # (pid, tid) pairs with thread_name emitted
    trace_events: List[Dict[str, Any]] = []
    for e in events:
        node = _node_label(e) or "?"
        if node not in pids:
            pids[node] = len(pids) + 1
            trace_events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pids[node],
                    "tid": 0,
                    "args": {"name": node},
                }
            )
        lane = e.get("trace_id")
        if lane not in tids:
            tids[lane] = len(tids) + 1
        if (pids[node], tids[lane]) not in named_lanes:
            # thread_name metadata is scoped per (pid, tid): a trace shared
            # across nodes needs its lane named under EVERY node's pid.
            named_lanes.add((pids[node], tids[lane]))
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pids[node],
                    "tid": tids[lane],
                    "args": {
                        "name": "untraced" if lane is None else f"trace {lane:#x}"
                    },
                }
            )
        args = dict(e.get("fields") or {})
        args["config_id"] = e.get("config_id")
        if lane is not None:
            args["trace_id"] = f"{lane:#x}"
        trace_events.append(
            {
                "name": e.get("name", "?"),
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": e.get("t_ms", 0.0) * 1000.0,  # trace-event ts is µs
                "pid": pids[node],
                "tid": tids[lane],
                "args": args,
            }
        )
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def _parse_trace_id(value: str) -> int:
    return int(value, 0)  # accepts decimal and the 0x-prefixed form we print


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="merge per-node flight recordings into one timeline"
    )
    parser.add_argument(
        "snapshots", nargs="+",
        help="telemetry-snapshot JSON files, one per node (--metrics-dump "
             "output), and/or chaos-scenario artifact directories "
             "(chaosrun output: nodes/*.json + faultlog.json)",
    )
    parser.add_argument(
        "--trace", type=_parse_trace_id, default=None, metavar="ID",
        help="only events of this trace id (one membership change)",
    )
    parser.add_argument(
        "--chrome", metavar="OUT.json", default=None,
        help="also write Chrome trace-event JSON (open in Perfetto)",
    )
    args = parser.parse_args(argv)

    try:
        snapshots: List[Dict[str, Any]] = []
        for arg in args.snapshots:
            if Path(arg).is_dir():
                snapshots.extend(scenario_snapshots(arg))
            else:
                snapshots.extend(load_snapshots([arg]))
    except SnapshotLoadError as exc:
        print(f"traceview: {exc}", file=sys.stderr)
        return 2
    recorded = sum(
        len((_recorder_of(s) or {}).get("events", ())) for s in snapshots
    )
    if recorded == 0:
        # Distinct from an empty --trace filter result: the inputs carry no
        # recording at all (e.g. dumps taken with recorder_tail=0), so there
        # is no timeline to merge — say so, nonzero.
        print(
            f"traceview: no recorder events in {len(args.snapshots)} "
            "snapshot file(s) — dump with the full recorder tail "
            "(--metrics-dump writes it by default)",
            file=sys.stderr,
        )
        return 2
    events = merge_events(snapshots, trace_id=args.trace)
    sys.stdout.write(render_text(events))
    if args.chrome:
        write_chrome(events, args.chrome)
        sys.stdout.write(f"wrote {args.chrome} ({len(events)} events)\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
