"""Sharded-collective audit: compile the engine under a device mesh and
classify every cross-device collective in the resulting HLO.

Substantiates parallel/mesh.py's communication claims (VERDICT r2 missing #4)
with compiled evidence rather than docstring assertion:

  - the convergence hot loop's unconditional collectives are psum-class
    all-reduces of scalar/[c] operands only;
  - the per-edge [n]-sized gathers (observer aliveness + packed rx-block
    words, rapid_tpu/models/virtual_cluster.py::_edge_masks) sit OUTSIDE the
    while body — hoisted once per convergence;
  - anything [c,n]-sized or larger moves only inside lax.cond branches that
    execute on view changes (sort-free topology rebuild), classic-fallback attempts, or
    the implicit-invalidation pass.

Classification logic lives in rapid_tpu/parallel/audit.py (pinned by
tests/test_parallel.py); this tool builds the committed evidence table.

    python tools/collective_audit.py [--n 10240] [--devices 8] [--out FILE]

Writes a JSON table and prints a markdown summary (EVALUATION.md
§collectives is generated from this).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=10240)
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--cohorts", type=int, default=64)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    from rapid_tpu.utils.platform import force_platform

    force_platform("cpu", n_host_devices=args.devices)
    import jax

    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        run_to_decision_impl,
    )
    from rapid_tpu.parallel.audit import audit_collectives, collective_violations
    from rapid_tpu.parallel.mesh import (
        fault_shardings,
        make_mesh,
        make_sharded_step,
        shard_faults,
        shard_state,
        state_shardings,
    )

    n_slots = args.n
    n_members = n_slots - args.devices  # leave a few dead slots
    vc = VirtualCluster.create(
        n_members, n_slots=n_slots, k=10, h=9, l=4, fd_threshold=2,
        cohorts=args.cohorts, delivery_spread=2, seed=0,
    )
    vc.assign_cohorts_roundrobin()
    mesh = make_mesh(jax.devices()[: args.devices])
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)

    report = {"n_slots": n_slots, "cohorts": args.cohorts,
              "devices": args.devices, "programs": {}}

    # Program 1: the single-dispatch CONVERGENCE loop (the product path for
    # run_to_decision) — while_loop around the round body, edge gathers
    # hoisted into the prologue.
    cfg = vc.cfg
    conv = jax.jit(
        lambda s, f: run_to_decision_impl(cfg, s, f, 96),
        in_shardings=(state_shardings(mesh), fault_shardings(mesh)),
    )
    conv_txt = conv.lower(state, faults).compile().as_text()
    report["programs"]["convergence_loop"] = audit_collectives(
        conv_txt, n_slots, args.cohorts
    )

    # Program 2: one engine step (the per-round driver used by the sharded
    # dry run / host-driven stepping) — pays the prologue gathers per call.
    step = make_sharded_step(cfg, mesh)
    step_txt = step.lower(state, faults).compile().as_text()
    report["programs"]["engine_step"] = audit_collectives(
        step_txt, n_slots, args.cohorts
    )

    violations = collective_violations(report["programs"]["convergence_loop"])
    report["violations"] = violations
    report["ok"] = not any(violations.values())

    # Markdown summary.
    def summarize(rows):
        agg = {}
        for r in rows:
            key = (r["location"], r["kind"], r["source"])
            agg.setdefault(key, {"count": 0, "bytes": 0})
            agg[key]["count"] += 1
            agg[key]["bytes"] += r["bytes"]
        return agg

    print("\n| program | location | kind | source | count | payload bytes |")
    print("|---|---|---|---|---|---|")
    for prog, rows in report["programs"].items():
        for (loc, kind, src), v in sorted(summarize(rows).items()):
            print(f"| {prog} | {loc} | {kind} | {src} | {v['count']} | {v['bytes']} |")
    print(f"\nok={report['ok']} violations=" + json.dumps(
        {k: len(v) for k, v in violations.items()}))

    out = args.out or "evidence/round3/collective_audit.json"
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
