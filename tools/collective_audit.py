"""Sharded-collective audit: compile the engine under a device mesh and
classify every cross-device collective in the resulting HLO.

Substantiates parallel/mesh.py's communication claims (VERDICT r2 missing #4)
with compiled evidence rather than docstring assertion:

  - the convergence hot loop's unconditional collectives are psum-class
    all-reduces of scalar/[c] operands only;
  - the per-edge [n]-sized gathers (observer aliveness + packed rx-block
    words, rapid_tpu/models/virtual_cluster.py::_edge_masks) sit OUTSIDE the
    while body — hoisted once per convergence;
  - anything [c,n]-sized or larger moves only inside lax.cond branches that
    execute on view changes (sort-free topology rebuild), classic-fallback attempts, or
    the implicit-invalidation pass.

This CLI is the evidence-table front end of the ``device_program`` check
family (tools/analysis/device_program.py): classification lives in
``rapid_tpu/parallel/hlo_facts.py`` (re-exported by rapid_tpu/parallel/audit.py,
pinned by tests/test_parallel.py), fact extraction — including donation
outcomes and XLA memory analysis — in ``device_program.extract_facts``. The
difference from the committed gate: the gate compiles at fixed small audit
shapes and freezes the facts into ``hlo.lock.json``; this tool compiles at
evidence scale (10K+ slots) and writes the full table.

    python tools/collective_audit.py [--n 10240] [--devices 8] \
        [--cohort-devices 2] [--out FILE]

``--cohort-devices D`` audits the 2-D ``('cohort', 'nodes')`` mesh (D rows
by devices/D columns — the 1M+ headline configuration's layout) instead of
the default 1-D ``('nodes',)`` mesh.

Writes a JSON table and prints a markdown summary (EVALUATION.md
§collectives is generated from this).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=10240)
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--cohorts", type=int, default=64)
    parser.add_argument(
        "--cohort-devices", type=int, default=0, metavar="D",
        help="audit the 2-D ('cohort','nodes') mesh with D cohort rows "
             "(must divide --devices and --cohorts); 0 = the 1-D mesh",
    )
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.cohort_devices and (
        args.devices % args.cohort_devices or args.cohorts % args.cohort_devices
    ):
        parser.error("--cohort-devices must divide --devices and --cohorts")

    from rapid_tpu.utils.platform import force_platform

    force_platform("cpu", n_host_devices=args.devices)
    import jax

    from analysis.device_program import _compile_program, extract_facts
    from analysis.hlo_facts import collective_violations
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        run_to_decision_impl,
    )
    from rapid_tpu.parallel.mesh import (
        fault_shardings,
        make_mesh,
        make_sharded_step,
        shard_faults,
        shard_state,
        state_shardings,
    )

    n_slots = args.n
    n_members = n_slots - args.devices  # leave a few dead slots
    vc = VirtualCluster.create(
        n_members, n_slots=n_slots, k=10, h=9, l=4, fd_threshold=2,
        cohorts=args.cohorts, delivery_spread=2, seed=0,
    )
    vc.assign_cohorts_roundrobin()
    if args.cohort_devices:
        mesh = make_mesh(
            jax.devices()[: args.devices],
            shape=(args.cohort_devices, args.devices // args.cohort_devices),
        )
    else:
        mesh = make_mesh(jax.devices()[: args.devices])
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    n_leaves = len(jax.tree_util.tree_leaves(state))

    report = {"n_slots": n_slots, "cohorts": args.cohorts,
              "devices": args.devices,
              "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
              "programs": {}, "facts": {}}
    cfg = vc.cfg

    # Program 1: the single-dispatch CONVERGENCE loop (the product path for
    # run_to_decision) — while_loop around the round body, edge gathers
    # hoisted into the prologue. Donating, like the product entrypoint.
    conv = jax.jit(
        lambda state, faults: run_to_decision_impl(cfg, state, faults, 96),
        in_shardings=(state_shardings(mesh), fault_shardings(mesh)),
        donate_argnums=(0,),
    )
    # Program 2: one engine step (the per-round driver used by the sharded
    # dry run / host-driven stepping) — pays the prologue gathers per call.
    step = make_sharded_step(cfg, mesh)

    for name, jitted, spec_args in (
        ("convergence_loop", conv, (state, faults)),
        ("engine_step", step, (state, faults)),
    ):
        compiled, reasons = _compile_program({"jit": jitted, "args": spec_args})
        facts = extract_facts(
            compiled, n_leaves, n_slots, args.cohorts, donation_reasons=reasons
        )
        report["programs"][name] = facts.pop("rows")
        report["facts"][name] = facts

    violations = collective_violations(report["programs"]["convergence_loop"])
    report["violations"] = violations
    report["ok"] = not any(violations.values())

    # Markdown summary.
    def summarize(rows):
        agg = {}
        for r in rows:
            key = (r["location"], r["kind"], r["source"])
            agg.setdefault(key, {"count": 0, "bytes": 0})
            agg[key]["count"] += 1
            agg[key]["bytes"] += r["bytes"]
        return agg

    print("\n| program | location | kind | source | count | payload bytes |")
    print("|---|---|---|---|---|---|")
    for prog, rows in report["programs"].items():
        for (loc, kind, src), v in sorted(summarize(rows).items()):
            print(f"| {prog} | {loc} | {kind} | {src} | {v['count']} | {v['bytes']} |")
    for prog, facts in report["facts"].items():
        d = facts["donation"]
        m = facts["memory"] or {}
        print(
            f"\n{prog}: donation {d['aliased']}/{d['donated_leaves']} aliased"
            f" ({d['dropped']} dropped), temp {m.get('temp_bytes', '?')} B,"
            f" args {m.get('argument_bytes', '?')} B"
        )
    print(f"\nok={report['ok']} violations=" + json.dumps(
        {k: len(v) for k, v in violations.items()}))

    out = args.out or "evidence/round3/collective_audit.json"
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
