"""Live top-like terminal dashboard over per-node telemetry snapshots.

``tools/traceview.py`` answers "show me this one view change, end to end";
this tool answers the operator's other question — "how is the cluster doing
RIGHT NOW". It reads the same inputs (one telemetry-snapshot JSON per node,
what ``--metrics-dump`` writes continuously) and renders a refreshing
cluster view: per-node health states (utils/health.py), configuration
agreement, message rates, and the phase-decomposed convergence quantiles
(detection / agreement / delivery, utils/histogram.py) — both per node and
merged cluster-wide, which is exactly what the histogram's associative
``merge()`` exists for.

Usage:

    python tools/clustertop.py dumps/*.json              # refresh every 2 s
    python tools/clustertop.py dumps/*.json --interval 1
    python tools/clustertop.py dumps/*.json --once       # one frame, exit

Unreadable or torn files (a node mid-rewrite, a crashed agent) degrade to a
footnote, never a crash: a live dashboard that dies on one bad file is
useless during the incident it exists for.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.utils.health import aggregate_health, parse_health  # noqa: E402
from rapid_tpu.utils.histogram import LogHistogram  # noqa: E402

#: Display order of the convergence phases (the protocol's causal order).
PHASE_ORDER = ("detection", "agreement", "delivery")

_CLEAR = "\x1b[2J\x1b[H"  # ANSI: clear screen + home cursor


def load_snapshots_tolerant(
    paths: List[str],
) -> Tuple[List[Dict[str, Any]], List[str]]:
    """(snapshots, error strings). A file holding a list contributes every
    entry (single-file dumps of many nodes); malformed files become error
    lines instead of exceptions."""
    snapshots: List[Dict[str, Any]] = []
    errors: List[str] = []
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except OSError as exc:
            errors.append(f"{path}: unreadable ({exc})")
            continue
        except json.JSONDecodeError as exc:
            errors.append(f"{path}: invalid JSON ({exc})")
            continue
        entries = data if isinstance(data, list) else [data]
        for entry in entries:
            if isinstance(entry, dict) and "node" in entry:
                snapshots.append(entry)
            else:
                errors.append(f"{path}: not a telemetry snapshot entry")
    return snapshots, errors


def _phase_histograms(snapshot: Dict[str, Any]) -> Dict[str, LogHistogram]:
    """Per-phase histograms of one node, agreement paths folded into their
    phase (``agreement/fast`` + ``agreement/classic`` -> ``agreement``) —
    merge is associative, so folding here and folding across nodes commute."""
    family = (snapshot.get("metrics") or {}).get("view_change_phase_ms") or {}
    out: Dict[str, LogHistogram] = {}
    for key, summary in family.items():
        if not isinstance(summary, dict) or "count" not in summary:
            continue
        phase = key.split("/", 1)[0]
        hist = out.setdefault(phase, LogHistogram())
        hist.merge(LogHistogram.from_summary(summary))
    return out


def _convergence_histogram(snapshot: Dict[str, Any]) -> Optional[LogHistogram]:
    summary = (snapshot.get("metrics") or {}).get("view_change_convergence_ms")
    if isinstance(summary, dict) and summary.get("count"):
        return LogHistogram.from_summary(summary)
    return None


def _fmt_ms(value: Optional[float]) -> str:
    if value is None:
        return "-"
    if value >= 1000.0:
        return f"{value / 1000.0:.2f}s"
    return f"{value:.1f}"


def _fmt_rate(stats: Optional[Dict[str, Any]], key: str) -> str:
    if not stats or key not in stats:
        return "-"
    return f"{float(stats[key]):.1f}"


def _quantile_cell(hist: Optional[LogHistogram], q: float) -> str:
    if hist is None or hist.count == 0:
        return "-"
    return _fmt_ms(hist.quantile(q))


def _render_table(header: Tuple[str, ...],
                  rows: List[Tuple[str, ...]]) -> List[str]:
    """Fixed-width text table — shared by the node table and engine pane."""
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows else len(header[i])
        for i in range(len(header))
    ]
    return [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        for row in (header, *rows)
    ]


def _fmt_bytes(value: Any) -> str:
    if not isinstance(value, (int, float)):
        return "-"
    if value >= 1 << 30:
        return f"{value / (1 << 30):.2f}G"
    if value >= 1 << 20:
        return f"{value / (1 << 20):.1f}M"
    if value >= 1 << 10:
        return f"{value / (1 << 10):.1f}K"
    return str(int(value))


def _dispatch_histogram(snapshot: Dict[str, Any]) -> Optional[LogHistogram]:
    """All engine dispatch latencies of one snapshot, entrypoint phases
    merged (merge is associative — same folding rule as the phase SLOs)."""
    family = (snapshot.get("metrics") or {}).get("engine_dispatch_ms") or {}
    merged = LogHistogram()
    for summary in family.values():
        if isinstance(summary, dict) and "count" in summary:
            merged.merge(LogHistogram.from_summary(summary))
    return merged if merged.count else None


def render_engine_pane(snapshots: List[Dict[str, Any]]) -> List[str]:
    """The device-engine rows: one line per snapshot carrying an ``engine``
    section (VirtualCluster scrapes) — compile count, persistent-cache hit
    rate, dispatch p99, transfer bytes, device memory, and the device
    telemetry plane's activity columns (active-subject fraction, mean
    winning tally, fast-path share, conflict rate). Snapshots from
    pre-ledger code (no ``engine`` key, or partial sections) and
    pre-telemetry scrapes (no ``activity`` block, or ``telemetry=0``)
    contribute nothing / dashes, never a crash."""
    engines = [s for s in snapshots if isinstance(s.get("engine"), dict)]
    if not engines:
        return []
    header = (
        "ENGINE", "TENANTS", "COMPILES", "CACHEHIT", "DISP99", "DISPATCHES",
        "H2D", "D2H", "LIVEBUF", "DEVMEM",
        "ACTIVE", "TALLY", "FAST%", "CONFLICT",
    )
    rows: List[Tuple[str, ...]] = []
    for snapshot in sorted(engines, key=lambda s: str(s.get("node", ""))):
        engine = snapshot["engine"]
        compile_stats = engine.get("compile") or {}
        memory = engine.get("memory") or {}
        metrics = snapshot.get("metrics") or {}
        tenancy = engine.get("tenancy")
        activity = engine.get("activity")
        activity = activity if isinstance(activity, dict) else {}
        hits = compile_stats.get("persistent_cache_hits")
        misses = compile_stats.get("persistent_cache_misses")
        if isinstance(hits, int) and isinstance(misses, int) and hits + misses:
            cache = f"{100.0 * hits / (hits + misses):.0f}%"
        else:
            cache = "-"
        rows.append((
            str(snapshot.get("node", "?")),
            # Tenant-fleet snapshots carry their batch width; single-cluster
            # (and pre-fleet) snapshots dash.
            str(tenancy.get("tenants", "-")) if isinstance(tenancy, dict)
            else "-",
            str(compile_stats.get("compiles", "-")),
            cache,
            _quantile_cell(_dispatch_histogram(snapshot), 0.99),
            str(metrics.get("engine_dispatches", "-")),
            _fmt_bytes(metrics.get("engine_h2d_bytes")),
            _fmt_bytes(metrics.get("engine_d2h_bytes")),
            _fmt_bytes(memory.get("live_buffer_bytes")),
            _fmt_bytes(memory.get("device_bytes_in_use")),
            _fmt_ratio(activity.get("active_fraction")),
            _fmt_opt(activity.get("winning_tally_mean"), ".1f"),
            _fmt_ratio(activity.get("fast_path_share")),
            _fmt_ratio(activity.get("conflict_rate")),
        ))
    return ["", *_render_table(header, rows)]


def _fmt_ratio(value: Any) -> str:
    if not isinstance(value, (int, float)):
        return "-"
    return f"{100.0 * float(value):.1f}%"


def _fmt_opt(value: Any, spec: str = ".2f") -> str:
    if not isinstance(value, (int, float)):
        return "-"
    return format(float(value), spec)


def render_stream_pane(snapshots: List[Dict[str, Any]]) -> List[str]:
    """The streaming-serving rows: one line per snapshot whose ``engine``
    section carries a ``stream`` block (a rapid_tpu.serving.StreamDriver is
    attached) — waves in flight, pipeline progress, sustained rate, overlap
    efficiency, p99 alert->commit. Pre-stream snapshots (no ``stream`` key,
    or pre-drain None rates) contribute nothing / dashes, never a crash."""
    streams = [
        s for s in snapshots
        if isinstance(s.get("engine"), dict)
        and isinstance(s["engine"].get("stream"), dict)
    ]
    if not streams:
        return []
    header = (
        "STREAM", "INFLIGHT", "SUBMITTED", "COMPLETED", "RATE/S",
        "OVERLAP", "P99MS",
    )
    rows: List[Tuple[str, ...]] = []
    for snapshot in sorted(streams, key=lambda s: str(s.get("node", ""))):
        stream = snapshot["engine"]["stream"]
        rows.append((
            str(snapshot.get("node", "?")),
            _fmt_opt(stream.get("waves_in_flight"), ".0f"),
            _fmt_opt(stream.get("waves_submitted"), ".0f"),
            _fmt_opt(stream.get("waves_completed"), ".0f"),
            _fmt_opt(stream.get("view_changes_per_sec")),
            _fmt_ratio(stream.get("overlap_efficiency")),
            _fmt_opt(stream.get("p99_alert_to_commit_ms"), ".1f"),
        ))
    return ["", *_render_table(header, rows)]


def render_recovery_pane(snapshots: List[Dict[str, Any]]) -> List[str]:
    """The self-healing tier rows: one line per snapshot whose ``engine``
    section carries a ``recovery`` block (a rapid_tpu.serving.supervisor.
    Supervisor is attached) — checkpoint cadence/progress, retry/wedge/
    resume tallies, the quarantine census, and the last resume's MTTR.
    Pre-supervision snapshots (no ``recovery`` key, or None gauges)
    contribute nothing / dashes, never a crash."""
    supervised = [
        s for s in snapshots
        if isinstance(s.get("engine"), dict)
        and isinstance(s["engine"].get("recovery"), dict)
    ]
    if not supervised:
        return []
    header = (
        "RECOVERY", "WAVES", "CKPTS", "LASTCKPT", "RETRIES", "WEDGES",
        "RESUMES", "QUARANTINED", "MTTRMS",
    )
    rows: List[Tuple[str, ...]] = []
    for snapshot in sorted(supervised, key=lambda s: str(s.get("node", ""))):
        recovery = snapshot["engine"]["recovery"]
        rows.append((
            str(snapshot.get("node", "?")),
            _fmt_opt(recovery.get("waves_submitted"), ".0f"),
            _fmt_opt(recovery.get("checkpoints_written"), ".0f"),
            _fmt_opt(recovery.get("last_checkpoint_wave"), ".0f"),
            _fmt_opt(recovery.get("retries"), ".0f"),
            _fmt_opt(recovery.get("wedges"), ".0f"),
            _fmt_opt(recovery.get("resumes"), ".0f"),
            _fmt_opt(recovery.get("quarantined"), ".0f"),
            _fmt_opt(recovery.get("mttr_ms"), ".1f"),
        ))
    return ["", *_render_table(header, rows)]


#: Height-coded glyphs for the ROUNDS sparkline, lowest to highest.
_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[Any], width: int = 16) -> str:
    """The last ``width`` numeric values as a unicode sparkline, scaled to
    the window's max (floor 1 so an all-zero window renders flat, not
    blank). Non-numeric entries (torn snapshots) are dropped; an empty
    window dashes."""
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    if not vals:
        return "-"
    vals = vals[-width:]
    top = max(max(vals), 1.0)
    hi = len(_SPARK_CHARS) - 1
    return "".join(
        _SPARK_CHARS[min(int(v * hi / top + 0.5), hi)] for v in vals
    )


def _trace_rows(snapshot: Dict[str, Any]) -> List[Tuple[str, Dict[str, Any]]]:
    """(row label, decoded trace summary) pairs of one snapshot: the
    single-cluster ``engine.trace`` section under the node's own label,
    fleet ``engine.tenant_trace`` entries as ``node/t<i>`` lanes. Sections
    of the wrong shape (torn mid-rewrite) contribute nothing."""
    engine = snapshot.get("engine")
    if not isinstance(engine, dict):
        return []
    node = str(snapshot.get("node", "?"))
    out: List[Tuple[str, Dict[str, Any]]] = []
    trace = engine.get("trace")
    if isinstance(trace, dict):
        out.append((node, trace))
    tenant_trace = engine.get("tenant_trace")
    if isinstance(tenant_trace, list):
        out.extend(
            (f"{node}/t{i}", t)
            for i, t in enumerate(tenant_trace)
            if isinstance(t, dict)
        )
    return out


def render_rounds_pane(snapshots: List[Dict[str, Any]]) -> List[str]:
    """The device round-trace rows: one line per decoded ring (a
    ``VirtualCluster`` with ``trace=R``, or one per tenant of a traced
    fleet) — recorded/held round counts, wrap and decision tallies, the
    last recorded round's stamp and decision path, and a sparkline of the
    ``rounds_undecided`` trajectory across the held window (the climb to
    each decision, as the compiled engine recorded it). Pre-trace
    snapshots (no ``trace`` / ``tenant_trace`` section) and torn records
    contribute nothing / dashes, never a crash."""
    from rapid_tpu.utils.engine_telemetry import TRACE_PATH_NAMES

    pairs = [pair for s in snapshots for pair in _trace_rows(s)]
    if not pairs:
        return []
    header = (
        "ROUNDS", "RECORDED", "HELD", "WRAPS", "DECIDED", "CONFLICT",
        "LASTROUND", "LASTPATH", "UNDECIDED",
    )
    rows: List[Tuple[str, ...]] = []
    for label, trace in sorted(pairs, key=lambda p: p[0]):
        records = trace.get("records")
        records = records if isinstance(records, list) else []
        undecided = [
            r.get("undecided") for r in records if isinstance(r, dict)
        ]
        path = trace.get("last_path")
        rows.append((
            label,
            _fmt_opt(trace.get("rounds_recorded"), ".0f"),
            _fmt_opt(trace.get("rounds_held"), ".0f"),
            _fmt_opt(trace.get("wraps"), ".0f"),
            _fmt_opt(trace.get("decisions_held"), ".0f"),
            _fmt_opt(trace.get("conflicts_held"), ".0f"),
            _fmt_opt(trace.get("last_round"), ".0f"),
            TRACE_PATH_NAMES.get(path, "-") if isinstance(path, int) else "-",
            _sparkline(undecided),
        ))
    return ["", *_render_table(header, rows)]


def render_frame(
    snapshots: List[Dict[str, Any]], errors: Optional[List[str]] = None
) -> str:
    """One complete dashboard frame as a string (the testable core; the
    refresh loop just clears the screen and prints it)."""
    lines: List[str] = []
    agg = aggregate_health(s.get("health") for s in snapshots)
    configs = {s.get("configuration_id") for s in snapshots}
    counts = ", ".join(f"{n} {state}" for state, n in agg["counts"].items() if n)
    lines.append(
        f"rapid clustertop — {len(snapshots)} node(s)"
        f" | health: {str(agg['overall']).upper()}"
        + (f" ({counts})" if counts else "")
        + f" | configs: {len(configs) if snapshots else 0}"
        + (" (agreement)" if len(configs) == 1 and snapshots else "")
    )

    # Cluster-wide phase SLOs: per-node bounded histograms merge exactly.
    merged: Dict[str, LogHistogram] = {}
    merged_conv = LogHistogram()
    for snapshot in snapshots:
        for phase, hist in _phase_histograms(snapshot).items():
            merged.setdefault(phase, LogHistogram()).merge(hist)
        conv = _convergence_histogram(snapshot)
        if conv is not None:
            merged_conv.merge(conv)
    slo_cells = []
    for phase in PHASE_ORDER:
        hist = merged.get(phase)
        slo_cells.append(
            f"{phase} p50={_quantile_cell(hist, 0.5)} p99={_quantile_cell(hist, 0.99)}"
        )
    slo_cells.append(
        f"convergence p50={_quantile_cell(merged_conv, 0.5)}"
        f" p99={_quantile_cell(merged_conv, 0.99)}"
    )
    lines.append("cluster SLO (ms): " + " | ".join(slo_cells))
    lines.append("")

    header = (
        "NODE", "HEALTH", "CONFIG", "SIZE", "VIEWS",
        "TXKBPS", "RXKBPS", "DET99", "AGR99", "DLV99", "CONV99",
    )
    rows: List[Tuple[str, ...]] = []
    for snapshot in sorted(snapshots, key=lambda s: str(s.get("node", ""))):
        metrics = snapshot.get("metrics") or {}
        phases = _phase_histograms(snapshot)
        transport = snapshot.get("transport") or {}
        client = transport.get("client")
        rows.append((
            str(snapshot.get("node", "?")),
            parse_health(snapshot.get("health")).value,
            str(snapshot.get("configuration_id", "-")),
            str(snapshot.get("membership_size", "-")),
            str(metrics.get("view_changes", 0)),
            _fmt_rate(client, "kbps_tx"),
            _fmt_rate(client, "kbps_rx"),
            _quantile_cell(phases.get("detection"), 0.99),
            _quantile_cell(phases.get("agreement"), 0.99),
            _quantile_cell(phases.get("delivery"), 0.99),
            _quantile_cell(_convergence_histogram(snapshot), 0.99),
        ))
    lines.extend(_render_table(header, rows))
    lines.extend(render_engine_pane(snapshots))
    lines.extend(render_rounds_pane(snapshots))
    lines.extend(render_stream_pane(snapshots))
    lines.extend(render_recovery_pane(snapshots))
    for error in errors or ():
        lines.append(f"! {error}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="live cluster health/SLO dashboard over telemetry snapshots"
    )
    parser.add_argument(
        "snapshots", nargs="+",
        help="telemetry-snapshot JSON files, one per node (--metrics-dump output)",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS",
        help="refresh interval (re-reads the files each frame)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render a single frame and exit (scripting/testing)",
    )
    args = parser.parse_args(argv)

    while True:
        snapshots, errors = load_snapshots_tolerant(args.snapshots)
        frame = render_frame(snapshots, errors)
        if args.once:
            sys.stdout.write(frame)
            # Nothing renderable at all is an error exit like traceview's.
            return 0 if snapshots else 2
        sys.stdout.write(_CLEAR + frame)
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
