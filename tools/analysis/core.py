"""Driver, Finding model, and CLI for the resolution-tier static analysis.

The per-check modules (names, signatures, clocks, deadcode, concurrency,
trace_safety) each export a ``check_*`` function over one parsed file; this
module owns everything shared: the ``Finding`` record, the root list, file
iteration (with the fixture-corpus exclusion), the ``run()`` driver that
parses each file once and fans it out to every check, and the CLI
(``--json``/``--select``/``--ignore``).

``REPO`` is read through this module at call time (``core.REPO``), never
imported by value, so tests can retarget the whole analysis at a temporary
tree with one monkeypatch.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

REPO = Path(__file__).resolve().parent.parent.parent

DEFAULT_ROOTS = (
    "rapid_tpu", "tests", "examples", "tools", "bench.py", "__graft_entry__.py"
)

#: Subtrees holding fixture DATA, not code under analysis: the seeded lint
#: corpus (tests/data/lint_corpus/) exists to be defective, so sweeping it
#: into the gate would fail the build on purpose-built defects. Explicit
#: file roots bypass this (naming a corpus file on the CLI analyzes it).
EXCLUDED_SUBTREES = ("tests/data/",)

#: Mutating methods of the stdlib containers shared state lives in — the
#: single source of truth for both the concurrency analyzer (guarded-field
#: mutation sites) and the trace-safety analyzer (closed-over container
#: mutation inside jit). One list so the two can never drift apart.
MUTATING_CONTAINER_METHODS = frozenset({
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "move_to_end", "appendleft",
    "popleft", "sort", "reverse",
})

#: Every check name any analyzer can emit — the vocabulary ``--select`` /
#: ``--ignore`` validate against (a typo'd filter must error, not silently
#: select nothing and report a green build).
ALL_CHECK_NAMES = frozenset({
    "syntax-error",
    "star-import",
    "undefined-name",
    "call-signature",
    "missing-attribute",
    "import-error",
    "clock-injection",
    "dead-definition",
    "guarded-by-annotation",
    "unguarded-mutation",
    "interleaving-hazard",
    "lock-reentrancy",
    "jit-side-effect",
    "jit-traced-branch",
    # wire_schema family
    "missing-tag",
    "missing-encode-arm",
    "missing-decode-arm",
    "tag-reuse",
    "dead-arm",
    "field-number-drift",
    "wire-lock-drift",
    # dispatch family
    "unreachable-dispatch-arm",
    "shadowed-arm",
    "dispatch-return",
    # taskflow family
    "leaked-task",
    "swallowed-exception",
    "cancellation-swallow",
    "unawaited-coroutine",
    # determinism family
    "unseeded-random",
    # ledger family
    "ledger-event-name",
    "ledger-stage-name",
    # device_program family (compiled-HLO budgets vs hlo.lock.json)
    "hlo-collective-budget",
    "hlo-transfer-budget",
    "hlo-donation-dropped",
    "hlo-memory-budget",
    "hlo-unknown-dtype",
    "hlo-lock-drift",
    "hlo-quiescent-activity",
    # telemetry family
    "telemetry-lane-drift",
    "telemetry-unmarked-fetch",
    # sharding family
    "missing-partition-spec",
    "host-sync-in-hot-path",
    "host-sync-in-stream",
    "donation-mismatch",
    "retrace-hazard",
    "dtype-widening",
    # chaosvocab family
    "chaos-unknown-kind",
    "chaos-family-drift",
    # cost_model family (fitted scaling classes vs cost.lock.json)
    "cost-unexplained",
    "cost-scaling-regression",
    "cost-superlinear",
    "cost-quiescent",
    "cost-lock-drift",
    # dataflow family (jaxpr lane provenance vs dataflow.lock.json)
    "dataflow-observer-effect",
    "dataflow-cross-tenant",
    "dataflow-dense-op",
    "dataflow-dead-lane",
    "dataflow-lock-drift",
})

#: The check families, in documentation order — one (name, description)
#: per analyzer module, listed by ``staticcheck --families``.
FAMILIES = (
    ("names", "undefined names and star imports (symtable scope resolution)"),
    ("signatures", "call-site conformance against the real runtime callees"),
    ("clocks", "clock-injection discipline: no wall-clock reads in "
               "protocol/monitoring/serving"),
    ("deadcode", "tree-wide liveness of module-level definitions"),
    ("concurrency", "asyncio guarded-by discipline, interleaving hazards, "
                    "lock re-entrancy"),
    ("trace_safety", "JAX jit purity and traced-branch staticness over ops/"),
    ("wire_schema", "wire mirrors (types/codec/proto) cross-checked and "
                    "frozen in wire.lock.json"),
    ("dispatch", "RapidRequest dispatch exhaustiveness, shadowed arms, "
                 "response return types"),
    ("taskflow", "async failure paths: leaked tasks, swallowed exceptions, "
                 "cancellation, unawaited coroutines"),
    ("determinism", "no unseeded randomness in the library: simulated runs "
                    "are pure functions of their seed"),
    ("ledger", "run-ledger vocabulary discipline: emit() events from "
               "LedgerEvent, stage() names from STAGE_NAMES"),
    ("device_program", "compiled-HLO budgets for the registered engine "
                       "entrypoints (collectives, transfers, donation, "
                       "memory) frozen in hlo.lock.json"),
    ("telemetry", "device telemetry plane discipline: the TelemetryLanes "
                  "field set mirrored into the analyzer, and every host "
                  "fetch of the lanes annotated as a declared sync "
                  "boundary (# telemetry-fetch-ok:)"),
    ("sharding", "engine sharding discipline: partition-spec coverage, "
                 "host syncs in the hot path and the streaming pipeline, "
                 "donation/static-argnames at jit seams, dtype-widening "
                 "on policy-narrowed lanes (ops/models/parallel/serving)"),
    ("chaosvocab", "chaos vocabulary discipline: FaultEvent kinds, scenario "
                   "FAMILIES, fleet mix tables, and the chaosrun CLI cannot "
                   "drift from the registered registries"),
    ("cost_model", "scaling-law cost model: every registered entrypoint's "
                   "compiled facts fitted across N/K/tenant geometry "
                   "ladders to O(1)/O(log N)/O(N)/O(N*K)/O(N^2) classes "
                   "and frozen in cost.lock.json (nothing in the round "
                   "body may exceed O(N*K))"),
    ("dataflow", "jaxpr dataflow provenance: per-lane taint over every "
                 "registered entrypoint's closed jaxpr, proving observer "
                 "silence (telemetry/trace lanes never influence engine "
                 "lanes) and fleet tenant isolation, plus the "
                 "sparse-opportunity map of mask-gated dense round-body "
                 "ops priced against the quiescent payload bytes — all "
                 "frozen in dataflow.lock.json"),
)


def union_member_names(value: "ast.AST") -> "Optional[List[str]]":
    """The member names of a ``Union[A, B, ...]`` annotation/value node, or
    None if the node is not a plain-Name Union subscript. Shared by the
    wire_schema and dispatch families so the two can never disagree about
    what counts as a union member (e.g. if types.py ever moves to PEP 604
    ``A | B`` spellings, both learn it in one place)."""
    if not (
        isinstance(value, ast.Subscript)
        and isinstance(value.value, ast.Name)
        and value.value.id == "Union"
    ):
        return None
    elts = value.slice.elts if isinstance(value.slice, ast.Tuple) else [value.slice]
    members = [e.id for e in elts if isinstance(e, ast.Name)]
    return members or None


@dataclass(frozen=True)
class Finding:
    path: str
    lineno: int
    check: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.lineno}: [{self.check}] {self.message}"

    def to_json(self) -> str:
        return json.dumps(
            {"path": self.path, "lineno": self.lineno, "check": self.check,
             "message": self.message}
        )


def rel(path: Path) -> str:
    try:
        return str(path.resolve().relative_to(REPO))
    except ValueError:
        return str(path)


def iter_files(roots: Sequence[str] = DEFAULT_ROOTS) -> Iterable[Path]:
    for root in roots:
        path = (REPO / root) if not Path(root).is_absolute() else Path(root)
        if path.is_file():
            yield path  # explicit file roots are never excluded
        elif path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                posix = rel(sub).replace("\\", "/")
                if any(posix.startswith(ex) for ex in EXCLUDED_SUBTREES):
                    continue
                yield sub
        else:
            # A typo'd or since-renamed root must fail the gate, not
            # silently exempt that tree from analysis.
            raise FileNotFoundError(f"staticcheck root does not exist: {path}")


def run(roots: Sequence[str] = DEFAULT_ROOTS) -> List[Finding]:
    # The per-file check imports live here (not module top level) so the
    # CLI shim can import this module before sys.path is fully arranged.
    from . import (
        chaosvocab, clocks, concurrency, cost_model, dataflow, deadcode,
        determinism, device_program, dispatch, ledger, names, sharding,
        signatures, taskflow, telemetry, trace_safety, wire_schema,
    )

    per_file_checks = [
        names.check_undefined_names,
        signatures.check_call_signatures,
        clocks.check_clock_injection,
        concurrency.check_concurrency,
        trace_safety.check_trace_safety,
        dispatch.check_dispatch,
        taskflow.check_taskflow,
        determinism.check_determinism,
        ledger.check_ledger,
        telemetry.check_telemetry,
        sharding.check_sharding,
        chaosvocab.check_chaosvocab,
    ]
    full_tree = tuple(roots) == DEFAULT_ROOTS
    if not full_tree:
        # Narrowed invocations still get the intra-file wire checks (tag
        # reuse, dead arms, proto number reuse — presence-gated, so real
        # mirror files analyzed alone are silent). Full sweeps instead run
        # the merged three-file check below, which subsumes these; running
        # both would double-report any intra-file defect.
        per_file_checks.append(wire_schema.check_wire_schema)
    # Mirror pytest's rootdir behavior: test modules import suite-local
    # helpers both as `tests.helpers` and bare `helpers`. Insert at the
    # FRONT: `tools`/`tests` are common top-level names, and a foreign
    # package earlier on sys.path would shadow this repo's namespace
    # packages and produce spurious import-error findings.
    for entry in (str(REPO), str(REPO / "tests")):
        if entry in sys.path:
            sys.path.remove(entry)
        sys.path.insert(0, entry)
    findings: List[Finding] = []
    trees: List[Tuple[ast.AST, str]] = []  # one parse per file, shared
    for path in iter_files(roots):
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as exc:
            # One broken file must not abort the whole gate: report it as a
            # finding and keep analyzing the rest of the tree.
            findings.append(
                Finding(rel(path), exc.lineno or 1, "syntax-error",
                        f"cannot parse: {exc.msg}")
            )
            continue
        trees.append((tree, rel(path)))
        for check in per_file_checks:
            findings.extend(check(path, src, tree))
    if full_tree:
        # Liveness is only meaningful over the FULL tree: with narrowed CLI
        # roots, code consumed from outside the subset would be reported as
        # dead — so the check runs only on complete invocations. The wire
        # lockfile gate is likewise whole-surface: it merges the three
        # mirror files, which a narrowed root set may not all contain.
        findings.extend(deadcode.check_dead_definitions(trees))
        findings.extend(wire_schema.check_wire_lock(trees))
        # The compiled-program and merged partition-spec gates are likewise
        # whole-surface: both presence-gate on this repo's real engine
        # files, so retargeted test trees skip them (and never pay the
        # device_program family's session-cached compiles).
        findings.extend(sharding.check_partition_specs(trees))
        findings.extend(telemetry.check_lane_mirror(trees))
        findings.extend(device_program.check_hlo_lock(trees))
        # The cost-model ladder runs right after the HLO gate so its base
        # point rides the collect_facts session cache the gate just paid
        # for; it presence-gates on the same engine sources.
        findings.extend(cost_model.check_cost_lock(trees))
        # The dataflow provenance gate traces (no compile) the same
        # registry and prices its opportunity map off the facts the two
        # gates above already cached; same presence gate, same session.
        findings.extend(dataflow.check_dataflow_lock(trees))
    return findings


def _check_name_set(parser: argparse.ArgumentParser, spec: str, flag: str) -> set:
    names = {n.strip() for n in spec.split(",") if n.strip()}
    unknown = names - ALL_CHECK_NAMES
    if unknown:
        parser.error(
            f"{flag}: unknown check name(s) {sorted(unknown)}; "
            f"valid: {', '.join(sorted(ALL_CHECK_NAMES))}"
        )
    return names


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="staticcheck",
        description="Resolution-tier static analysis (see tools/analysis/).",
    )
    parser.add_argument("roots", nargs="*", help="files/dirs (default: whole tree)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="one JSON object per finding per line")
    parser.add_argument("--select", default=None, metavar="CHECKS",
                        help="comma-separated check names to keep")
    parser.add_argument("--ignore", default=None, metavar="CHECKS",
                        help="comma-separated check names to drop")
    parser.add_argument("--families", action="store_true",
                        help="list the registered check families and exit")
    parser.add_argument("--update-wire-lock", action="store_true",
                        dest="update_wire_lock",
                        help="regenerate tools/analysis/wire.lock.json from "
                             "the live schema mirrors (refuses while the "
                             "mirrors disagree with each other)")
    parser.add_argument("--update-hlo-lock", action="store_true",
                        dest="update_hlo_lock",
                        help="recompile the registered engine entrypoints "
                             "and regenerate tools/analysis/hlo.lock.json "
                             "(refuses while an unknown dtype or an "
                             "unwaived dropped donation is present)")
    parser.add_argument("--update-cost-lock", action="store_true",
                        dest="update_cost_lock",
                        help="refit the geometry ladders and regenerate "
                             "tools/analysis/cost.lock.json (refuses while "
                             "any fit is unexplained, any fact exceeds its "
                             "ceiling, or the hlo.lock differentials "
                             "disagree)")
    parser.add_argument("--update-dataflow-lock", action="store_true",
                        dest="update_dataflow_lock",
                        help="retrace the registered entrypoints and "
                             "regenerate tools/analysis/dataflow.lock.json "
                             "(refuses while any provenance proof fails: "
                             "an observer leak, a cross-tenant edge, a "
                             "dead lane, or an opportunity map under the "
                             "90% coverage floor)")
    args = parser.parse_args(argv)
    if args.families:
        for name, description in FAMILIES:
            print(f"{name:<14} {description}")
        return 0
    if args.update_wire_lock:
        from . import wire_schema

        findings, lock_path = wire_schema.update_wire_lock()
        if findings:
            for f in findings:
                print(f)
            print("staticcheck: refusing to lock an inconsistent wire "
                  "surface — fix the mirror disagreements above first")
            return 1
        print(f"wrote {lock_path}")
        return 0
    if args.update_hlo_lock:
        from . import device_program

        findings, lock_path = device_program.update_hlo_lock()
        if findings:
            for f in findings:
                print(f)
            print("staticcheck: refusing to lock a compiled-program surface "
                  "the gate would immediately fail — fix the findings above "
                  "first")
            return 1
        print(f"wrote {lock_path}")
        return 0
    if args.update_cost_lock:
        from . import cost_model

        findings, lock_path = cost_model.update_cost_lock()
        if findings:
            for f in findings:
                print(f)
            print("staticcheck: refusing to lock a scaling surface the gate "
                  "would immediately fail — fix the findings above first")
            return 1
        print(f"wrote {lock_path}")
        return 0
    if args.update_dataflow_lock:
        from . import dataflow as dataflow_mod

        findings, lock_path = dataflow_mod.update_dataflow_lock()
        if findings:
            for f in findings:
                print(f)
            print("staticcheck: refusing to lock a provenance surface the "
                  "gate would immediately fail — fix the findings above "
                  "first")
            return 1
        print(f"wrote {lock_path}")
        return 0
    findings = run(args.roots or DEFAULT_ROOTS)
    if args.select:
        keep = _check_name_set(parser, args.select, "--select")
        findings = [f for f in findings if f.check in keep]
    if args.ignore:
        drop = _check_name_set(parser, args.ignore, "--ignore")
        findings = [f for f in findings if f.check not in drop]
    if args.as_json:
        for f in findings:
            print(f.to_json())
    else:
        for f in findings:
            print(f)
        print(f"staticcheck: {len(findings)} finding(s)")
    return 1 if findings else 0
