"""Check family 7: wire-schema conformance across the four hand-kept mirrors.

The interop guarantee rests on byte-exact wire compatibility with the
reference IDL (``rapid/src/main/proto/rapid.proto``) — yet the message
schema lives in four hand-maintained mirrors: the ``RapidRequest`` /
``RapidResponse`` unions and their frozen dataclasses (``rapid_tpu/
types.py``), the tag tables plus the ``isinstance`` encode arms and
``tag ==`` decode arms (``rapid_tpu/messaging/codec.py``), the
``_field(name, number, ...)`` descriptors (``rapid_tpu/interop/
proto_schema.py``), and the service dispatch chain (checked by the
``dispatch`` family). This module cross-checks the first three:

- every union member has exactly one tag, one encode arm, and one decode
  arm decoding that tag back to the same type (``missing-tag``,
  ``missing-encode-arm``, ``missing-decode-arm``);
- no tag value is used twice (``tag-reuse``), and no arm or tag exists
  for a type outside the union (``dead-arm``);
- every union member with a protobuf mirror covers its dataclass fields
  with proto fields, no field number is reused inside a message, and the
  proto envelope's field numbers agree with the native tags
  (``field-number-drift``).

The whole surface (tags, field numbers, dataclass field order) is frozen
into the committed lockfile ``tools/analysis/wire.lock.json``. Any drift
fails the gate with a buf-style breaking-change message
(``wire-lock-drift``) until the developer regenerates via ``python
tools/staticcheck.py --update-wire-lock`` — a wire-format change is an
explicit, reviewed act, never a silent side effect of a refactor.

``check_wire_schema`` runs the cross-check over a single module (the lint
corpus keeps miniature mirrors in one file); sections only apply when the
module defines the artifacts they need, so a file holding only a tag
table is checked for tag discipline and nothing else.
``check_wire_lock`` is the tree-mode entry the driver calls on full
sweeps: it merges the three real mirror files and adds the lock
comparison.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import core
from .core import Finding

#: The real mirror files merged on full-tree sweeps (posix-relative).
WIRE_FILES = (
    "rapid_tpu/types.py",
    "rapid_tpu/messaging/codec.py",
    "rapid_tpu/interop/proto_schema.py",
)

#: The committed freeze of the wire surface, repo-relative.
LOCK_REL = "tools/analysis/wire.lock.json"

#: Union members with no protobuf mirror by design: the gossip envelope is
#: a native-transport extension the reference never ships (types.py), so
#: the interop surface deliberately excludes it.
NATIVE_ONLY_MESSAGES = frozenset({"GossipMessage"})

#: Dataclass fields that ride only the native codec (optional trailing
#: trace context; on the interop path it travels as gRPC metadata instead).
NATIVE_ONLY_FIELDS = frozenset({"trace_id"})

#: snake_case -> camelCase exceptions where the reference IDL diverges from
#: mechanical conversion (rapid.proto uses the singular ``ringNumber`` for
#: the repeated field).
_PROTO_NAME_ALIASES = {"ring_numbers": "ringNumber"}

_SIDES = ("request", "response")

_UNION_NAMES = {"RapidRequest": "request", "RapidResponse": "response"}
_TAG_TABLE_NAMES = {"_REQUEST_TAGS": "request", "_RESPONSE_TAGS": "response"}
#: Encode functions per side, most-specific first (the public
#: ``encode_request`` is a caching wrapper with no arms of its own, so the
#: impl wins whenever both exist).
_ENCODE_FN_NAMES = {
    "request": ("_encode_request_impl", "encode_request"),
    "response": ("_encode_response_impl", "encode_response"),
}
_DECODE_FN_NAMES = {"request": "decode_request", "response": "decode_response"}

_REGEN_HINT = (
    "if this wire-format change is intentional, regenerate via "
    "`python tools/staticcheck.py --update-wire-lock` and review the diff"
)


class _Loc:
    __slots__ = ("path", "lineno")

    def __init__(self, path: str, lineno: int) -> None:
        self.path = path
        self.lineno = lineno


class WireSurface:
    """Everything the mirrors say about the wire format, with source
    locations so findings point at the drifted artifact."""

    def __init__(self) -> None:
        self.unions: Dict[str, Optional[List[str]]] = {s: None for s in _SIDES}
        self.union_locs: Dict[str, _Loc] = {}
        self.dataclass_fields: Dict[str, List[str]] = {}
        self.class_locs: Dict[str, _Loc] = {}
        # side -> ordered (name, tag, loc) entries, duplicates preserved
        self.tags: Dict[str, Optional[List[Tuple[str, int, _Loc]]]] = {
            s: None for s in _SIDES
        }
        self.tag_table_locs: Dict[str, _Loc] = {}
        self.encode_arms: Dict[str, Optional[Dict[str, _Loc]]] = {
            s: None for s in _SIDES
        }
        self.encode_fn_locs: Dict[str, _Loc] = {}
        # side -> ordered (tag, constructed type name, loc)
        self.decode_arms: Dict[str, Optional[List[Tuple[int, str, _Loc]]]] = {
            s: None for s in _SIDES
        }
        self.decode_fn_locs: Dict[str, _Loc] = {}
        # proto message -> ordered (field name, number, loc)
        self.proto: Dict[str, List[Tuple[str, int, _Loc]]] = {}
        self.proto_locs: Dict[str, _Loc] = {}
        # Types whose decode arm constructs with zero arguments — proof of
        # fieldlessness local to the codec, for when the dataclasses are in
        # another file (the per-file check on codec.py alone).
        self.fieldless_decoded: set = set()

    def tag_map(self, side: str) -> Dict[str, int]:
        return {name: tag for name, tag, _ in (self.tags[side] or [])}

    def loc_of_tag(self, side: str, name: str) -> Optional[_Loc]:
        for entry_name, _, loc in self.tags[side] or []:
            if entry_name == name:
                return loc
        return None


def to_proto_field_name(field: str) -> str:
    """The proto spelling of a native dataclass field (camelCase with the
    reference's naming quirks)."""
    if field in _PROTO_NAME_ALIASES:
        return _PROTO_NAME_ALIASES[field]
    head, *rest = field.split("_")
    return head + "".join(part.title() for part in rest)


def _envelope_field_name(member: str) -> str:
    return member[0].lower() + member[1:]


# -- extraction -------------------------------------------------------------


def _is_dataclass_decorated(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "dataclass":
            return True
        if isinstance(target, ast.Attribute) and target.attr == "dataclass":
            return True
    return False


def _extract_tag_table(value: ast.AST, rel: str) -> Optional[List[Tuple[str, int, _Loc]]]:
    if not isinstance(value, ast.Dict):
        return None
    entries = []
    for key, val in zip(value.keys, value.values):
        if (
            isinstance(key, ast.Name)
            and isinstance(val, ast.Constant)
            and isinstance(val.value, int)
        ):
            entries.append((key.id, val.value, _Loc(rel, key.lineno)))
    return entries


def _encode_arms(fn: ast.AST, rel: str) -> Dict[str, _Loc]:
    args = fn.args.args
    if not args:
        return {}
    param = args[0].arg
    arms: Dict[str, _Loc] = {}
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.If)
            and isinstance(node.test, ast.Call)
            and isinstance(node.test.func, ast.Name)
            and node.test.func.id == "isinstance"
            and len(node.test.args) == 2
            and isinstance(node.test.args[0], ast.Name)
            and node.test.args[0].id == param
        ):
            continue
        target = node.test.args[1]
        names = (
            [e.id for e in target.elts if isinstance(e, ast.Name)]
            if isinstance(target, ast.Tuple)
            else [target.id] if isinstance(target, ast.Name) else []
        )
        for name in names:
            arms.setdefault(name, _Loc(rel, node.lineno))
    return arms


def _constructed_type(branch_body: Sequence[ast.stmt]) -> Optional[Tuple[str, int, bool]]:
    """The message type a decode branch builds — the Call bound to ``out``
    (the codec idiom) or returned directly — plus whether the constructor
    takes zero arguments (a fieldless message)."""
    for stmt in branch_body:
        for node in ast.walk(stmt):
            call = None
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "out" for t in node.targets
            ):
                call = node.value
            elif (
                isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.target.id == "out"
            ):
                call = node.value
            elif isinstance(node, ast.Return):
                call = node.value
            if isinstance(call, ast.Call) and isinstance(call.func, ast.Name):
                fieldless = not call.args and not call.keywords
                return call.func.id, node.lineno, fieldless
    return None


def _decode_arms(
    fn: ast.AST, rel: str, surface: WireSurface
) -> List[Tuple[int, str, _Loc]]:
    arms = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.If)
            and isinstance(node.test, ast.Compare)
            and isinstance(node.test.left, ast.Name)
            and len(node.test.ops) == 1
            and isinstance(node.test.ops[0], ast.Eq)
            and len(node.test.comparators) == 1
            and isinstance(node.test.comparators[0], ast.Constant)
            and isinstance(node.test.comparators[0].value, int)
        ):
            continue
        built = _constructed_type(node.body)
        if built is not None:
            arms.append(
                (node.test.comparators[0].value, built[0], _Loc(rel, node.lineno))
            )
            if built[2]:
                surface.fieldless_decoded.add(built[0])
    return arms


def _extract_proto(tree: ast.AST, rel: str, surface: WireSurface) -> None:
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_msg"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        fields = []
        for arg in node.args[1:]:
            if (
                isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "_field"
                and len(arg.args) >= 2
                and isinstance(arg.args[0], ast.Constant)
                and isinstance(arg.args[1], ast.Constant)
                and isinstance(arg.args[1].value, int)
            ):
                fields.append((arg.args[0].value, arg.args[1].value, _Loc(rel, arg.lineno)))
        surface.proto[name] = fields
        surface.proto_locs[name] = _Loc(rel, node.lineno)


def extract_surface(trees: Sequence[Tuple[ast.AST, str]]) -> WireSurface:
    """Pull the wire surface out of (tree, relpath) pairs — the three real
    mirror files on tree sweeps, or one corpus module holding miniatures."""
    surface = WireSurface()
    # side -> candidate fn name -> (fn node, relpath)
    encode_fns: Dict[str, Dict[str, Tuple[ast.AST, str]]] = {s: {} for s in _SIDES}
    for tree, rel in trees:
        _extract_proto(tree, rel, surface)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and _is_dataclass_decorated(node):
                fields = [
                    stmt.target.id
                    for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                ]
                surface.dataclass_fields[node.name] = fields
                surface.class_locs[node.name] = _Loc(rel, node.lineno)
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ) or (
                isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.value is not None
            ):
                target = (
                    node.target.id if isinstance(node, ast.AnnAssign)
                    else node.targets[0].id
                )
                if target in _UNION_NAMES:
                    members = core.union_member_names(node.value)
                    if members:
                        side = _UNION_NAMES[target]
                        surface.unions[side] = members
                        surface.union_locs[side] = _Loc(rel, node.lineno)
                elif target in _TAG_TABLE_NAMES:
                    entries = _extract_tag_table(node.value, rel)
                    if entries is not None:
                        side = _TAG_TABLE_NAMES[target]
                        surface.tags[side] = entries
                        surface.tag_table_locs[side] = _Loc(rel, node.lineno)
            elif isinstance(node, ast.FunctionDef):
                for side in _SIDES:
                    if node.name in _ENCODE_FN_NAMES[side]:
                        encode_fns[side][node.name] = (node, rel)
                    if node.name == _DECODE_FN_NAMES[side]:
                        surface.decode_arms[side] = _decode_arms(node, rel, surface)
                        surface.decode_fn_locs[side] = _Loc(rel, node.lineno)
    for side in _SIDES:
        for candidate in _ENCODE_FN_NAMES[side]:
            if candidate in encode_fns[side]:
                fn, fn_rel = encode_fns[side][candidate]
                surface.encode_arms[side] = _encode_arms(fn, fn_rel)
                surface.encode_fn_locs[side] = _Loc(fn_rel, fn.lineno)
                break
    return surface


# -- cross-check ------------------------------------------------------------


def _find(loc: Optional[_Loc], check: str, message: str) -> Finding:
    loc = loc or _Loc(LOCK_REL, 1)
    return Finding(loc.path, loc.lineno, check, message)


def cross_check(surface: WireSurface) -> List[Finding]:
    findings: List[Finding] = []
    for side in _SIDES:
        findings.extend(_check_side(surface, side))
    findings.extend(_check_proto(surface))
    return findings


def _check_side(surface: WireSurface, side: str) -> List[Finding]:
    findings: List[Finding] = []
    union = surface.unions[side]
    tags = surface.tags[side]
    tag_map = surface.tag_map(side)

    if tags is not None:
        seen: Dict[int, str] = {}
        for name, tag, loc in tags:
            if tag in seen:
                findings.append(_find(
                    loc, "tag-reuse",
                    f"{side} tag {tag} assigned to both {seen[tag]} and {name}",
                ))
            else:
                seen[tag] = name

    if union is not None and tags is not None:
        for member in union:
            if member not in tag_map:
                findings.append(_find(
                    surface.tag_table_locs.get(side), "missing-tag",
                    f"{side} union member {member} has no entry in the "
                    f"{side} tag table",
                ))
        for name, _, loc in tags:
            if name not in union:
                findings.append(_find(
                    loc, "dead-arm",
                    f"{side} tag table entry for {name}, which is not a "
                    f"{side} union member",
                ))

    enc = surface.encode_arms[side]
    if tags is not None and enc is not None:
        for name, _, _ in tags:
            fieldless = (
                surface.dataclass_fields.get(name) == []
                or name in surface.fieldless_decoded
            )
            if name not in enc and not fieldless:
                # Fieldless messages (Response, ConsensusResponse) encode as
                # a bare tag: no isinstance arm is needed or present. Proof
                # of fieldlessness is the empty dataclass (types.py) or the
                # zero-argument decode constructor (codec.py standalone).
                findings.append(_find(
                    surface.encode_fn_locs.get(side), "missing-encode-arm",
                    f"{side} type {name} is tagged but has no isinstance "
                    f"encode arm",
                ))
        for name, loc in enc.items():
            if name not in tag_map:
                findings.append(_find(
                    loc, "dead-arm",
                    f"encode arm for {name}, which has no {side} tag "
                    f"(unreachable: the tag lookup raises first)",
                ))

    dec = surface.decode_arms[side]
    if tags is not None and dec is not None:
        decoded = {tag: (name, loc) for tag, name, loc in dec}
        for name, tag, _ in tags:
            if tag not in decoded:
                findings.append(_find(
                    surface.decode_fn_locs.get(side), "missing-decode-arm",
                    f"{side} tag {tag} ({name}) has no decode arm — frames "
                    f"of this type raise instead of decoding",
                ))
            elif decoded[tag][0] != name:
                findings.append(_find(
                    decoded[tag][1], "missing-decode-arm",
                    f"{side} tag {tag} decodes to {decoded[tag][0]} but the "
                    f"tag table assigns it to {name}",
                ))
        for tag, name, loc in dec:
            if tag not in {t for _, t, _ in tags}:
                findings.append(_find(
                    loc, "dead-arm",
                    f"decode arm for {side} tag {tag} ({name}), which no "
                    f"type in the tag table uses",
                ))
    return findings


def _check_proto(surface: WireSurface) -> List[Finding]:
    findings: List[Finding] = []
    for msg, fields in surface.proto.items():
        seen: Dict[int, str] = {}
        for fname, number, loc in fields:
            if number in seen:
                findings.append(_find(
                    loc, "field-number-drift",
                    f"proto message {msg} reuses field number {number} "
                    f"({seen[number]} and {fname})",
                ))
            else:
                seen[number] = fname
    if not surface.proto:
        return findings
    for side, envelope in (("request", "RapidRequest"), ("response", "RapidResponse")):
        union = surface.unions[side]
        if union is None:
            continue
        for member in union:
            if member in NATIVE_ONLY_MESSAGES:
                continue
            if member not in surface.proto:
                findings.append(_find(
                    surface.union_locs.get(side), "field-number-drift",
                    f"{side} union member {member} has no proto message "
                    f"mirror in the interop schema",
                ))
                continue
            proto_fields = {fname for fname, _, _ in surface.proto[member]}
            for field in surface.dataclass_fields.get(member, []):
                if field in NATIVE_ONLY_FIELDS:
                    continue
                if to_proto_field_name(field) not in proto_fields:
                    findings.append(_find(
                        surface.proto_locs.get(member), "field-number-drift",
                        f"proto message {member} has no field covering "
                        f"dataclass field {field!r} "
                        f"(expected {to_proto_field_name(field)!r})",
                    ))
        # The oneof envelope's field numbers double as the native tags in
        # the reference IDL; drift between them is a silent interop break.
        env_fields = {
            fname: (number, loc) for fname, number, loc in surface.proto.get(envelope, [])
        }
        for member, tag in surface.tag_map(side).items():
            if member in NATIVE_ONLY_MESSAGES:
                continue
            entry = env_fields.get(_envelope_field_name(member))
            if entry is not None and entry[0] != tag:
                findings.append(_find(
                    entry[1], "field-number-drift",
                    f"{envelope} envelope field {_envelope_field_name(member)} "
                    f"is number {entry[0]} but the native {side} tag is {tag}",
                ))
    return findings


# -- per-file entry (lint corpus + narrowed CLI roots) ----------------------


def check_wire_schema(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Cross-check the wire mirrors present in ONE module. Sections gate on
    artifact presence, so real mirror files analyzed alone (union but no
    tags, tags but no union) produce no spurious findings — the merged
    tree-mode check owns the cross-file obligations."""
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    return cross_check(extract_surface([(tree, core.rel(path))]))


# -- tree mode: merged mirrors + the lockfile gate --------------------------


def surface_to_lock(surface: WireSurface) -> Dict[str, object]:
    """The canonical freeze of the surface: tags, dataclass field order for
    every union member, and every proto field number."""
    fields: Dict[str, List[str]] = {}
    for side in _SIDES:
        for member in surface.unions[side] or []:
            if member in surface.dataclass_fields:
                fields[member] = list(surface.dataclass_fields[member])
    return {
        "request_tags": surface.tag_map("request"),
        "response_tags": surface.tag_map("response"),
        "fields": fields,
        "proto": {
            msg: {fname: number for fname, number, _ in entries}
            for msg, entries in surface.proto.items()
        },
    }


def compare_lock(surface: WireSurface, locked: Dict[str, object]) -> List[Finding]:
    """Buf-style breaking-change report: every difference between the live
    surface and the committed lock, each naming the drifted message type."""
    current = surface_to_lock(surface)
    findings: List[Finding] = []

    def drift(loc: Optional[_Loc], message: str) -> None:
        findings.append(_find(loc, "wire-lock-drift", f"{message} — {_REGEN_HINT}"))

    for side in _SIDES:
        key = f"{side}_tags"
        cur: Dict[str, int] = current[key]  # type: ignore[assignment]
        old: Dict[str, int] = locked.get(key, {})  # type: ignore[assignment]
        for name in sorted(set(cur) | set(old)):
            if name not in old:
                drift(surface.loc_of_tag(side, name),
                      f"{side} message {name} added since the wire lock "
                      f"(tag {cur[name]})")
            elif name not in cur:
                drift(surface.tag_table_locs.get(side),
                      f"{side} message {name} removed since the wire lock "
                      f"(was tag {old[name]})")
            elif cur[name] != old[name]:
                drift(surface.loc_of_tag(side, name),
                      f"{side} message {name} renumbered: tag "
                      f"{old[name]} -> {cur[name]}")
    cur_fields: Dict[str, List[str]] = current["fields"]  # type: ignore[assignment]
    old_fields: Dict[str, List[str]] = locked.get("fields", {})  # type: ignore[assignment]
    for name in sorted(set(cur_fields) | set(old_fields)):
        if name not in old_fields:
            drift(surface.class_locs.get(name),
                  f"message {name} has no field-order entry in the wire lock")
        elif name not in cur_fields:
            drift(None, f"message {name} vanished from the unions but is "
                        f"still in the wire lock")
        elif cur_fields[name] != old_fields[name]:
            drift(surface.class_locs.get(name),
                  f"message {name} dataclass field order changed: "
                  f"{old_fields[name]} -> {cur_fields[name]} (the native "
                  f"codec serializes fields positionally)")
    cur_proto: Dict[str, Dict[str, int]] = current["proto"]  # type: ignore[assignment]
    old_proto: Dict[str, Dict[str, int]] = locked.get("proto", {})  # type: ignore[assignment]
    for msg in sorted(set(cur_proto) | set(old_proto)):
        if msg not in old_proto:
            drift(surface.proto_locs.get(msg),
                  f"proto message {msg} added since the wire lock")
            continue
        if msg not in cur_proto:
            drift(None, f"proto message {msg} removed since the wire lock")
            continue
        for fname in sorted(set(cur_proto[msg]) | set(old_proto[msg])):
            if fname not in old_proto[msg]:
                drift(surface.proto_locs.get(msg),
                      f"proto message {msg} gained field {fname} "
                      f"(number {cur_proto[msg][fname]}) since the wire lock")
            elif fname not in cur_proto[msg]:
                drift(surface.proto_locs.get(msg),
                      f"proto message {msg} lost field {fname} "
                      f"(was number {old_proto[msg][fname]})")
            elif cur_proto[msg][fname] != old_proto[msg][fname]:
                drift(surface.proto_locs.get(msg),
                      f"proto message {msg} field {fname} renumbered: "
                      f"{old_proto[msg][fname]} -> {cur_proto[msg][fname]}")
    return findings


def _wire_trees(trees: Sequence[Tuple[ast.AST, str]]):
    wanted = {f: None for f in WIRE_FILES}
    for tree, rel in trees:
        posix = rel.replace("\\", "/")
        if posix in wanted:
            wanted[posix] = tree
    if any(tree is None for tree in wanted.values()):
        return None  # not this repo's tree (tests retarget REPO) — skip
    return [(tree, rel) for rel, tree in wanted.items()]


def check_wire_lock(trees: Sequence[Tuple[ast.AST, str]]) -> List[Finding]:
    """Tree-mode gate: merge the three mirror files, cross-check them
    against each other, then against the committed lock."""
    selected = _wire_trees(trees)
    if selected is None:
        return []
    surface = extract_surface(selected)
    findings = cross_check(surface)
    lock_path = core.REPO / LOCK_REL
    if not lock_path.exists():
        findings.append(Finding(
            LOCK_REL, 1, "wire-lock-drift",
            f"wire lockfile missing — generate it via "
            f"`python tools/staticcheck.py --update-wire-lock`",
        ))
        return findings
    try:
        locked = json.loads(lock_path.read_text())
    except json.JSONDecodeError as exc:
        findings.append(Finding(
            LOCK_REL, 1, "wire-lock-drift",
            f"wire lockfile is not valid JSON ({exc.msg}) — regenerate via "
            f"`python tools/staticcheck.py --update-wire-lock`",
        ))
        return findings
    findings.extend(compare_lock(surface, locked))
    return findings


def update_wire_lock() -> Tuple[List[Finding], Optional[Path]]:
    """Regenerate the lockfile from the live mirrors. Refuses (returning the
    findings) while the mirrors disagree with each other — an inconsistent
    surface must be fixed, not frozen."""
    trees = []
    for rel in WIRE_FILES:
        path = core.REPO / rel
        trees.append((ast.parse(path.read_text(), filename=str(path)), rel))
    surface = extract_surface(trees)
    findings = cross_check(surface)
    if findings:
        return findings, None
    lock_path = core.REPO / LOCK_REL
    payload = {
        "_comment": (
            "Frozen wire surface: native codec tags, dataclass field order, "
            "and interop proto field numbers. Generated by `python "
            "tools/staticcheck.py --update-wire-lock`; do not edit by hand — "
            "any drift from the live mirrors fails the staticcheck gate."
        ),
        **surface_to_lock(surface),
    }
    lock_path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return [], lock_path
