"""Check family 9: async failure-path hygiene over the library.

The membership protocol's stability claim lives or dies in its failure
paths: a swallowed exception or a dropped background task turns a crash
(restartable, alertable) into a silent wedge — exactly the failure mode
the reconfiguration literature identifies as the hard part. These checks
cover the four ways an asyncio codebase loses an error, over all of
``rapid_tpu/``:

- ``leaked-task`` — ``asyncio.create_task(...)`` / ``ensure_future(...)``
  whose result is discarded as an expression statement: nothing retains
  the task (the loop holds it weakly — it can be garbage-collected
  mid-flight) and nothing observes its exception. Retain it, add it to a
  tracked set with a done-callback, or chain ``.add_done_callback``.
- ``swallowed-exception`` — ``except Exception:`` / ``except
  BaseException:`` / bare ``except:`` that neither re-raises nor carries
  a ``# noqa: BLE001 — <reason>`` justification on the ``except`` line.
  A broad catch is sometimes right (fault-isolation boundaries, app
  callbacks); it is never right silently.
- ``cancellation-swallow`` — a handler inside ``async def`` that catches
  ``asyncio.CancelledError`` (explicitly, via ``BaseException``, or via
  bare ``except``) without a ``raise`` in its body: the task absorbs its
  own cancellation and ``shutdown()`` hangs on the gather. (Plain
  ``except Exception`` is safe here — ``CancelledError`` derives from
  ``BaseException`` since Python 3.8 — which is why the broad catches in
  the liveness loops are legal once justified.)
- ``unawaited-coroutine`` — a call whose target resolves to an ``async
  def`` in the same module/class, discarded as an expression statement:
  the coroutine object is built and dropped, the body never runs.

Escape hatch: ``# taskflow-ok: <reason>`` on the offending line
allowlists any of the four (``swallowed-exception`` also honors the
conventional ``# noqa: BLE001``). Resolution is conservative: only
targets provable from the same file are judged.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set

from . import core
from .core import Finding

#: The tree this discipline applies to (posix-style relative prefixes).
TASKFLOW_PREFIXES = ("rapid_tpu/",)

_SPAWN_NAMES = frozenset({"create_task", "ensure_future"})
_BROAD_EXC_NAMES = frozenset({"Exception", "BaseException"})
_CANCEL_EXC_NAMES = frozenset({"BaseException", "CancelledError"})

_ALLOW_RE = re.compile(r"#\s*taskflow-ok\b")
_NOQA_BLE_RE = re.compile(r"#\s*noqa:\s*BLE001\b")


def _exc_names(node: Optional[ast.AST]) -> Optional[Set[str]]:
    """The exception-class names a handler's ``type`` clause mentions, or
    None for a bare ``except:`` (which catches everything)."""
    if node is None:
        return None
    names: Set[str] = set()
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    for elt in elts:
        if isinstance(elt, ast.Name):
            names.add(elt.id)
        elif isinstance(elt, ast.Attribute):
            names.add(elt.attr)
    return names


def _has_raise(stmts: List[ast.stmt]) -> bool:
    """A ``raise`` anywhere in these statements' own function scope."""

    def walk(node: ast.AST) -> bool:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        if isinstance(node, ast.Raise):
            return True
        return any(walk(child) for child in ast.iter_child_nodes(node))

    return any(walk(stmt) for stmt in stmts)


def _is_spawn_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in _SPAWN_NAMES
    return isinstance(func, ast.Attribute) and func.attr in _SPAWN_NAMES


class _AsyncIndex:
    """Same-file resolution targets: module-level async defs and per-class
    async methods."""

    def __init__(self, tree: ast.AST) -> None:
        self.module_async: Set[str] = {
            node.name
            for node in getattr(tree, "body", [])
            if isinstance(node, ast.AsyncFunctionDef)
        }
        self.class_async: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.class_async[node.name] = {
                    item.name
                    for item in node.body
                    if isinstance(item, ast.AsyncFunctionDef)
                }


def check_taskflow(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in TASKFLOW_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()

    def allowed(lineno: int, extra: Optional[re.Pattern] = None) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        if _ALLOW_RE.search(line):
            return True
        return bool(extra and extra.search(line))

    index = _AsyncIndex(tree)
    findings: List[Finding] = []

    def visit(node: ast.AST, in_async: bool, cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                visit(child, in_async, node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child_async = isinstance(node, ast.AsyncFunctionDef)
            for child in node.body:
                visit(child, child_async, cls)
            return
        if isinstance(node, ast.Lambda):
            return

        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if _is_spawn_call(call):
                if not allowed(node.lineno):
                    findings.append(Finding(
                        rel, node.lineno, "leaked-task",
                        "fire-and-forget task: the result of "
                        f"{ast.unparse(call.func)}(...) is neither retained, "
                        "tracked in a set, nor given a done-callback — the "
                        "loop holds tasks weakly and its exception is never "
                        "observed",
                    ))
            else:
                target = None
                func = call.func
                if (
                    isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "self"
                    and cls is not None
                    and func.attr in index.class_async.get(cls, ())
                ):
                    target = f"self.{func.attr}"
                elif isinstance(func, ast.Name) and func.id in index.module_async:
                    target = func.id
                if target is not None and not allowed(node.lineno):
                    findings.append(Finding(
                        rel, node.lineno, "unawaited-coroutine",
                        f"{target}(...) is an async def but its coroutine is "
                        "discarded as a statement — the body never runs; "
                        "await it or schedule it as a tracked task",
                    ))

        if isinstance(node, ast.ExceptHandler):
            names = _exc_names(node.type)
            broad = names is None or bool(names & _BROAD_EXC_NAMES)
            catches_cancel = names is None or bool(names & _CANCEL_EXC_NAMES)
            reraises = _has_raise(node.body)
            if broad and not reraises and not allowed(node.lineno, _NOQA_BLE_RE):
                caught = "bare except" if names is None else ", ".join(sorted(names))
                findings.append(Finding(
                    rel, node.lineno, "swallowed-exception",
                    f"broad catch ({caught}) neither re-raises nor carries a "
                    "`# noqa: BLE001 — <reason>` justification — a silent "
                    "failure path turns crashes into wedges",
                ))
            if in_async and catches_cancel and not reraises and not allowed(node.lineno):
                caught = "bare except" if names is None else ", ".join(
                    sorted(names & _CANCEL_EXC_NAMES) or sorted(names)
                )
                findings.append(Finding(
                    rel, node.lineno, "cancellation-swallow",
                    f"handler ({caught}) inside async def absorbs "
                    "asyncio.CancelledError without re-raising — the task "
                    "survives its own cancellation and shutdown hangs on it",
                ))

        for child in ast.iter_child_nodes(node):
            visit(child, in_async, cls)

    for stmt in getattr(tree, "body", []):
        visit(stmt, False, None)
    return findings
