"""Check family 4: dead module-level definitions (tree-wide liveness)."""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from .core import Finding

_DEF_ALLOW_PREFIXES = ("test_", "Test", "pytest_", "__")
_DEF_ALLOW_NAMES = {"main", "entry", "dryrun_multichip"}  # external entry points
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _collect_definitions(tree: ast.AST, rel: str):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield node.name, rel, node.lineno
        # Simple module constants too (plain Name targets only: tuple
        # unpacking legitimately discards elements, so it is out of scope;
        # dunders like __all__ fall to the allowlist).
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    yield target.id, rel, node.lineno
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            yield node.target.id, rel, node.lineno


def _collect_references(tree: ast.AST) -> set:
    """Every way a module-level definition can be consumed: name loads,
    attribute accesses, function parameter names (pytest fixtures are used
    by naming them as parameters), ``getattr``/``setattr``/``hasattr``/
    ``delattr`` with a literal field name (dynamic lane access is still
    access — the dataflow family's dead-lane check and this one must
    never disagree on liveness), identifiers inside f-string fragments
    (a lane named in a debug label is consumed by whoever reads the
    label), and identifiers inside CODE-LOOKING string constants
    (multi-line or call-shaped — subprocess job scripts, ``python -c``
    payloads). Other single-word strings deliberately do NOT count: an
    ``__all__`` entry must not keep an otherwise-unreferenced export
    alive — re-export padding is exactly what this check exists to catch.

    A module-level definition's OWN subtree never contributes its own name:
    a dead recursive helper, a class naming itself in a method, or a
    constant whose initializer/mutation mentions itself must not keep
    itself alive.
    """

    def walk(node, self_name):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id != self_name:
                refs.add(node.id)
        elif isinstance(node, ast.Attribute):
            if node.attr != self_name:
                refs.add(node.attr)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = node.args
            for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
                refs.add(arg.arg)
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("getattr", "setattr", "hasattr", "delattr")
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
                and node.args[1].value != self_name
            ):
                refs.add(node.args[1].value)
        elif isinstance(node, ast.JoinedStr):
            for frag in node.values:
                if isinstance(frag, ast.Constant) and isinstance(frag.value, str):
                    refs.update(
                        w for w in _IDENT.findall(frag.value) if w != self_name
                    )
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "\n" in node.value or "(" in node.value:
                refs.update(w for w in _IDENT.findall(node.value) if w != self_name)
        for child in ast.iter_child_nodes(node):
            walk(child, self_name)

    refs: set = set()
    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            for child in ast.iter_child_nodes(stmt):
                walk(child, stmt.name)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
            stmt.targets[0], ast.Name
        ):
            walk(stmt.value, stmt.targets[0].id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            walk(stmt.annotation, None)  # the type names ARE references
            if stmt.value is not None:
                walk(stmt.value, stmt.target.id)
        else:
            walk(stmt, None)
    return refs


def check_dead_definitions(
    contributions: "List[Tuple[ast.AST, str]]",
) -> List[Finding]:
    """Module-level functions/classes/constants referenced NOWHERE in the tree.

    Takes (tree, relpath) pairs for the WHOLE analyzed tree — liveness is
    only meaningful over the full root set, so run() skips this check when
    the CLI narrows the roots. Tree-wide, name-based (not resolution-based):
    a name collision anywhere keeps a definition alive, so every finding is
    a definition no file could be using. The repo's standard is that
    unconsumed code is deleted, not exported (the Mosaic watermark kernel
    precedent)."""
    defs: List[Tuple[str, str, int]] = []
    refs: set = set()
    for tree, rel in contributions:
        defs.extend(_collect_definitions(tree, rel))
        refs |= _collect_references(tree)
    findings = []
    for name, rel, lineno in defs:
        if name.startswith(_DEF_ALLOW_PREFIXES) or name in _DEF_ALLOW_NAMES:
            continue
        if name not in refs:
            findings.append(
                Finding(rel, lineno, "dead-definition",
                        f"module-level {name!r} is referenced nowhere in the tree")
            )
    return findings
