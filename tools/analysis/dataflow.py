"""Check family 17: jaxpr dataflow provenance gate (``dataflow``).

The families up to here gate the compiled artifact's COST (hlo.lock.json
budgets, cost.lock.json scaling classes). This family gates its
INFLUENCE STRUCTURE: every registered ``device_program`` entrypoint is
traced (no XLA compile — ``jitted.trace``) to its closed jaxpr and a
deterministic per-lane taint/provenance propagation runs over it, through
``pjit``/``scan``/``while``/``cond`` sub-jaxprs, producing:

* a lane -> lane influence relation per entrypoint (which input lanes can
  affect which output lanes, with ``while``/``scan`` carries tracked
  PER SLOT so carry/donated-buffer reuse never fabricates an edge);
* a per-equation provenance classification (prologue / cond / hot-loop
  scope, dense-over-N or not, mask-gated or not).

Both are frozen in ``tools/analysis/dataflow.lock.json`` and regenerated
byte-identically by ``python tools/staticcheck.py --update-dataflow-lock``
(which REFUSES while any proof below fails). Checks:

``dataflow-observer-effect``
    No telemetry (``tl_*``) or trace-ring (``tr_*``) lane may influence
    any ``EngineState`` lane or step event. The trace-on/off bit-identity
    grids in the test suite sample this; here it is a whole-program proof
    over the jaxpr — an observer that perturbs its subject cannot trace.

``dataflow-cross-tenant``
    Under the fleet vmap, no un-batched influence edge between
    tenant-indexed lanes: a tenant-axis abstract interpretation tracks
    which dimension of every intermediate is the tenant axis and proves
    no data output mixes tenants (while-loop PREDICATES legitimately
    reduce over tenants — vmap lockstep semantics — and are exempt; data
    lanes are not). Complements the HLO gate's zero-cross-tenant-
    collective budget at the dataflow level.

``dataflow-dense-op``
    The sparse-opportunity map: round-body equations that compute over
    the full N slots yet are provably gated by the activity/alert/freeze
    masks (structurally inside an activity-gated ``cond`` branch, or all
    of whose consumers are activity-masked selects). Each is priced by
    joining against the quiescent collective rows (the cost.lock.json
    ``quiescent_round_cost`` block) on (location, source), so the map
    states what share of the frozen quiescent payload bytes each dense
    op explains. ROADMAP item 3's sparse restructure consumes this map
    as its work-list; the check fires when the map stops explaining >=
    90% of the frozen bytes, or the two locks disagree on the total.

``dataflow-dead-lane``
    State lanes written by some entrypoint but never influencing any
    output or fetched digest, under the transitive closure of the
    step relation — dead weight the deadcode family (name-based) cannot
    see and must never disagree with.

``dataflow-lock-drift``
    The committed lock no longer matches the live trace.

Tracing is cheap (~2 s for the whole registry, no compile) and the
byte-pricing join reuses the HLO gate's session-cached compiles, so this
family rides in the same session budget as the cost ladder.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from . import core, hlo_facts
from .core import Finding

DATAFLOW_LOCK_REL = "tools/analysis/dataflow.lock.json"

_REGEN_HINT = (
    "rerun `python tools/staticcheck.py --update-dataflow-lock` after "
    "reviewing the influence change"
)

#: Containers whose fields become lane-label prefixes. Anything else
#: labels by field path alone (corpus probes may define their own
#: NamedTuples under these names and get the same treatment).
_CONTAINER_PREFIX = {
    "EngineState": "state",
    "TelemetryLanes": "telem",
    "TraceRing": "trace",
    "FaultInputs": "faults",
    "StepEvents": "events",
}

#: Observer planes: lanes on these containers (or with these field-name
#: spellings) must never influence a subject lane.
_OBSERVER_CONTAINERS = ("telem", "trace")
_OBSERVER_FIELDS = ("tl_", "tr_")
#: Subject planes the observer-effect proof protects.
_SUBJECT_CONTAINERS = ("state", "events")

#: Activity/alert/freeze masks: a dense op counts as mask-GATED when the
#: predicate deciding whether its result is used derives from one of
#: these lanes (field names, container-agnostic — the fleet's batched
#: lanes carry the same labels).
GATING_LANE_FIELDS = frozenset({
    "alive", "crashed", "probe_fail", "rx_block",
    "fd_fired", "fd_count", "fd_hist", "fire_round",
    "report_bits", "seen_down", "released", "announced",
    "prop_mask", "join_pending", "vote_valid", "retired",
    "rounds_undecided", "decided", "round_idx",
})

#: How a jaxpr primitive spells its HLO op_name leaf — the join key that
#: lets a dense jaxpr equation claim the collective rows its lowering
#: produced (GSPMD strips Python function scopes from op_names; only the
#: primitive leaf and surviving inner-jit scopes remain, so the join runs
#: through hlo_facts.source_of applied to BOTH sides).
_PRIM_HLO_LEAF = {
    "cumsum": "cumsum", "cummax": "reduce_window", "cummin": "reduce_window",
    "cumprod": "reduce_window",
    "reduce_min": "reduce", "reduce_and": "reduce", "reduce_prod": "reduce",
    "argmax": "reduce", "argmin": "reduce",
    "select_n": "select",
    "dynamic_update_slice": "dynamic_update_slice",
}


def _is_literal(atom: Any) -> bool:
    return hasattr(atom, "val")


def _is_dropvar(var: Any) -> bool:
    return type(var).__name__ == "DropVar"


# ---------------------------------------------------------------------------
# lane labeling
# ---------------------------------------------------------------------------


def _lane_labels(tree: Any, role: str) -> List[str]:
    """One label per flattened leaf, in jax flatten order: NamedTuple
    containers contribute their registered prefix (``state.alive``),
    positional nesting contributes indices, bare leaves fall back to
    ``<role><i>``. The order contract (matching ``tree_leaves``) is
    asserted by the caller against the jaxpr's invar count."""
    labels: List[str] = []

    def walk(node: Any, prefix: str, fallback: str) -> None:
        if node is None:
            return
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            cname = _CONTAINER_PREFIX.get(
                type(node).__name__, type(node).__name__.lower()
            )
            base = f"{prefix}.{cname}" if prefix else cname
            for field in node._fields:
                walk(getattr(node, field), f"{base}.{field}", f"{base}.{field}")
            return
        if isinstance(node, (tuple, list)):
            for i, item in enumerate(node):
                walk(item, f"{prefix}[{i}]" if prefix else "", f"{fallback}[{i}]")
            return
        if isinstance(node, dict):
            for key in sorted(node):
                sub = f"{prefix}.{key}" if prefix else str(key)
                walk(node[key], sub, sub)
            return
        labels.append(prefix or fallback)

    if isinstance(tree, tuple) and not hasattr(tree, "_fields"):
        for i, arg in enumerate(tree):
            walk(arg, "", f"{role}{i}")
    else:
        walk(tree, "", f"{role}0")
    return labels


def _field_of(label: str) -> str:
    return label.rsplit(".", 1)[-1]


def _container_of(label: str) -> str:
    return label.split(".", 1)[0] if "." in label else ""


def _is_observer_lane(label: str) -> bool:
    return _container_of(label) in _OBSERVER_CONTAINERS or _field_of(
        label
    ).startswith(_OBSERVER_FIELDS)


def _is_subject_lane(label: str) -> bool:
    return _container_of(label) in _SUBJECT_CONTAINERS and not _field_of(
        label
    ).startswith(_OBSERVER_FIELDS)


def _is_gating_lane(label: str) -> bool:
    return _field_of(label) in GATING_LANE_FIELDS


# ---------------------------------------------------------------------------
# taint interpreter (lane -> lane influence)
# ---------------------------------------------------------------------------


def _sub_jaxpr(params: Dict[str, Any]):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = params.get(key)
        if sub is not None and (hasattr(sub, "jaxpr") or hasattr(sub, "invars")):
            return sub
    return None


def _taint_closed(closed: Any, in_taints: List[FrozenSet[int]]) -> List[FrozenSet[int]]:
    """Per-output taint sets (indices into the caller's lane space) for
    one (closed) jaxpr. A sub-jaxpr whose arity does not match the
    call-site operands (exotic custom-call packing) degrades soundly to
    union-of-everything instead of guessing an alignment."""
    jaxpr = getattr(closed, "jaxpr", closed)
    if len(in_taints) != len(jaxpr.invars):
        union: FrozenSet[int] = frozenset().union(*in_taints) if in_taints else frozenset()
        return [union] * len(jaxpr.outvars)
    env: Dict[Any, FrozenSet[int]] = {}
    for var in jaxpr.constvars:
        env[var] = frozenset()
    for var, taint in zip(jaxpr.invars, in_taints):
        env[var] = taint

    def read(atom: Any) -> FrozenSet[int]:
        if _is_literal(atom):
            return frozenset()
        return env.get(atom, frozenset())

    for eqn in jaxpr.eqns:
        outs = _eqn_taints(eqn, [read(a) for a in eqn.invars])
        for var, taint in zip(eqn.outvars, outs):
            if not _is_dropvar(var):
                env[var] = taint
    return [read(v) for v in jaxpr.outvars]


def _eqn_taints(eqn: Any, in_t: List[FrozenSet[int]]) -> List[FrozenSet[int]]:
    prim = eqn.primitive.name
    params = eqn.params
    n_out = len(eqn.outvars)
    if prim == "cond":
        # Control dependence: the predicate decides WHICH branch's values
        # flow, so it taints every output.
        pred, ops = in_t[0], in_t[1:]
        outs = [frozenset(pred) for _ in range(n_out)]
        for branch in params["branches"]:
            branch_outs = _taint_closed(branch, list(ops))
            for i in range(min(n_out, len(branch_outs))):
                outs[i] = outs[i] | branch_outs[i]
        return outs
    if prim == "while":
        cn = params["cond_nconsts"]
        bn = params["body_nconsts"]
        cond_consts, body_consts = in_t[:cn], in_t[cn:cn + bn]
        carry = list(in_t[cn + bn:])
        # Per-slot fixpoint: carries are tracked separately so slot reuse
        # (aliasing/donation at the buffer level) cannot fabricate an
        # influence edge between unrelated lanes. The predicate taints
        # every carry (it decides how many updates run).
        while True:
            pred_outs = _taint_closed(params["cond_jaxpr"], cond_consts + carry)
            pred = pred_outs[0] if pred_outs else frozenset()
            body_outs = _taint_closed(params["body_jaxpr"], body_consts + carry)
            merged = [c | b | pred for c, b in zip(carry, body_outs)]
            if merged == carry:
                return carry
            carry = merged
    if prim == "scan":
        nc, nk = params["num_consts"], params["num_carry"]
        consts, xs = in_t[:nc], list(in_t[nc + nk:])
        carry = list(in_t[nc:nc + nk])
        while True:
            outs = _taint_closed(params["jaxpr"], consts + carry + xs)
            merged = [c | o for c, o in zip(carry, outs[:nk])]
            if merged == carry:
                return carry + list(outs[nk:])
            carry = merged
    sub = _sub_jaxpr(params)
    if sub is not None:
        return _taint_closed(sub, list(in_t))
    union = frozenset().union(*in_t) if in_t else frozenset()
    return [union] * n_out


# ---------------------------------------------------------------------------
# provenance walk (per-equation classification + sparse-opportunity map)
# ---------------------------------------------------------------------------


class _Provenance:
    """Instrumented re-walk of one traced entrypoint: same recursion as
    the taint interpreter, but recording per-equation (location, scope,
    dense, gated) records and location counts. ``location`` follows
    hlo_facts.classify_location semantics: a while body/cond is hot-loop,
    a cond branch is cond (hot-loop-cond inside a loop), else prologue."""

    def __init__(self, in_labels: List[str], dense_threshold: int):
        self.in_labels = in_labels
        self.dense_threshold = dense_threshold
        self.dense_records: List[Dict[str, Any]] = []
        self.location_counts: Dict[str, int] = {}

    def _labels_for(self, taint: FrozenSet[int]) -> List[str]:
        return sorted(self.in_labels[i] for i in taint)

    def _gating(self, taint: FrozenSet[int]) -> List[str]:
        return sorted(
            self.in_labels[i] for i in taint if _is_gating_lane(self.in_labels[i])
        )

    def run(self, closed: Any, in_taints: List[FrozenSet[int]]) -> None:
        self._walk(closed, in_taints, scopes=(), location="prologue",
                   gate_lanes=frozenset())

    def _walk(self, closed: Any, in_taints: List[FrozenSet[int]],
              scopes: Tuple[str, ...], location: str,
              gate_lanes: FrozenSet[int]) -> None:
        jaxpr = getattr(closed, "jaxpr", closed)
        if len(in_taints) != len(jaxpr.invars):
            return
        env: Dict[Any, FrozenSet[int]] = {}
        for var in jaxpr.constvars:
            env[var] = frozenset()
        for var, taint in zip(jaxpr.invars, in_taints):
            env[var] = taint

        def read(atom: Any) -> FrozenSet[int]:
            if _is_literal(atom):
                return frozenset()
            return env.get(atom, frozenset())

        consumers: Dict[Any, List[Any]] = {}
        for eqn in jaxpr.eqns:
            for atom in eqn.invars:
                if not _is_literal(atom):
                    consumers.setdefault(atom, []).append(eqn)
        escaping = {v for v in jaxpr.outvars if not _is_literal(v)}

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            params = eqn.params
            in_t = [read(a) for a in eqn.invars]
            self.location_counts[location] = (
                self.location_counts.get(location, 0) + 1
            )
            if prim == "cond":
                branch_loc = "hot-loop-cond" if location.startswith("hot-loop") else "cond"
                branch_gates = gate_lanes | frozenset(
                    i for i in in_t[0] if _is_gating_lane(self.in_labels[i])
                )
                for branch in params["branches"]:
                    self._walk(branch, list(in_t[1:]), scopes + ("cond",),
                               branch_loc, branch_gates)
            elif prim == "while":
                cn, bn = params["cond_nconsts"], params["body_nconsts"]
                carry = self._fixpoint_while(params, in_t)
                self._walk(params["cond_jaxpr"], in_t[:cn] + carry,
                           scopes + ("while",), "hot-loop", gate_lanes)
                self._walk(params["body_jaxpr"], in_t[cn:cn + bn] + carry,
                           scopes + ("while",), "hot-loop", gate_lanes)
            elif prim == "scan":
                nc, nk = params["num_consts"], params["num_carry"]
                carry = self._fixpoint_scan(params, in_t)
                self._walk(params["jaxpr"],
                           in_t[:nc] + carry + list(in_t[nc + nk:]),
                           scopes + ("scan",), location, gate_lanes)
            else:
                sub = _sub_jaxpr(params)
                if sub is not None:
                    name = params.get("name") or prim
                    self._walk(sub, list(in_t), scopes + (str(name),),
                               location, gate_lanes)
                else:
                    self._record(eqn, in_t, read, consumers, escaping,
                                 scopes, location, gate_lanes)
            outs = _eqn_taints(eqn, in_t)
            for var, taint in zip(eqn.outvars, outs):
                if not _is_dropvar(var):
                    env[var] = taint

    def _fixpoint_while(self, params, in_t):
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        carry = list(in_t[cn + bn:])
        while True:
            pred_outs = _taint_closed(params["cond_jaxpr"], in_t[:cn] + carry)
            pred = pred_outs[0] if pred_outs else frozenset()
            body = _taint_closed(params["body_jaxpr"], in_t[cn:cn + bn] + carry)
            merged = [c | b | pred for c, b in zip(carry, body)]
            if merged == carry:
                return carry
            carry = merged

    def _fixpoint_scan(self, params, in_t):
        nc, nk = params["num_consts"], params["num_carry"]
        carry = list(in_t[nc:nc + nk])
        xs = list(in_t[nc + nk:])
        while True:
            outs = _taint_closed(params["jaxpr"], in_t[:nc] + carry + xs)
            merged = [c | o for c, o in zip(carry, outs[:nk])]
            if merged == carry:
                return carry
            carry = merged

    def _record(self, eqn, in_t, read, consumers, escaping, scopes,
                location, gate_lanes) -> None:
        sizes = [
            int(getattr(a.aval, "size", 0))
            for a in list(eqn.invars) + list(eqn.outvars)
            if not _is_literal(a) and hasattr(a, "aval")
        ]
        if not sizes or max(sizes) < self.dense_threshold:
            return
        prim = eqn.primitive.name
        gated_by: FrozenSet[int] = frozenset()
        if gate_lanes:
            gated_by = gate_lanes
        else:
            select_gates = self._select_gated(eqn, read, consumers, escaping)
            if select_gates is not None:
                gated_by = select_gates
        leaf = _PRIM_HLO_LEAF.get(prim, prim)
        op_name = "/".join(scopes + (leaf,))
        self.dense_records.append({
            "prim": prim,
            "scope": op_name,
            "location": location,
            "source": hlo_facts.source_of(op_name),
            "elems": max(sizes),
            "gated": bool(gated_by),
            "gated_by": sorted(
                {self.in_labels[i] for i in gated_by}
            ),
        })

    def _select_gated(self, eqn, read, consumers, escaping) -> Optional[FrozenSet[int]]:
        """Consumer rule: every use of every output is a select whose
        predicate carries an activity-mask taint and which consumes the
        value as a CASE (not as the predicate). An output escaping this
        sub-jaxpr counts as an ungated use — the caller's context is not
        visible here, so the claim stays conservative."""
        gates: FrozenSet[int] = frozenset()
        for var in eqn.outvars:
            if _is_dropvar(var):
                continue
            if var in escaping:
                return None
            uses = consumers.get(var, [])
            if not uses:
                continue
            for use in uses:
                pred = self._select_pred(use)
                if pred is None or pred is var:
                    return None
                pred_gates = frozenset(
                    i for i in read(pred) if _is_gating_lane(self.in_labels[i])
                )
                if not pred_gates:
                    return None
                gates = gates | pred_gates
        return gates if gates else None

    @staticmethod
    def _select_pred(eqn) -> Optional[Any]:
        if eqn.primitive.name == "select_n" and eqn.invars:
            return eqn.invars[0]
        if eqn.primitive.name == "pjit" and str(
            eqn.params.get("name", "")
        ).startswith("_where") and eqn.invars:
            return eqn.invars[0]
        return None


# ---------------------------------------------------------------------------
# tenant-axis abstract interpretation (cross-tenant proof)
# ---------------------------------------------------------------------------

_MIXED = "mixed"

_ELEMENTWISE_SAFE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "max", "min",
    "and", "or", "xor", "not", "neg", "sign", "abs", "floor", "ceil",
    "round", "exp", "log", "log1p", "expm1", "sqrt", "rsqrt", "tanh",
    "logistic", "sin", "cos", "is_finite", "eq", "ne", "lt", "le", "gt",
    "ge", "select_n", "convert_element_type", "stop_gradient", "copy",
    "clamp", "nextafter", "population_count", "clz", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "erf", "erf_inv",
    "erfc", "square", "real", "imag", "device_put", "optimization_barrier",
    "reduce_precision", "exp2", "atan2", "sharding_constraint",
})


def _unify_axes(axes: List[Any]) -> Any:
    """None (uniform) / int (tenant dim) / 'mixed' lattice join for
    equal-shape operands."""
    seen = {a for a in axes if a is not None}
    if not seen:
        return None
    if _MIXED in seen or len(seen) > 1:
        return _MIXED
    return seen.pop()


def _axis_closed(closed: Any, in_axes: List[Any], tenants: int,
                 fallbacks: List[str]) -> List[Any]:
    jaxpr = getattr(closed, "jaxpr", closed)
    if len(in_axes) != len(jaxpr.invars):
        worst = _MIXED if any(a is not None for a in in_axes) else None
        return [worst] * len(jaxpr.outvars)
    env: Dict[Any, Any] = {}
    for var in jaxpr.constvars:
        env[var] = None
    for var, axis in zip(jaxpr.invars, in_axes):
        env[var] = axis

    def read(atom: Any) -> Any:
        if _is_literal(atom):
            return None
        return env.get(atom)

    for eqn in jaxpr.eqns:
        outs = _axis_eqn(eqn, [read(a) for a in eqn.invars], tenants, fallbacks)
        for var, axis in zip(eqn.outvars, outs):
            if not _is_dropvar(var):
                env[var] = axis
    return [read(v) for v in jaxpr.outvars]


def _axis_eqn(eqn: Any, in_a: List[Any], tenants: int,
              fallbacks: List[str]) -> List[Any]:
    prim = eqn.primitive.name
    params = eqn.params
    n_out = len(eqn.outvars)
    if all(a is None for a in in_a):
        return [None] * n_out
    if prim in _ELEMENTWISE_SAFE:
        return [_unify_axes(in_a)] * n_out
    if prim == "broadcast_in_dim":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [params["broadcast_dimensions"][axis]] * n_out
    if prim == "transpose":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [list(params["permutation"]).index(axis)] * n_out
    if prim == "squeeze":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        removed = params["dimensions"]
        if axis in removed:
            return [_MIXED] * n_out
        return [axis - sum(1 for d in removed if d < axis)] * n_out
    if prim == "expand_dims":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        added = params["dimensions"]
        return [axis + sum(1 for d in added if d <= axis)] * n_out
    if prim == "reshape":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        operand = eqn.invars[0].aval.shape
        new_sizes = params["new_sizes"]
        prefix = 1
        for d in range(axis):
            prefix *= operand[d]
        running = 1
        for e, size in enumerate(new_sizes):
            if running == prefix and size == operand[axis]:
                return [e] * n_out
            running *= size
        return [_MIXED] * n_out
    if prim.startswith("reduce_") or prim in ("argmax", "argmin"):
        axis = _unify_axes(in_a)
        if axis in (None, _MIXED):
            return [axis] * n_out
        axes = params.get("axes", ())
        if axis in axes:
            return [_MIXED] * n_out
        return [axis - sum(1 for d in axes if d < axis)] * n_out
    if prim.startswith("cum"):
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [_MIXED if params.get("axis") == axis else axis] * n_out
    if prim == "concatenate":
        axis = _unify_axes(in_a)
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [_MIXED if params["dimension"] == axis else axis] * n_out
    if prim == "pad":
        return [in_a[0]] * n_out
    if prim == "slice":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        shape = eqn.invars[0].aval.shape
        keeps_all = (
            params["start_indices"][axis] == 0
            and params["limit_indices"][axis] == shape[axis]
        )
        return [axis if keeps_all else _MIXED] * n_out
    if prim == "rev":
        axis = in_a[0]
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [_MIXED if axis in params["dimensions"] else axis] * n_out
    if prim == "iota":
        return [None] * n_out
    if prim == "sort":
        axis = _unify_axes(in_a)
        if axis in (None, _MIXED):
            return [axis] * n_out
        return [_MIXED if params["dimension"] == axis else axis] * n_out
    if prim == "dynamic_slice":
        operand_axis = in_a[0]
        if any(a is not None for a in in_a[1:]):
            return [_MIXED] * n_out
        if operand_axis in (None, _MIXED):
            return [operand_axis] * n_out
        full = params["slice_sizes"][operand_axis] == tenants
        return [operand_axis if full else _MIXED] * n_out
    if prim == "dynamic_update_slice":
        operand_axis, update_axis = in_a[0], in_a[1]
        if any(a is not None for a in in_a[2:]):
            return [_MIXED] * n_out
        if _MIXED in (operand_axis, update_axis):
            return [_MIXED] * n_out
        if operand_axis is None and update_axis is None:
            return [None] * n_out
        if operand_axis == update_axis:
            return [operand_axis] * n_out
        return [_MIXED] * n_out
    if prim == "gather":
        return [_axis_gather(eqn, in_a, fallbacks)] * n_out
    if prim.startswith("scatter"):
        return [_axis_scatter(eqn, in_a)] * n_out
    if prim == "cond":
        branch_axes = [
            _axis_closed(b, list(in_a[1:]), tenants, fallbacks)
            for b in params["branches"]
        ]
        return [
            _unify_axes([bo[i] for bo in branch_axes if i < len(bo)])
            for i in range(n_out)
        ]
    if prim == "while":
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        carry = list(in_a[cn + bn:])
        # The loop PREDICATE reduces over all tenants by design (vmap
        # lockstep: iterate while ANY tenant still runs) — a mixed pred is
        # the batching rule's own semantics, not a data leak, so it is
        # deliberately not joined into the carries.
        while True:
            body = _axis_closed(params["body_jaxpr"], in_a[cn:cn + bn] + carry,
                                tenants, fallbacks)
            merged = [_unify_axes([c, b]) for c, b in zip(carry, body)]
            if merged == carry:
                return carry
            carry = merged
    if prim == "scan":
        nc, nk = params["num_consts"], params["num_carry"]
        carry = list(in_a[nc:nc + nk])
        xs = list(in_a[nc + nk:])
        while True:
            outs = _axis_closed(params["jaxpr"], in_a[:nc] + carry + xs,
                                tenants, fallbacks)
            merged = [_unify_axes([c, o]) for c, o in zip(carry, outs[:nk])]
            if merged == carry:
                return carry + list(outs[nk:])
            carry = merged
    sub = _sub_jaxpr(params)
    if sub is not None:
        return _axis_closed(sub, list(in_a), tenants, fallbacks)
    fallbacks.append(prim)
    return [_MIXED] * n_out


def _axis_gather(eqn: Any, in_a: List[Any], fallbacks: List[str]) -> Any:
    """A gather is tenant-safe only as the BATCHED per-tenant gather vmap
    produces: the tenant dims of operand and indices are declared as
    batching dims, which pins every lookup inside its own tenant block.
    Any other gather touching a tenant-indexed operand is a potential
    cross-tenant read -> mixed."""
    operand_axis, indices_axis = in_a[0], in_a[1]
    if operand_axis is None and indices_axis is None:
        return None
    if _MIXED in (operand_axis, indices_axis):
        return _MIXED
    dnums = eqn.params["dimension_numbers"]
    op_batch = tuple(getattr(dnums, "operand_batching_dims", ()) or ())
    idx_batch = tuple(getattr(dnums, "start_indices_batching_dims", ()) or ())
    if operand_axis is not None and operand_axis in op_batch:
        # Batched per-tenant gather (vmap may stack further batch dims —
        # the fleet's [tenant, ring] gathers batch both): the tenant dim
        # must pair with the indices' tenant dim, and it surfaces in the
        # output at the slot its indices batch dim maps to (indices batch
        # dims fill the non-offset output positions in order).
        pos = list(op_batch).index(operand_axis)
        paired = list(idx_batch)[pos] if pos < len(idx_batch) else None
        if paired is not None and (indices_axis is None or indices_axis == paired):
            out_ndim = eqn.outvars[0].aval.ndim
            offset = set(dnums.offset_dims)
            batch_slots = [p for p in range(out_ndim) if p not in offset]
            if paired < len(batch_slots):
                return batch_slots[paired]
        return _MIXED
    if operand_axis is not None and indices_axis is None and not op_batch:
        # Uniform indices selecting along NON-tenant dims, with the tenant
        # dim carried whole inside every slice: the same per-tenant rows
        # come out for every tenant — no cross-tenant read. The tenant dim
        # lands at the offset_dims slot its (non-collapsed) operand rank
        # maps to.
        d = operand_axis
        collapsed = tuple(dnums.collapsed_slice_dims)
        if (
            d not in dnums.start_index_map
            and d not in collapsed
            and eqn.params["slice_sizes"][d] == eqn.invars[0].aval.shape[d]
        ):
            surviving = [
                dim for dim in range(eqn.invars[0].aval.ndim)
                if dim not in collapsed
            ]
            return tuple(dnums.offset_dims)[surviving.index(d)]
    return _MIXED


def _axis_scatter(eqn: Any, in_a: List[Any]) -> Any:
    """Tenant-safe only as the batched per-tenant scatter vmap produces:
    every non-uniform input tracks the same tenant dim, declared as a
    batching dim on both the operand and the indices — each tenant's
    updates then land inside its own batch slice. A uniform operand is
    fine (scattering per-tenant data into a shared zero buffer); the
    output keeps the tenant dim at the operand's batching position."""
    if all(a is None for a in in_a):
        return None
    if _MIXED in in_a:
        return _MIXED
    dnums = eqn.params["dimension_numbers"]
    op_batch = tuple(getattr(dnums, "operand_batching_dims", ()) or ())
    idx_batch = tuple(getattr(dnums, "scatter_indices_batching_dims", ()) or ())
    operand_axis, indices_axis = in_a[0], in_a[1]
    axes = {a for a in in_a if a is not None}
    if len(axes) == 1:
        d = axes.pop()
        if (
            (operand_axis is None or operand_axis == d)
            and d in op_batch
            and (indices_axis is None or d in idx_batch)
        ):
            return d
    return _MIXED


# ---------------------------------------------------------------------------
# entrypoint tracing
# ---------------------------------------------------------------------------


def _trace_entry(name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
    traced = spec["jit"].trace(*spec["args"])
    closed = traced.jaxpr
    in_labels = _lane_labels(spec["args"], "in")
    out_labels = _lane_labels(traced.out_info, "out")
    n_in, n_out = len(closed.jaxpr.invars), len(closed.jaxpr.outvars)
    if len(in_labels) != n_in or len(out_labels) != n_out:
        raise RuntimeError(
            f"{name}: lane labels do not align with the jaxpr "
            f"({len(in_labels)}/{n_in} inputs, {len(out_labels)}/{n_out} "
            f"outputs) — the labeler must mirror jax flatten order"
        )
    return {
        "name": name,
        "closed": closed,
        "in_labels": in_labels,
        "out_labels": out_labels,
    }


def _registry_with_fleet() -> Dict[str, Dict[str, Any]]:
    """The device-program registry plus the MESHLESS vmapped fleet step at
    the audit geometry — the cross-tenant proof must also cover the
    non-GSPMD tenancy path (what single-host deployments run)."""
    from . import device_program

    registry = dict(device_program._build_registry())
    if "fleet_step" not in registry:
        registry["fleet_step"] = device_program.build_ladder_spec(
            "fleet_step",
            device_program.AUDIT_N,
            device_program.AUDIT_K,
            device_program.AUDIT_C,
            tenants=device_program.AUDIT_TENANTS,
        )
    return registry


def _tenant_in_axes(entry: Dict[str, Any], spec: Dict[str, Any],
                    tenants: int) -> List[Any]:
    import jax

    axes: List[Any] = []
    for leaf in jax.tree_util.tree_leaves(spec["args"]):
        shape = getattr(leaf, "shape", ())
        axes.append(0 if (len(shape) >= 1 and shape[0] == tenants) else None)
    if len(axes) != len(entry["in_labels"]):
        raise RuntimeError(
            f"{entry['name']}: tenant axis assignment does not align with "
            f"the flattened arguments"
        )
    return axes


# ---------------------------------------------------------------------------
# proof checks
# ---------------------------------------------------------------------------


def observer_effect_findings(
    entry: Dict[str, Any], out_taints: List[FrozenSet[int]],
    loc: Tuple[str, int],
) -> List[Finding]:
    path, lineno = loc
    findings = []
    labels = entry["in_labels"]
    for out_label, taint in zip(entry["out_labels"], out_taints):
        if not _is_subject_lane(out_label):
            continue
        leaks = sorted(labels[i] for i in taint if _is_observer_lane(labels[i]))
        if leaks:
            findings.append(Finding(
                path, lineno, "dataflow-observer-effect",
                f"{entry['name']}: observer lane(s) {', '.join(leaks)} "
                f"influence subject lane {out_label} — telemetry and the "
                f"trace ring must be write-only planes; an observer that "
                f"perturbs the engine invalidates every trace it records",
            ))
    return findings


def cross_tenant_findings(
    entry: Dict[str, Any], out_axes: List[Any], fallbacks: List[str],
    loc: Tuple[str, int],
) -> List[Finding]:
    path, lineno = loc
    findings = []
    for out_label, axis in zip(entry["out_labels"], out_axes):
        if axis == _MIXED:
            findings.append(Finding(
                path, lineno, "dataflow-cross-tenant",
                f"{entry['name']}: output lane {out_label} mixes tenants — "
                f"an influence edge crosses the fleet's tenant axis"
                + (
                    f" (conservatively, via unhandled primitive(s) "
                    f"{', '.join(sorted(set(fallbacks)))})"
                    if fallbacks else ""
                ),
            ))
    return findings


def _opportunity_map(
    entry: Dict[str, Any], prov: "_Provenance", quiescent_rows: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Join the dense gated jaxpr equations against the quiescent
    entrypoint's collective rows on (location, source). A bucket is
    CLAIMED when at least one gated dense op shares its (location,
    source) — those payload bytes are provably maskable and belong on
    ROADMAP item 3's work-list; the rest stay listed as unclaimed."""
    buckets: Dict[Tuple[str, str], int] = {}
    for row in quiescent_rows:
        key = (row["location"], row["source"])
        buckets[key] = buckets.get(key, 0) + int(row["bytes"])
    total = sum(buckets.values())

    ops_by_key: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for rec in prov.dense_records:
        if not rec["gated"]:
            continue
        ops_by_key.setdefault((rec["location"], rec["source"]), []).append(rec)

    entries: List[Dict[str, Any]] = []
    unclaimed: List[Dict[str, Any]] = []
    claimed_bytes = 0
    for (location, source), nbytes in sorted(buckets.items()):
        ops = ops_by_key.get((location, source), [])
        if ops:
            claimed_bytes += nbytes
            grouped: Dict[Tuple[str, str], Dict[str, Any]] = {}
            for rec in ops:
                gkey = (rec["prim"], rec["scope"])
                slot = grouped.setdefault(gkey, {
                    "prim": rec["prim"], "scope": rec["scope"], "count": 0,
                    "gated_by": set(),
                })
                slot["count"] += 1
                slot["gated_by"].update(rec["gated_by"])
            entries.append({
                "location": location,
                "source": source,
                "bytes": nbytes,
                "share_pct": round(100.0 * nbytes / total, 2) if total else 0.0,
                "dense_ops": [
                    {
                        "prim": g["prim"], "scope": g["scope"],
                        "count": g["count"],
                        "gated_by": sorted(g["gated_by"]),
                    }
                    for _, g in sorted(grouped.items())
                ],
            })
        else:
            unclaimed.append({
                "location": location, "source": source, "bytes": nbytes,
            })
    coverage = (claimed_bytes / total) if total else 0.0
    return {
        "entrypoint": entry["name"],
        "total_collective_payload_bytes": total,
        "claimed_bytes": claimed_bytes,
        "coverage_pct": round(100.0 * coverage, 2),
        "dense_gated": entries,
        "unclaimed": unclaimed,
    }


def _carry_only_lanes(entries: List[Dict[str, Any]],
                      influence: Dict[str, Dict[str, List[str]]]) -> List[str]:
    """State lanes written by some entrypoint but unreachable (through
    the transitive step relation) from any non-state output — the jaxpr
    side of the dead-lane check. A carry-only lane is NOT yet dead: the
    full state pytree is a program output, so the host may fetch the lane
    directly (config_id reads config_hi/config_lo; the admission path
    reads retired). The finding fires only when the tree-wide reference
    scan (the deadcode family's collector — attribute reads, getattr
    strings, f-string fields) cannot find the lane consumed by name
    anywhere either; that join is what keeps the two liveness families
    from ever disagreeing."""
    written: set = set()
    edges: Dict[str, set] = {}
    live_now: set = set()
    for entry in entries:
        rel = influence[entry["name"]]
        for out_label, in_labels in rel.items():
            if out_label.startswith("state."):
                field = out_label
                if in_labels != [out_label]:
                    written.add(field)
                for src in in_labels:
                    if src.startswith("state."):
                        edges.setdefault(src, set()).add(field)
            else:
                for src in in_labels:
                    if src.startswith("state."):
                        live_now.add(src)
    live = set(live_now)
    frontier = list(live_now)
    reverse: Dict[str, set] = {}
    for src, dsts in edges.items():
        for dst in dsts:
            reverse.setdefault(dst, set()).add(src)
    while frontier:
        lane = frontier.pop()
        for src in reverse.get(lane, ()):
            if src not in live:
                live.add(src)
                frontier.append(src)
    return sorted(written - live)


# ---------------------------------------------------------------------------
# collection + lock
# ---------------------------------------------------------------------------

_DATAFLOW_CACHE: Optional[Tuple[Dict[str, Any], List[Finding], bool]] = None


def collect_dataflow(
    force: bool = False, require_mesh: bool = True,
) -> Tuple[Dict[str, Any], List[Finding]]:
    """Trace the full registry, run every proof, and build the lock
    payload. Cached per session like the HLO facts/cost ladder (the trace
    itself is compile-free; the byte-pricing join reuses the session's
    collect_facts cache). Raises RuntimeError without the 8-device mesh
    when ``require_mesh`` — a partial registry must never be frozen or
    compared against the committed lock."""
    global _DATAFLOW_CACHE
    import jax

    from . import device_program

    have_mesh = jax.device_count() >= device_program.AUDIT_DEVICES
    if require_mesh and not have_mesh:
        raise RuntimeError(
            f"dataflow audit needs {device_program.AUDIT_DEVICES} devices, "
            f"have {jax.device_count()} — force them before jax initializes "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{device_program.AUDIT_DEVICES})"
        )
    if _DATAFLOW_CACHE is not None and not force and _DATAFLOW_CACHE[2] == have_mesh:
        return _DATAFLOW_CACHE[0], _DATAFLOW_CACHE[1]

    payload, findings = _build_payload(have_mesh)
    _DATAFLOW_CACHE = (payload, findings, have_mesh)
    return payload, findings


def _build_payload(have_mesh: bool) -> Tuple[Dict[str, Any], List[Finding]]:
    from . import device_program

    loc = (DATAFLOW_LOCK_REL, 1)
    registry = _registry_with_fleet()
    findings: List[Finding] = []
    entries: List[Dict[str, Any]] = []
    influence: Dict[str, Dict[str, List[str]]] = {}
    eqn_counts: Dict[str, Dict[str, int]] = {}
    observer_silent: Dict[str, bool] = {}
    tenant_axes_out: Dict[str, Dict[str, Any]] = {}
    provenances: Dict[str, "_Provenance"] = {}

    tenants = device_program.AUDIT_TENANTS
    for name in sorted(registry):
        spec = registry[name]
        entry = _trace_entry(name, spec)
        entries.append(entry)
        n_in = len(entry["in_labels"])
        in_taints = [frozenset([i]) for i in range(n_in)]
        out_taints = _taint_closed(entry["closed"], in_taints)
        labels = entry["in_labels"]
        influence[name] = {
            out_label: sorted(labels[i] for i in taint)
            for out_label, taint in zip(entry["out_labels"], out_taints)
        }
        obs = observer_effect_findings(entry, out_taints, loc)
        findings.extend(obs)
        observer_silent[name] = not obs

        prov = _Provenance(labels, device_program.AUDIT_N)
        prov.run(entry["closed"], in_taints)
        provenances[name] = prov
        eqn_counts[name] = dict(sorted(prov.location_counts.items()))

        if name.startswith("fleet"):
            in_axes = _tenant_in_axes(entry, spec, tenants)
            fallbacks: List[str] = []
            out_axes = _axis_closed(entry["closed"], in_axes, tenants, fallbacks)
            findings.extend(cross_tenant_findings(entry, out_axes, fallbacks, loc))
            tenant_axes_out[name] = {
                "proven": not any(a == _MIXED for a in out_axes),
                "mixed_outputs": sorted(
                    lbl for lbl, a in zip(entry["out_labels"], out_axes)
                    if a == _MIXED
                ),
                "axis_rule_fallbacks": sorted(set(fallbacks)),
            }

    # Sparse-opportunity map: priced against the quiescent entrypoint's
    # live collective rows, cross-checked against the cost lock's frozen
    # total (the two-lock coupling).
    opportunity: Dict[str, Any] = {
        "entrypoint": "sharded_step",
        "status": "unavailable: no 8-device mesh",
    }
    if have_mesh:
        facts = device_program.collect_facts(require_mesh=True)
        fact_entry = facts.get("sharded_step")
        if fact_entry is not None and "sharded_step" in provenances:
            entry_obj = next(e for e in entries if e["name"] == "sharded_step")
            opportunity = _opportunity_map(
                entry_obj, provenances["sharded_step"], fact_entry["rows"]
            )
            findings.extend(_coverage_findings(opportunity, loc))

    carry_only = _carry_only_lanes(entries, influence)
    referenced = _tree_reference_names()
    for lane in carry_only:
        if _field_of(lane) not in referenced:
            findings.append(Finding(
                loc[0], loc[1], "dataflow-dead-lane",
                f"state lane {lane} is written by the engine but "
                f"influences no output or fetched digest in any "
                f"registered entrypoint, and no host code references it "
                f"by name — dead weight in the donated state buffers",
            ))

    payload = {
        "_comment": (
            "Lane-level dataflow provenance of every registered "
            "device_program entrypoint, traced (compile-free) from the "
            "closed jaxpr: the lane->lane influence relation, "
            "per-location equation counts, the observer-silence and "
            "tenant-isolation proofs, and the sparse-opportunity map "
            "(dense mask-gated round-body ops priced against the "
            "cost.lock.json quiescent payload bytes) that ROADMAP item "
            "3's sparse restructure consumes as its work-list. Generated "
            "by `python tools/staticcheck.py --update-dataflow-lock`; do "
            "not edit by hand — any drift from the live trace fails the "
            "staticcheck gate."
        ),
        "entrypoints": {
            e["name"]: {
                "influence": influence[e["name"]],
                "eqn_locations": eqn_counts[e["name"]],
                "observer_silent": observer_silent[e["name"]],
            }
            for e in entries
        },
        "tenant_isolation": tenant_axes_out,
        "opportunity_map": opportunity,
        "carry_only_lanes": carry_only,
    }
    return payload, findings


def _coverage_findings(opportunity: Dict[str, Any],
                       loc: Tuple[str, int]) -> List[Finding]:
    """The map must EXPLAIN the frozen quiescent bytes: >= 90% of the
    payload attributed to provably mask-gated dense ops, and the live
    join total must agree with the cost lock's frozen
    quiescent_round_cost (two locks, one artifact)."""
    path, lineno = loc
    findings = []
    from .cost_model import COST_LOCK_REL

    cost_lock = core.REPO / COST_LOCK_REL
    if cost_lock.exists():
        try:
            frozen = json.loads(cost_lock.read_text())
            frozen_bytes = frozen.get("quiescent_round_cost", {}).get(
                "collective_payload_bytes"
            )
        except json.JSONDecodeError:
            frozen_bytes = None
        live_total = opportunity.get("total_collective_payload_bytes")
        if frozen_bytes is not None and live_total != frozen_bytes:
            findings.append(Finding(
                path, lineno, "dataflow-dense-op",
                f"sparse-opportunity join total ({live_total} B) does not "
                f"match the cost lock's frozen quiescent "
                f"collective_payload_bytes ({frozen_bytes} B) — refreeze "
                f"the cost lock first, then this one",
            ))
    coverage = opportunity.get("coverage_pct", 0.0)
    if coverage < 90.0:
        unclaimed = opportunity.get("unclaimed", [])
        detail = ", ".join(
            f"{u['location']}/{u['source']} ({u['bytes']} B)" for u in unclaimed
        )
        findings.append(Finding(
            path, lineno, "dataflow-dense-op",
            f"sparse-opportunity map explains only {coverage}% of the "
            f"quiescent payload bytes (floor 90%) — unclaimed buckets: "
            f"{detail or 'none'}; a dense op whose bytes the map cannot "
            f"attribute to a mask gate is not provably sparsifiable",
        ))
    return findings


def _tree_reference_names() -> set:
    """Every identifier the analyzed tree consumes, per the deadcode
    family's reference collector (attribute reads, getattr-string
    arguments, f-string field fragments) — the host-side 'fetched'
    evidence the jaxpr cannot see. Parsed fresh from disk (cheap next to
    the trace) so tree-less callers — the lock updater, bench — get the
    same answer as the driver's tree mode."""
    from . import deadcode

    names: set = set()
    for path in core.iter_files():
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except (SyntaxError, OSError):
            continue
        names |= deadcode._collect_references(tree)
    return names


def check_dataflow_lock(trees: Sequence[Tuple[ast.AST, str]]) -> List[Finding]:
    """Tree-mode gate: trace the registry (session-cached), run the
    proofs, and compare against the committed lock. Presence-gated on the
    engine sources exactly like the HLO/cost gates, so retargeted test
    trees never pay a trace."""
    from . import device_program

    rels = {rel.replace("\\", "/") for _, rel in trees}
    if not all(src in rels for src in device_program.REGISTRY_SOURCES):
        return []
    try:
        payload, findings = collect_dataflow()
    except RuntimeError as exc:
        return [Finding(DATAFLOW_LOCK_REL, 1, "dataflow-lock-drift",
                        f"cannot trace the registry: {exc}")]
    findings = list(findings)
    lock_path = core.REPO / DATAFLOW_LOCK_REL
    if not lock_path.exists():
        findings.append(Finding(
            DATAFLOW_LOCK_REL, 1, "dataflow-lock-drift",
            "dataflow lockfile missing — generate it via "
            "`python tools/staticcheck.py --update-dataflow-lock`",
        ))
        return findings
    try:
        locked = json.loads(lock_path.read_text())
    except json.JSONDecodeError as exc:
        findings.append(Finding(
            DATAFLOW_LOCK_REL, 1, "dataflow-lock-drift",
            f"dataflow lockfile is not valid JSON ({exc.msg}) — "
            f"regenerate via `python tools/staticcheck.py "
            f"--update-dataflow-lock`",
        ))
        return findings
    live = _canonical(payload)
    committed = _canonical(locked)
    for key in sorted(set(live) | set(committed)):
        if live.get(key) != committed.get(key):
            findings.append(Finding(
                DATAFLOW_LOCK_REL, 1, "dataflow-lock-drift",
                f"{key!r} block drifted from the committed dataflow lock "
                f"— {_REGEN_HINT}",
            ))
    return findings


def _canonical(payload: Dict[str, Any]) -> Dict[str, Any]:
    """JSON round-trip (tuples -> lists, key ordering) minus the prose
    comment, so live and committed payloads compare structurally."""
    slim = {k: v for k, v in payload.items() if k != "_comment"}
    return json.loads(json.dumps(slim, sort_keys=True))


def update_dataflow_lock() -> Tuple[List[Finding], Optional[Path]]:
    """Regenerate the dataflow lockfile from a fresh trace. Refuses while
    ANY proof fails — an observer leak, a cross-tenant edge, a dead lane,
    or an opportunity map that stops explaining the quiescent bytes must
    be fixed, never frozen. Byte-identical when nothing changed (the
    trace and the joins are pure deterministic walks)."""
    try:
        payload, findings = collect_dataflow(force=True)
    except RuntimeError as exc:
        return [Finding(DATAFLOW_LOCK_REL, 1, "dataflow-lock-drift",
                        str(exc))], None
    if findings:
        return (
            [Finding(f.path, f.lineno, f.check,
                     f"refusing to freeze: {f.message}")
             for f in findings],
            None,
        )
    lock_path = core.REPO / DATAFLOW_LOCK_REL
    lock_path.write_text(
        json.dumps(payload, indent=2, sort_keys=True) + "\n"
    )
    return [], lock_path


# ---------------------------------------------------------------------------
# per-file corpus mode
# ---------------------------------------------------------------------------


def check_dataflow(
    path: Path, source: Optional[str] = None, tree: Optional[ast.AST] = None,
) -> List[Finding]:
    """Corpus/per-file mode: execute a module that declares
    ``DATAFLOW_AUDIT_PROGRAMS`` (name -> {"build": zero-arg callable
    returning a registry-shaped spec, "checks": subset of
    ("observer-effect", "cross-tenant", "dense-op"), optional
    "tenants"/"dense_n"}) and run the requested proofs over each traced
    program. Findings anchor at the program's dict-key line, mirroring
    the cost-model corpus convention. Files without the marker are
    skipped — this family's tree mode runs against the real registry."""
    rel = _rel(path)
    if source is None:
        try:
            source = path.read_text()
        except OSError:
            return []
    if "DATAFLOW_AUDIT_PROGRAMS" not in source:
        return []
    if tree is None:
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError:
            return []
    linenos = _program_key_linenos(tree)
    namespace: Dict[str, Any] = {"__name__": f"_dataflow_corpus_{path.stem}"}
    try:
        exec(compile(source, str(path), "exec"), namespace)  # noqa: S102
    except Exception as exc:  # noqa: BLE001 — a broken probe is a finding
        return [Finding(rel, 1, "dataflow-lock-drift",
                        f"dataflow audit module failed to execute: {exc!r}")]
    programs = namespace.get("DATAFLOW_AUDIT_PROGRAMS")
    if not isinstance(programs, dict):
        return []
    findings: List[Finding] = []
    for name in sorted(programs):
        cfg = programs[name]
        lineno = linenos.get(name, 1)
        loc = (rel, lineno)
        try:
            spec = cfg["build"]()
            entry = _trace_entry(name, spec)
        except Exception as exc:  # noqa: BLE001
            findings.append(Finding(
                rel, lineno, "dataflow-lock-drift",
                f"{name}: audit program failed to trace: {exc!r}"))
            continue
        checks = tuple(cfg.get("checks", ()))
        n_in = len(entry["in_labels"])
        in_taints = [frozenset([i]) for i in range(n_in)]
        if "observer-effect" in checks:
            out_taints = _taint_closed(entry["closed"], in_taints)
            findings.extend(observer_effect_findings(entry, out_taints, loc))
        if "cross-tenant" in checks:
            tenants = int(cfg.get("tenants", 0))
            in_axes = _tenant_in_axes(entry, spec, tenants)
            fallbacks: List[str] = []
            out_axes = _axis_closed(entry["closed"], in_axes, tenants, fallbacks)
            findings.extend(
                cross_tenant_findings(entry, out_axes, fallbacks, loc))
        if "dense-op" in checks:
            prov = _Provenance(entry["in_labels"], int(cfg.get("dense_n", 1)))
            prov.run(entry["closed"], in_taints)
            for rec in prov.dense_records:
                if rec["gated"]:
                    findings.append(Finding(
                        rel, lineno, "dataflow-dense-op",
                        f"{name}: dense {rec['prim']} over {rec['elems']} "
                        f"elements is provably gated by "
                        f"{', '.join(rec['gated_by'])} yet computes over "
                        f"the full lane — a sparse-opportunity candidate",
                    ))
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))


def _rel(path: Path) -> str:
    try:
        return str(Path(path).resolve().relative_to(core.REPO)).replace(
            "\\", "/"
        )
    except ValueError:
        return str(path)


def _program_key_linenos(tree: ast.AST) -> Dict[str, int]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "DATAFLOW_AUDIT_PROGRAMS" in targets and isinstance(
                node.value, ast.Dict
            ):
                return {
                    key.value: key.lineno
                    for key in node.value.keys
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                }
    return {}
