"""Tools-side shim over :mod:`rapid_tpu.parallel.hlo_facts`.

The classifier's canonical home is inside the packaged library (stdlib
only, importable from an installed wheel); the analysis package consumes
it from there so the dependency points tools -> library, never the
reverse. This shim resolves the repo root the way the rest of the
analysis driver does (``core.REPO``, inserted at the FRONT so a foreign
top-level ``rapid_tpu`` can never shadow this repo's) and re-exports the
surface under the name the family modules import.
"""

from __future__ import annotations

import sys

from . import core

_REPO = str(core.REPO)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from rapid_tpu.parallel.hlo_facts import (  # noqa: E402,F401 — re-exported
    COLLECTIVE_KINDS,
    DTYPE_BITS,
    PAYLOAD_CLASS_RANK,
    TRANSFER_OPS,
    audit_collectives,
    classify_location,
    collective_groups,
    collective_violations,
    compiled_cost_analysis,
    count_transfer_ops,
    entry_parameter_bytes,
    groups_cross_blocks,
    input_output_aliases,
    payload_class,
    shape_bytes,
    shape_operand_bytes,
    source_of,
)

__all__ = [
    "COLLECTIVE_KINDS",
    "DTYPE_BITS",
    "PAYLOAD_CLASS_RANK",
    "TRANSFER_OPS",
    "audit_collectives",
    "classify_location",
    "collective_violations",
    "collective_groups",
    "compiled_cost_analysis",
    "count_transfer_ops",
    "entry_parameter_bytes",
    "groups_cross_blocks",
    "input_output_aliases",
    "payload_class",
    "shape_bytes",
    "shape_operand_bytes",
    "source_of",
]
