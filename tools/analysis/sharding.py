"""Check family 13: engine sharding discipline (source-level lint).

The compiled-artifact gate (family 12, ``device_program``) catches what XLA
actually emitted; this family catches the source patterns that PRODUCE bad
compiled programs, over ``rapid_tpu/ops/``, ``rapid_tpu/models/``, and
``rapid_tpu/parallel/``:

- ``missing-partition-spec`` — every array leaf of the engine state pytree
  (``EngineState``/``FaultInputs`` in models/state.py) must be covered by
  ``parallel/mesh.py``'s partition declarations. Two declaration styles are
  understood: the regex rule table (``PARTITION_RULES`` — the current
  engine style: every leaf must fullmatch a rule, a rule matching no leaf
  is a dead entry, and a rule whose spec names no mesh axis must justify
  the replication with ``# replicated-ok: <reason>`` on its spec line) and
  the legacy explicit constructor table (``state_shardings`` /
  ``fault_shardings`` keyword-per-leaf — same leaf coverage + justified
  ``sh()`` discipline). An uncovered leaf silently replicates [n]- or
  [c,n]-scale state onto every device. Since the cohort axis became a real
  mesh axis (the 2-D ``('cohort', 'nodes')`` mesh), any surviving
  ``cohort axis is not meshed`` replication justification is itself a
  finding — the annotation's premise is false.
- ``host-sync-in-hot-path`` — ``jax.device_get`` / ``.block_until_ready()``
  / ``.item()`` / ``float(...)`` / ``np.asarray(...)`` inside the traced
  convergence seams (jitted functions, the ``*_impl`` engine convention,
  and callables handed to ``lax.while_loop``/``lax.cond``/``lax.scan``):
  each is a device->host round trip the fused-dispatch design exists to
  avoid. Escape hatch ``# host-sync-ok: <reason>``.
- ``host-sync-in-stream`` — the streaming-pipeline sibling of the check
  above, over ``rapid_tpu/serving/``: a blocking read
  (``block_until_ready`` — method or ``jax.block_until_ready`` —,
  ``.item()``, ``jax.device_get``, ``np.asarray``, and the scalar-fetch
  casts ``int(jnp...)``/``float(jnp...)`` over resolvable jax calls)
  ANYWHERE in the pipeline module body stalls every enqueued wave behind
  it, so each one must be an explicit fetch boundary justified with
  ``# host-sync-ok: <reason>``. Unlike the hot-path check this one is not
  limited to traced functions: the stream driver's whole value is that
  its HOST code never blocks outside declared boundaries.
- ``donation-mismatch`` — a ``jax.jit`` application whose wrapped callable
  takes the engine ``state`` pytree but whose ``donate_argnums`` does not
  cover it: the long-running driver loop then holds two copies of the
  state between steps. Deliberate non-donating variants carry
  ``# donate-ok: <reason>``.
- ``retrace-hazard`` — a bare Python numeric literal passed in a traced
  position of a same-file jitted entrypoint: the first such call traces
  with ``weak_type=True``, a later ``jnp.int32(...)``-wrapped call traces
  again — one silent recompile per spelling. Wrap the constant
  (``jnp.int32(x)``) or pin the parameter static. Escape hatch
  ``# retrace-ok: <reason>``.
- ``dtype-widening`` — inline arithmetic stored back into a
  policy-NARROWED engine lane (``models/state.NARROWABLE_LANES`` — int8/
  int16/uint8 under the compact policy) without an explicit cast: jnp
  type promotion silently re-widens the whole lane to int32/uint32 the
  moment a wide operand touches the expression, un-doing the compaction
  byte-for-byte while every test keeps passing (wide mode compiles
  identically). Convicts a ``_replace(...)``/state-constructor keyword
  for a narrowed lane whose value contains a BinOp not wrapped in an
  ``.astype(...)``; name-only stores pass (the round body's convention:
  compute, cast, bind, store the name). Escape hatch
  ``# widen-ok: <reason>``.

Resolution is conservative (skip-don't-guess), matching the rest of the
package: only same-file jit applications are resolved, only direct
parameter/keyword shapes convict.

``check_sharding`` is the per-file entry (prefix-gated; the lint corpus
keeps miniature state+table pairs in one module);
``check_partition_specs`` is the tree-mode entry that merges the real
state.py/mesh.py pair on full sweeps.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import core
from .core import Finding
from .trace_safety import _dotted, _import_aliases, _jitted_functions

SHARDING_PREFIXES = (
    "rapid_tpu/ops/",
    "rapid_tpu/models/",
    "rapid_tpu/parallel/",
    "rapid_tpu/tenancy/",
)

#: The streaming-pipeline prefix: every blocking read here must be a
#: justified fetch boundary (``host-sync-in-stream``), not just the ones
#: inside traced functions.
STREAM_PREFIXES = ("rapid_tpu/serving/",)

#: The real files the tree-mode partition-spec check merges.
STATE_FILE = "rapid_tpu/models/state.py"
MESH_FILE = "rapid_tpu/parallel/mesh.py"

#: State-pytree classes and the sharding-table functions that must cover
#: their array leaves, by name (the engine convention).
_PYTREE_TABLES = {
    "EngineState": "state_shardings",
    "FaultInputs": "fault_shardings",
    "TenantKnobs": "knob_shardings",
    "TelemetryLanes": "telemetry_shardings",
    "TraceRing": "trace_shardings",
}

_LAX_LOOP_FNS = frozenset({
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.scan", "lax.scan",
    "jax.lax.fori_loop", "lax.fori_loop",
})

_HOST_SYNC_METHODS = frozenset({"block_until_ready", "item"})


def _comment_ok(source_lines: List[str], lineno: int, marker: str) -> bool:
    if 1 <= lineno <= len(source_lines):
        return marker in source_lines[lineno - 1]
    return False


# -- host-sync-in-hot-path / host-sync-in-stream -----------------------------


def _blocking_read(node: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """The blocking-read spelling of a call node — the one classifier both
    host-sync checks share, so the two can never disagree about what counts
    as a device->host sync. None = not a blocking read."""
    dotted = _dotted(node.func, aliases)
    if dotted == "jax.device_get":
        return "jax.device_get"
    if dotted == "jax.block_until_ready":
        return "jax.block_until_ready(...)"
    if dotted in ("numpy.asarray", "np.asarray", "numpy.array", "np.array"):
        # Both spellings materialize a device array on host (np.array just
        # also copies); classifying only asarray would leave np.array as a
        # silent undeclared-sync spelling.
        return f"{dotted} (implicit device fetch)"
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _HOST_SYNC_METHODS
    ):
        return f".{node.func.attr}()"
    return None


def _traced_functions(tree: ast.AST, aliases: Dict[str, str]) -> List[ast.AST]:
    """Every function node the engine traces: jit-applied (trace_safety's
    resolution), ``*_impl``-named (the repo's traced-impl convention), and
    callables handed to the lax control-flow combinators."""
    traced: Dict[int, ast.AST] = {}
    for fn, _static in _jitted_functions(tree, aliases):
        traced[id(fn)] = fn
    by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name[node.name] = node
            if node.name.endswith("_impl"):
                traced[id(node)] = node
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _dotted(node.func, aliases) in _LAX_LOOP_FNS):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                traced[id(arg)] = arg
            elif isinstance(arg, ast.Name) and arg.id in by_name:
                fn = by_name[arg.id]
                traced[id(fn)] = fn
    return list(traced.values())


def _check_host_sync(
    tree: ast.AST,
    aliases: Dict[str, str],
    rel: str,
    source_lines: List[str],
    findings: List[Finding],
) -> None:
    seen: Set[int] = set()
    for fn in _traced_functions(tree, aliases):
        label = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            what = _blocking_read(node, aliases)
            if what is None and (
                isinstance(node.func, ast.Name)
                and node.func.id == "float"
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                what = "float(...) (scalar fetch)"
            if what is None:
                continue
            seen.add(id(node))
            if _comment_ok(source_lines, node.lineno, "# host-sync-ok:"):
                continue
            findings.append(Finding(
                rel, node.lineno, "host-sync-in-hot-path",
                f"{what} inside traced {label!r}: a device->host sync in "
                f"the convergence hot path — keep the value on device "
                f"(jnp ops / lax.cond), or justify with "
                f"`# host-sync-ok: <reason>`",
            ))


def _cast_of_device_value(
    node: ast.Call, aliases: Dict[str, str]
) -> Optional[str]:
    """The scalar-fetch cast spelling: ``int(...)``/``float(...)`` whose
    argument computes through a ``jax.*``/``jax.numpy.*`` call — e.g.
    ``int(jnp.sum(state.config_epoch))``, the drain-fetch spelling the
    pipeline itself uses. Casts of host values (numpy rng draws, plain
    attributes) pass: an AST pass cannot know a bare name holds a device
    array, so this branch is precise on the calls it CAN resolve rather
    than noisy on everything."""
    if not (
        isinstance(node.func, ast.Name)
        and node.func.id in ("int", "float")
        and node.args
    ):
        return None
    for sub in ast.walk(node.args[0]):
        if isinstance(sub, ast.Call):
            dotted = _dotted(sub.func, aliases) or ""
            if dotted.startswith(("jax.", "jnp.")):
                return f"{node.func.id}({dotted}(...)) (scalar fetch)"
    return None


def _check_stream_host_sync(
    tree: ast.AST,
    aliases: Dict[str, str],
    rel: str,
    source_lines: List[str],
    findings: List[Finding],
) -> None:
    """The streaming-pipeline variant: every blocking-read spelling in a
    serving module is a pipeline stall (JAX async dispatch only overlaps
    host work with device compute while the host never blocks), so each one
    must be a declared fetch boundary — hatch ``# host-sync-ok: <reason>``
    — not just the ones inside traced functions. Covers the shared
    classifier's spellings plus the scalar-fetch casts over resolvable
    jax/jnp calls (:func:`_cast_of_device_value`)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _blocking_read(node, aliases) or _cast_of_device_value(
            node, aliases
        )
        if what is None:
            continue
        if _comment_ok(source_lines, node.lineno, "# host-sync-ok:"):
            continue
        findings.append(Finding(
            rel, node.lineno, "host-sync-in-stream",
            f"{what} in the streaming pipeline: a blocking read here "
            f"stalls every enqueued wave behind it — keep the pipeline "
            f"fetch-free (enqueue-only dispatches, device-resident "
            f"tickets), or declare the fetch boundary with "
            f"`# host-sync-ok: <reason>`",
        ))


# -- donation-mismatch -------------------------------------------------------


def _callable_params(
    target: ast.AST, by_name: Dict[str, ast.AST]
) -> Optional[List[str]]:
    """Positional parameter names of a jit-wrapped callable: a same-file
    def referenced by name, or an inline lambda. None = unresolvable."""
    if isinstance(target, ast.Lambda):
        return [a.arg for a in (*target.args.posonlyargs, *target.args.args)]
    if isinstance(target, ast.Name) and target.id in by_name:
        fn = by_name[target.id]
        return [a.arg for a in (*fn.args.posonlyargs, *fn.args.args)]
    return None


def _int_tuple(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """A donate_argnums/static_argnums value as ints; None = unresolvable
    (dynamic spec: skip, don't guess). Missing keyword -> empty tuple is
    the CALLER's choice (pass a Constant sentinel)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _str_tuple(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    """A *_argnames value as strings; None = unresolvable, () = absent."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _jit_keyword(call: ast.Call, name: str) -> Optional[ast.AST]:
    return next((kw.value for kw in call.keywords if kw.arg == name), None)


def _check_donation(
    tree: ast.AST,
    aliases: Dict[str, str],
    rel: str,
    source_lines: List[str],
    findings: List[Finding],
) -> None:
    by_name = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _dotted(node.func, aliases) == "jax.jit"):
            continue
        if not node.args:
            continue
        params = _callable_params(node.args[0], by_name)
        if params is None or "state" not in params:
            continue
        state_idx = params.index("state")
        donate = _int_tuple(_jit_keyword(node, "donate_argnums"))
        donate_names = _str_tuple(_jit_keyword(node, "donate_argnames"))
        if donate is None or donate_names is None:
            continue  # dynamic spec: skip, don't guess
        if state_idx in donate or "state" in donate_names:
            continue
        if _comment_ok(source_lines, node.lineno, "# donate-ok:"):
            continue
        findings.append(Finding(
            rel, node.lineno, "donation-mismatch",
            f"jax.jit application does not donate the engine state pytree "
            f"(param 'state' at index {state_idx}, donate_argnums="
            f"{donate}): the driver loop holds two state copies between "
            f"steps — add donate_argnums=({state_idx},) or justify with "
            f"`# donate-ok: <reason>`",
        ))


# -- retrace-hazard ----------------------------------------------------------


def _jitted_bindings(
    tree: ast.AST, aliases: Dict[str, str], by_name: Dict[str, ast.AST]
) -> Dict[str, Tuple[int, Tuple[int, ...]]]:
    """Module-level ``name = jax.jit(fn, ...)`` bindings: name ->
    (positional arity of the wrapped callable, static argnums). Only
    statically-resolvable specs are included."""
    out: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and _dotted(node.value.func, aliases) == "jax.jit"
            and node.value.args
        ):
            continue
        params = _callable_params(node.value.args[0], by_name)
        if params is None:
            continue
        static = _int_tuple(_jit_keyword(node.value, "static_argnums"))
        static_names = _str_tuple(_jit_keyword(node.value, "static_argnames"))
        if static is None or static_names is None:
            continue  # dynamic spec: skip, don't guess
        # static_argnames pins by NAME; jax maps positional calls onto the
        # named parameters, so a bare literal at that position never
        # retraces — resolve the names to indices and merge.
        name_idx = tuple(
            params.index(n) for n in static_names if n in params
        )
        out[node.targets[0].id] = (len(params), tuple(set(static) | set(name_idx)))
    return out


def _check_retrace(
    tree: ast.AST,
    aliases: Dict[str, str],
    rel: str,
    source_lines: List[str],
    findings: List[Finding],
) -> None:
    by_name = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    jitted = _jitted_bindings(tree, aliases, by_name)
    if not jitted:
        return
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in jitted
        ):
            continue
        _arity, static = jitted[node.func.id]
        for idx, arg in enumerate(node.args):
            if idx in static:
                continue
            if not (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float))
                and not isinstance(arg.value, bool)
            ):
                continue
            if _comment_ok(source_lines, arg.lineno, "# retrace-ok:"):
                continue
            findings.append(Finding(
                rel, arg.lineno, "retrace-hazard",
                f"bare Python literal {arg.value!r} passed in traced "
                f"position {idx} of jitted {node.func.id!r}: mixing bare "
                f"and wrapped spellings retraces per weak-type — wrap it "
                f"(jnp.int32({arg.value!r})) or pin the parameter in "
                f"static_argnums/static_argnames",
            ))


# -- dtype-widening ----------------------------------------------------------

#: The engine lanes the compact policy stores below 32 bits — the LITERAL
#: mirror of ``rapid_tpu/models/state.NARROWABLE_LANES`` (the analysis
#: package imports no jax-bearing library module; the two sets are pinned
#: equal by tests/test_state_compaction.py so they cannot drift).
NARROWED_LANES = frozenset({
    "ring_perm", "obs_idx", "subj_idx", "inval_obs", "cohort_of",
    "fd_count", "fd_hist", "fire_round", "report_bits",
    "cp_rnd_r", "cp_rnd_i", "cp_vrnd_r", "cp_vrnd_i", "cp_vval_src",
    "classic_epoch", "rounds_undecided",
})

#: Call shapes whose keywords are lane STORES: the NamedTuple ``_replace``
#: method and the state-pytree constructors themselves.
_STORE_CONSTRUCTORS = frozenset({"EngineState", "FaultInputs"})


def _binop_outside_astype(node: ast.AST, inside: bool = False) -> bool:
    """True when the expression contains a BinOp not enclosed by an
    ``.astype(...)`` call — arithmetic whose result dtype is promotion's
    choice, not the lane's. Comparisons and boolean ops are excluded (they
    produce bools, which no narrowed lane stores)."""
    if isinstance(node, ast.Call) and (
        isinstance(node.func, ast.Attribute) and node.func.attr == "astype"
    ):
        inside = True
    if isinstance(node, ast.BinOp) and not inside:
        return True
    return any(
        _binop_outside_astype(child, inside) for child in ast.iter_child_nodes(node)
    )


def _check_dtype_widening(
    tree: ast.AST,
    rel: str,
    source_lines: List[str],
    findings: List[Finding],
) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        is_replace = (
            isinstance(node.func, ast.Attribute) and node.func.attr == "_replace"
        )
        is_ctor = (
            isinstance(node.func, ast.Name)
            and node.func.id in _STORE_CONSTRUCTORS
        )
        if not (is_replace or is_ctor):
            continue
        for kw in node.keywords:
            if kw.arg not in NARROWED_LANES:
                continue
            if not _binop_outside_astype(kw.value):
                continue
            if _comment_ok(source_lines, kw.value.lineno, "# widen-ok:"):
                continue
            findings.append(Finding(
                rel, kw.value.lineno, "dtype-widening",
                f"arithmetic stored into policy-narrowed lane {kw.arg!r} "
                f"without an explicit cast: jnp type promotion re-widens "
                f"the lane to 32 bits the moment a wide operand appears — "
                f"accumulate in int32 and `.astype(...)` the store (or "
                f"justify with `# widen-ok: <reason>`)",
            ))


# -- missing-partition-spec --------------------------------------------------

#: The regex rule table's module-level name (parallel/mesh.py).
RULES_NAME = "PARTITION_RULES"

#: The tenant batch axis (rapid_tpu/parallel/mesh.TENANT_AXIS): a pytree
#: leaf whose shape annotation declares a leading ``[t`` dimension is a
#: TENANT-STACKED leaf, and its rule must shard dimension 0 on this axis —
#: an unmeshed tenant dimension replicates every tenant's state onto every
#: tenant's devices, the exact failure mode the fleet mesh exists to
#: prevent.
TENANT_AXIS_NAME = "tenant"
_TENANT_SHAPE_RE = re.compile(r"#\s*\[t[\],]")

#: A replication justification whose premise died with the 1-D mesh: the
#: cohort axis IS meshed now, so any surviving instance is a finding.
STALE_REPLICATION_REASON = "cohort axis is not meshed"


def _partition_rules(tree: ast.AST) -> Optional[Tuple[int, List[Dict[str, Any]]]]:
    """The module-level ``PARTITION_RULES`` tuple literal, parsed to
    (assignment lineno, [{pattern, meshed_axes, lineno, spec_lineno}]).
    None when the module declares no rule table. Only statically-resolvable
    (pattern-Constant, spec-Tuple) rules are kept — skip, don't guess."""
    for node in tree.body:
        value = None
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == RULES_NAME
        ):
            value = node.value
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == RULES_NAME
        ):
            value = node.value
        if not isinstance(value, ast.Tuple):
            continue
        rules: List[Dict[str, Any]] = []
        for elt in value.elts:
            if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 2):
                continue
            pat, spec = elt.elts
            if not (isinstance(pat, ast.Constant) and isinstance(pat.value, str)):
                continue
            if not isinstance(spec, ast.Tuple):
                continue  # a computed spec: skip, don't guess
            meshed = sum(
                1
                for a in spec.elts
                if not (isinstance(a, ast.Constant) and a.value is None)
            )

            def _is_tenant_axis(node: ast.AST) -> bool:
                if isinstance(node, ast.Name):
                    return node.id == "TENANT_AXIS"
                return (
                    isinstance(node, ast.Constant)
                    and node.value == TENANT_AXIS_NAME
                )

            rules.append({
                "pattern": pat.value,
                "meshed_axes": meshed,
                "dim0_tenant": bool(spec.elts) and _is_tenant_axis(spec.elts[0]),
                "lineno": pat.lineno,
                "spec_lineno": spec.lineno,
            })
        return node.lineno, rules
    return None


def _stale_annotation_findings(rel: str, source_lines: List[str]) -> List[Finding]:
    return [
        Finding(
            rel, lineno, "missing-partition-spec",
            f"stale replication justification {STALE_REPLICATION_REASON!r}: "
            f"the cohort axis IS a mesh axis (2-D ('cohort', 'nodes') mesh) "
            f"— shard the leaf over it or state the real reason",
        )
        for lineno, line in enumerate(source_lines, 1)
        if STALE_REPLICATION_REASON in line
    ]


def _tenant_leaves(tree: ast.AST, source_lines: List[str]) -> Set[str]:
    """Field names of the module's state-pytree classes whose shape
    annotation comment declares a LEADING tenant dimension (``# [t]`` /
    ``# [t, ...]``) — the leaves the tenant-axis rule discipline covers."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name in _PYTREE_TABLES):
            continue
        for stmt in node.body:
            if not (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ):
                continue
            if 1 <= stmt.lineno <= len(source_lines) and _TENANT_SHAPE_RE.search(
                source_lines[stmt.lineno - 1]
            ):
                out.add(stmt.target.id)
    return out


def _rule_findings(
    fields_by_class: Dict[str, List[str]],
    assign_lineno: int,
    rules: List[Dict[str, Any]],
    rel: str,
    source_lines: List[str],
    tenant_leaves: Optional[Set[str]] = None,
) -> List[Finding]:
    """Coverage of the engine pytree leaves by the regex rule table: every
    leaf fullmatches a rule (first match wins, mirroring
    ``mesh.match_partition_rules``), no rule is dead, and a rule that
    replicates (names no mesh axis) justifies itself on its spec line."""
    findings: List[Finding] = []
    compiled: List[Optional["re.Pattern"]] = []
    for rule in rules:
        try:
            compiled.append(re.compile(rule["pattern"]))
        except re.error as exc:
            compiled.append(None)
            findings.append(Finding(
                rel, rule["lineno"], "missing-partition-spec",
                f"{RULES_NAME} rule {rule['pattern']!r} is not a valid "
                f"regex ({exc}) — it can cover nothing",
            ))
    all_fields = sorted({f for fields in fields_by_class.values() for f in fields})
    matched_fields: Dict[int, List[str]] = {}
    for field in all_fields:
        hit = None
        for idx, pattern in enumerate(compiled):
            if pattern is not None and pattern.fullmatch(field):
                hit = idx
                break
        if hit is None:
            findings.append(Finding(
                rel, assign_lineno, "missing-partition-spec",
                f"engine pytree leaf {field!r} matches no rule in "
                f"{RULES_NAME} — an uncovered leaf silently replicates "
                f"onto every device",
            ))
        else:
            matched_fields.setdefault(hit, []).append(field)
    for idx, rule in enumerate(rules):
        if compiled[idx] is None:
            continue
        fields = matched_fields.get(idx, [])
        if not fields:
            findings.append(Finding(
                rel, rule["lineno"], "missing-partition-spec",
                f"{RULES_NAME} rule {rule['pattern']!r} matches no engine "
                f"pytree leaf — dead table entry",
            ))
        elif rule["meshed_axes"] == 0 and not _comment_ok(
            source_lines, rule["spec_lineno"], "# replicated-ok:"
        ):
            findings.append(Finding(
                rel, rule["spec_lineno"], "missing-partition-spec",
                f"{RULES_NAME} rule {rule['pattern']!r} fully replicates "
                f"leaves {fields} without a `# replicated-ok: <reason>` "
                f"justification",
            ))
        stacked = sorted(set(fields) & (tenant_leaves or set()))
        if stacked and not rule["dim0_tenant"]:
            findings.append(Finding(
                rel, rule["spec_lineno"], "missing-partition-spec",
                f"{RULES_NAME} rule {rule['pattern']!r} covers "
                f"tenant-stacked leaves {stacked} ([t, ...] shape "
                f"annotation) but does not shard dimension 0 on the "
                f"'{TENANT_AXIS_NAME}' axis — an unmeshed tenant dimension "
                f"replicates every tenant's state onto every tenant's "
                f"devices",
            ))
    findings.extend(_stale_annotation_findings(rel, source_lines))
    return findings


def _pytree_array_fields(tree: ast.AST) -> Dict[str, List[str]]:
    """Array-leaf field names of each state-pytree NamedTuple present in
    the module (annotation mentions ``ndarray``)."""
    out: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name in _PYTREE_TABLES):
            continue
        fields = []
        for stmt in node.body:
            if not (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ):
                continue
            ann = ast.dump(stmt.annotation)
            if "ndarray" in ann or "Array" in ann:
                fields.append(stmt.target.id)
        if fields:
            out[node.name] = fields
    return out


def _table_constructor_calls(
    tree: ast.AST,
) -> Dict[str, Tuple[ast.Call, int]]:
    """class name -> (the pytree constructor Call inside its sharding-table
    function, the function's lineno)."""
    out: Dict[str, Tuple[ast.Call, int]] = {}
    fn_for = {fn: cls for cls, fn in _PYTREE_TABLES.items()}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and node.name in fn_for):
            continue
        cls = fn_for[node.name]
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == cls
                and sub.keywords
            ):
                out[cls] = (sub, node.lineno)
                break
    return out


def _partition_spec_findings(
    fields_by_class: Dict[str, List[str]],
    tables_tree: ast.AST,
    tables_rel: str,
    tables_source: str,
) -> List[Finding]:
    findings: List[Finding] = []
    source_lines = tables_source.splitlines()
    calls = _table_constructor_calls(tables_tree)
    for cls, fields in sorted(fields_by_class.items()):
        if cls not in calls:
            continue  # presence-gated: no table for this pytree here
        call, fn_lineno = calls[cls]
        declared = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        table_fn = _PYTREE_TABLES[cls]
        for field in fields:
            if field not in declared:
                findings.append(Finding(
                    tables_rel, call.lineno, "missing-partition-spec",
                    f"{cls} array leaf {field!r} has no declared "
                    f"PartitionSpec in {table_fn}() — an undeclared leaf "
                    f"silently replicates onto every device",
                ))
                continue
            value = declared[field]
            if not (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "sh"
            ):
                continue  # a non-sh() spec: skip, don't guess
            has_axis = any(
                not (isinstance(a, ast.Constant) and a.value is None)
                for a in value.args
            )
            if not has_axis and not _comment_ok(
                source_lines, value.lineno, "# replicated-ok:"
            ):
                findings.append(Finding(
                    tables_rel, value.lineno, "missing-partition-spec",
                    f"{cls} leaf {field!r} is declared fully replicated "
                    f"(sh() with no axes) without a `# replicated-ok: "
                    f"<reason>` justification",
                ))
        for kw in call.keywords:
            if kw.arg and kw.arg not in fields:
                findings.append(Finding(
                    tables_rel, kw.value.lineno, "missing-partition-spec",
                    f"{table_fn}() declares a spec for {kw.arg!r}, which is "
                    f"not an array leaf of {cls} — dead table entry",
                ))
    findings.extend(_stale_annotation_findings(tables_rel, source_lines))
    return findings


# -- entry points ------------------------------------------------------------


def check_sharding(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Per-file sharding lint (prefix-gated). The partition-spec section
    runs only when the file holds BOTH a state pytree and its sharding
    table (the corpus miniatures); the real split pair is merged by the
    tree-mode check."""
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    is_stream = any(posix.startswith(p) for p in STREAM_PREFIXES)
    if not is_stream and not any(posix.startswith(p) for p in SHARDING_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    aliases = _import_aliases(tree)
    source_lines = src.splitlines()
    findings: List[Finding] = []
    if is_stream:
        # Serving modules get the strict whole-module discipline (every
        # blocking read is a declared boundary) and none of the jit-seam
        # checks — the pipeline is host code in front of already-audited
        # compiled entrypoints.
        _check_stream_host_sync(tree, aliases, rel, source_lines, findings)
        return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))
    _check_host_sync(tree, aliases, rel, source_lines, findings)
    _check_donation(tree, aliases, rel, source_lines, findings)
    _check_retrace(tree, aliases, rel, source_lines, findings)
    _check_dtype_widening(tree, rel, source_lines, findings)
    fields = _pytree_array_fields(tree)
    rules = _partition_rules(tree)
    if fields and rules is not None:
        findings.extend(_rule_findings(
            fields, rules[0], rules[1], rel, source_lines,
            tenant_leaves=_tenant_leaves(tree, source_lines),
        ))
    elif fields and _table_constructor_calls(tree):
        findings.extend(_partition_spec_findings(fields, tree, rel, src))
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))


def check_partition_specs(
    trees: Sequence[Tuple[ast.AST, str]]
) -> List[Finding]:
    """Tree-mode entry: merge the real state.py/mesh.py pair. Presence-
    gated on both files being part of the sweep (tests retargeting
    ``core.REPO`` at temporary trees skip silently)."""
    state_tree = mesh_tree = None
    for tree, rel in trees:
        posix = rel.replace("\\", "/")
        if posix == STATE_FILE:
            state_tree = tree
        elif posix == MESH_FILE:
            mesh_tree = tree
    if state_tree is None or mesh_tree is None:
        return []
    fields = _pytree_array_fields(state_tree)
    if not fields:
        return []
    mesh_path = core.REPO / MESH_FILE
    mesh_source = mesh_path.read_text()
    rules = _partition_rules(mesh_tree)
    if rules is not None:
        state_source = (core.REPO / STATE_FILE).read_text()
        return _rule_findings(
            fields, rules[0], rules[1], MESH_FILE, mesh_source.splitlines(),
            tenant_leaves=_tenant_leaves(state_tree, state_source.splitlines()),
        )
    return _partition_spec_findings(fields, mesh_tree, MESH_FILE, mesh_source)
