"""Check family 1: undefined names (symtable scope resolution).

Compiler-grade scope analysis via ``symtable``: every name a scope reads
through the global scope must be bound at module level (import/assign/def/
class), declared ``global`` and assigned in some function, or a builtin.
Catches typos in rarely-executed paths (the error branch that NameErrors
only when the error happens), which no test-coverage gate can promise to
reach.
"""

from __future__ import annotations

import ast
import builtins
import symtable
from pathlib import Path
from typing import List, Optional

from . import core
from .core import Finding

# Module-scope dunders the compiler binds implicitly.
_IMPLICIT_GLOBALS = {
    "__name__", "__file__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__path__", "__dict__", "__class__",
}


def _global_assigned_names(table: symtable.SymbolTable) -> set:
    """Names any nested scope both declares ``global`` and assigns — those
    are module-bound at runtime even if never assigned at module scope."""
    names = set()
    for sym in table.get_symbols():
        if sym.is_global() and sym.is_assigned():
            names.add(sym.get_name())
    for child in table.get_children():
        names |= _global_assigned_names(child)
    return names


def _undefined_in_table(
    table: symtable.SymbolTable,
    bound: set,
    rel: str,
    load_lines: dict,
    findings: List[Finding],
) -> None:
    for sym in table.get_symbols():
        if not (sym.is_global() and sym.is_referenced()):
            continue
        name = sym.get_name()
        if name in bound or hasattr(builtins, name) or name in _IMPLICIT_GLOBALS:
            continue
        # Point at the offending READ, not the enclosing def: the first
        # load site at or after the scope's start line (falling back to the
        # first in the file — scope start is a lower bound, good enough to
        # land inside the right function).
        scope_start = table.get_lineno()
        lines = load_lines.get(name, [])
        lineno = next((ln for ln in lines if ln >= scope_start),
                      lines[0] if lines else scope_start)
        findings.append(
            Finding(
                rel,
                lineno,
                "undefined-name",
                f"{name!r} (read in {table.get_type()} "
                f"{table.get_name()!r}) is bound nowhere at module scope "
                "and is not a builtin",
            )
        )
    for child in table.get_children():
        _undefined_in_table(child, bound, rel, load_lines, findings)


def check_undefined_names(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Every name resolving through the global scope must exist there."""
    src = source if source is not None else path.read_text()
    rel = core.rel(path)
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            # A star import makes the global namespace statically unknowable;
            # flag the import itself rather than silently skipping the file.
            return [
                Finding(rel, node.lineno, "star-import",
                        "wildcard import defeats scope analysis")
            ]
    table = symtable.symtable(src, str(path), "exec")
    bound = {s.get_name() for s in table.get_symbols() if s.is_local()}
    bound |= _global_assigned_names(table)
    load_lines: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            load_lines.setdefault(node.id, []).append(node.lineno)
    for lines in load_lines.values():
        lines.sort()
    findings: List[Finding] = []
    _undefined_in_table(table, bound, rel, load_lines, findings)
    return findings
