"""Check family 3: clock-injection discipline.

No direct wall-clock reads in the timing-sensitive packages: every timing
consumer in ``rapid_tpu/protocol/`` AND ``rapid_tpu/monitoring/`` (failure
detectors are timing consumers too) AND ``rapid_tpu/serving/`` (the
supervision tier's deadline/backoff decisions must replay under an injected
clock — a wall-clock read in the wedge-detection path would make every
fault drill nondeterministic) must go through the injected Clock
(utils/clock.py) / Metrics ``now_ms`` source, or simulated-time tests
silently measure wall time (and phase SLO histograms record garbage under
ManualClock).

Caught spellings: attribute access on the ``time`` module (``time.time``,
``time.time_ns``, ``time.monotonic``, ...), ``from time import
perf_counter``-style imports, and the datetime spellings
``datetime.datetime.now(...)`` / ``datetime.now(...)`` (the latter for
``from datetime import datetime``). A deliberate exception carries a
``# wall-clock-ok: <reason>`` comment on the offending line.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional

from . import core
from .core import Finding

#: Wall-clock readers banned inside the clock-disciplined packages.
_BANNED_CLOCK_ATTRS = frozenset(
    {"time", "time_ns", "perf_counter", "perf_counter_ns",
     "monotonic", "monotonic_ns"}
)

#: The trees this discipline applies to (posix-style relative prefixes).
CLOCK_DISCIPLINE_PREFIXES = (
    "rapid_tpu/protocol/",
    "rapid_tpu/monitoring/",
    "rapid_tpu/serving/",
)

_ALLOW_RE = re.compile(r"#\s*wall-clock-ok\b")

_GUIDANCE = "use the injected Clock / Metrics now_ms source"


def _is_datetime_now(node: ast.Attribute) -> bool:
    """``datetime.now`` (from-import spelling) or ``datetime.datetime.now``."""
    if node.attr != "now":
        return False
    value = node.value
    if isinstance(value, ast.Name) and value.id == "datetime":
        return True
    return (
        isinstance(value, ast.Attribute)
        and value.attr == "datetime"
        and isinstance(value.value, ast.Name)
        and value.value.id == "datetime"
    )


def check_clock_injection(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in CLOCK_DISCIPLINE_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()

    def allowed(lineno: int) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return bool(_ALLOW_RE.search(line))

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "time"
                and node.attr in _BANNED_CLOCK_ATTRS
            ):
                if not allowed(node.lineno):
                    findings.append(
                        Finding(rel, node.lineno, "clock-injection",
                                f"direct wall-clock read time.{node.attr} in a "
                                f"clock-disciplined package — {_GUIDANCE}")
                    )
            elif _is_datetime_now(node):
                if not allowed(node.lineno):
                    findings.append(
                        Finding(rel, node.lineno, "clock-injection",
                                "direct wall-clock read datetime.now in a "
                                f"clock-disciplined package — {_GUIDANCE}")
                    )
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            banned = [a.name for a in node.names if a.name in _BANNED_CLOCK_ATTRS]
            if banned and not allowed(node.lineno):
                findings.append(
                    Finding(rel, node.lineno, "clock-injection",
                            f"importing {', '.join(banned)} from time in a "
                            f"clock-disciplined package — {_GUIDANCE}")
                )
    return findings
