"""Check family 14: chaos vocabulary discipline.

The chaos subsystem indexes on ONE closed vocabulary three ways: fault
event kinds (``rapid_tpu/sim/faults.py`` ``ALL_KINDS``), scenario family
names (``rapid_tpu/sim/fuzz.py`` ``FAMILIES``), and the CLI surface
(``tools/chaosrun.py`` ``run <family>`` choices, plus the tenancy fleet's
``ENGINE_FAMILIES``/``HIER_FAMILIES`` mix tables). A string that drifts in
any one of them used to fail at the worst possible moment — mid-scenario,
inside a fuzz round, as a raw KeyError. ``FaultEvent.__post_init__`` now
raises at construction (the runtime half, pinned in test_sim_faults); this
family is the static half:

- ``chaos-unknown-kind`` — a ``FaultEvent("<literal>", ...)`` construction
  whose kind is not in the registered ``ALL_KINDS``. Deliberate negative
  fixtures carry ``# chaos-kind-ok: <reason>`` on the line.
- ``chaos-family-drift`` — the registries disagree: a ``FAMILIES`` table
  key that does not match the generator function it maps to (the (name,
  function) pair is the replay contract — repro files and CLI args carry
  the KEY); an ``ENGINE_FAMILIES``/``HIER_FAMILIES``/``FLEET_FAMILIES``
  entry naming a family the fuzz registry does not export; or a
  ``chaosrun`` family argument whose ``choices=`` is not wired to the
  ``FAMILIES`` registry itself (a re-typed list would drift silently).

Applied only to files that touch the chaos surface (import
``rapid_tpu.sim.faults``/``fuzz``, or define one of the tables), so
unrelated ``FaultEvent`` classes elsewhere are never touched. The kind and
family vocabularies come from the runtime modules themselves — the same
never-drift rule as the ledger family's ``STAGE_NAMES`` import.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional

from . import core
from .core import Finding

#: Deliberate unknown-kind fixtures (e.g. the construction-raises pin in
#: test_sim_faults.py) opt out per line, reason required by convention.
_KIND_OK_RE = re.compile(r"#\s*chaos-kind-ok\b")

#: Trees the discipline applies to (chaos schedules are minted here).
_CHAOS_PREFIXES = ("rapid_tpu/", "tools/", "tests/", "examples/", "bench.py")

#: Family-mix tables whose entries must exist in the fuzz registry.
_MIX_TABLES = ("ENGINE_FAMILIES", "HIER_FAMILIES", "FLEET_FAMILIES")


def _imports_chaos(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and (
                node.module.endswith("sim.faults")
                or node.module.endswith("sim.fuzz")
                or node.module.endswith("sim")
            ):
                return True
        elif isinstance(node, ast.Import):
            if any(
                a.name.endswith("sim.faults") or a.name.endswith("sim.fuzz")
                for a in node.names
            ):
                return True
    return False


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _kind_literal(node: ast.Call) -> Optional[ast.Constant]:
    arg = node.args[0] if node.args else next(
        (kw.value for kw in node.keywords if kw.arg == "kind"), None
    )
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg
    return None


def _check_event_kinds(
    rel: str, src_lines: List[str], tree: ast.AST
) -> List[Finding]:
    from rapid_tpu.sim.faults import ALL_KINDS

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and _callee_name(node.func) == "FaultEvent"
        ):
            continue
        arg = _kind_literal(node)
        if arg is None or arg.value in ALL_KINDS:
            continue
        line = (
            src_lines[node.lineno - 1] if node.lineno <= len(src_lines) else ""
        )
        if _KIND_OK_RE.search(line):
            continue
        findings.append(Finding(
            rel, node.lineno, "chaos-unknown-kind",
            f"FaultEvent kind {arg.value!r} is not in the registered "
            "vocabulary (rapid_tpu/sim/faults.py ALL_KINDS); construction "
            "will raise ScheduleError at runtime",
        ))
    return findings


def _check_families_table(rel: str, tree: ast.AST) -> List[Finding]:
    """The ``FAMILIES = {"name": function, ...}`` registry: every key must
    match its generator function's name — the key is what repro files,
    ``chaosrun run``, and the fleet mix tables carry, and a renamed
    generator left under a stale key replays a DIFFERENT scenario than the
    name says."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "FAMILIES" in targets and isinstance(node.value, ast.Dict):
            for key, value in zip(node.value.keys, node.value.values):
                if not (
                    isinstance(key, ast.Constant) and isinstance(key.value, str)
                ):
                    findings.append(Finding(
                        rel, node.lineno, "chaos-family-drift",
                        "FAMILIES keys must be string literals (the "
                        "replayable scenario vocabulary)",
                    ))
                    continue
                fn = value.id if isinstance(value, ast.Name) else None
                if fn is not None and fn != key.value:
                    findings.append(Finding(
                        rel, key.lineno, "chaos-family-drift",
                        f"FAMILIES key {key.value!r} maps to function "
                        f"{fn!r}; the key IS the replay contract — rename "
                        "one to match the other",
                    ))
        for table in set(targets) & set(_MIX_TABLES):
            entries = None
            if isinstance(node.value, (ast.Tuple, ast.List)):
                entries = node.value.elts
            if entries is None:
                continue
            from rapid_tpu.sim.fuzz import FAMILIES as _RUNTIME_FAMILIES

            for elt in entries:
                if (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                    and elt.value not in _RUNTIME_FAMILIES
                ):
                    findings.append(Finding(
                        rel, elt.lineno, "chaos-family-drift",
                        f"{table} entry {elt.value!r} is not a registered "
                        "sim/fuzz.py family; the fleet compiler would "
                        "KeyError on it",
                    ))
    return findings


def _check_cli_choices(rel: str, tree: ast.AST) -> List[Finding]:
    """The ``add_argument("family", ...)`` call must wire ``choices=`` to
    the FAMILIES registry (an attribute or name ending in ``FAMILIES``
    somewhere in the expression) — a hand-maintained list of family names
    is exactly the drift this family exists to prevent."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and _callee_name(node.func) == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value in ("family", "--family")
        ):
            continue
        choices = next(
            (kw.value for kw in node.keywords if kw.arg == "choices"), None
        )
        wired = choices is not None and any(
            (isinstance(sub, ast.Attribute) and sub.attr == "FAMILIES")
            or (isinstance(sub, ast.Name) and sub.id == "FAMILIES")
            for sub in ast.walk(choices)
        )
        if not wired:
            findings.append(Finding(
                rel, node.lineno, "chaos-family-drift",
                "the family CLI argument must take choices= from the "
                "FAMILIES registry (sim/fuzz.py), not a re-typed list — "
                "a typo'd family must error with the real vocabulary",
            ))
    return findings


def check_chaosvocab(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in _CHAOS_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    # Cheap textual pre-gate before any parse/walk: files that never spell
    # a chaos surface cannot produce a finding (the tree sweep visits every
    # file in the prefixes — which is most of the repo).
    if not ("FaultEvent" in src or "FAMILIES" in src):
        return []
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    defines_table = any(
        isinstance(node, ast.Assign)
        and any(
            isinstance(t, ast.Name) and t.id in (("FAMILIES",) + _MIX_TABLES)
            for t in node.targets
        )
        for node in ast.walk(tree)
    )
    if not (_imports_chaos(tree) or defines_table):
        return []
    findings = _check_event_kinds(rel, src.splitlines(), tree)
    findings.extend(_check_families_table(rel, tree))
    findings.extend(_check_cli_choices(rel, tree))
    return findings
