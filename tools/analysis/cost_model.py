"""Check family 16: the scaling-law cost-model gate (cost.lock.json).

The HLO gate (family 12) freezes compiled-program budgets at ONE audit
shape — which means a refactor that silently turns an O(N) payload into
O(N·K) or O(N²) still passes it, because a single shape cannot tell the
classes apart. This family compiles each registered entrypoint across a
small geometry **ladder** (N ∈ {64, 128, 256, 512} at fixed K/C, a K
ladder for the round-body entrypoints, a tenant-count ladder for the
fleet), extracts per-shape facts via ``rapid_tpu/parallel/hlo_facts.py``
(total and largest collective payload bytes, per-device argument/temp/
codegen bytes, transfer ops, and ``compiled.cost_analysis()`` FLOPs /
bytes-accessed where the backend exposes them — None-tolerant, never
guessed), and FITS each fact to a scaling class:

    O(1) < O(log N) < O(N) < O(N*K) < O(N^2)

by non-negative least squares over the nested basis ``{1, log2 N, N,
N·K, N²}`` — smallest class whose model explains every ladder point
within the fact's tolerance wins; if none does, the fit REFUSES
(``cost-unexplained``) rather than guess. Plain log-log slope matching is
deliberately not used: the real facts are affine mixtures (argument bytes
at the audit geometry are exactly ``108 + 253·N + 38·N·K``) whose log-log
slope sits between classes.

Fitted classes + leading coefficients freeze into the committed
``tools/analysis/cost.lock.json`` via ``staticcheck --update-cost-lock``
(refuses while any fit is unexplained, any fact exceeds its ceiling, or
the hlo.lock differentials disagree; regeneration is byte-identical when
nothing changed). Drift fails the gate with named findings:

- ``cost-scaling-regression`` — an entrypoint/fact whose fitted class
  worsened vs the lock (the silent-asymptotics failure this family
  exists to catch);
- ``cost-superlinear`` — any fact exceeding its per-entrypoint ceiling
  (nothing in the round body may exceed O(N*K): Rapid's central claim);
- ``cost-quiescent`` — drift of ``quiescent_round_cost``, the zero-churn
  round's per-round FLOPs and collective payload, frozen next to PR 15's
  ``quiescent_round_activity == 0`` fact so ROADMAP item 3's sparse
  restructure has its artifact-provable before/after predicate;
- ``cost-unexplained`` / ``cost-lock-drift`` — unclassifiable facts and
  ordinary lock staleness.

Ladder compiles are session-cached like the HLO gate's (one collection
per process, shared by the tree sweep, the lock regenerator, the bench's
``hlo_audit`` stage and every test); the base point (N=256, K=4) reuses
``device_program.collect_facts`` outright, and the tenant ladder uses the
MESHLESS vmapped fleet step so no extra GSPMD compiles are paid.

``check_cost_model`` is the per-file mode for the seeded lint corpus: a
module defining ``COST_AUDIT_PROGRAMS`` (name -> builder taking ``n``)
plus an inline ``COST_LOCK`` is compiled across its own miniature ladder
and compared — the corpus way to pin an injected O(N²) payload, finding
by finding.
"""

from __future__ import annotations

import ast
import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import core, device_program
from .core import Finding

#: The committed freeze of the fitted scaling classes, repo-relative.
COST_LOCK_REL = "tools/analysis/cost.lock.json"

#: Scaling-class vocabulary, weakest to strongest. ASCII spellings so the
#: lockfile and findings survive every terminal; prose may write O(N·K).
CLASSES = ("O(1)", "O(log N)", "O(N)", "O(N*K)", "O(N^2)")
CLASS_RANK = {cls: rank for rank, cls in enumerate(CLASSES)}

#: Nothing in the round body may exceed O(N·K) — the paper's per-node
#: O(K) claim priced at the whole-cluster grain. Every registered
#: entrypoint carries this ceiling unless COST_CEILINGS overrides it.
DEFAULT_CEILING = "O(N*K)"
COST_CEILINGS: Dict[str, str] = {}

#: The geometry ladders. BASE_* mirror the HLO gate's audit shapes so the
#: base point reuses the session's ``collect_facts`` compile verbatim.
BASE_N = device_program.AUDIT_N
BASE_K = device_program.AUDIT_K
BASE_C = device_program.AUDIT_C
N_LADDER = (64, 128, 256, 512)
K_LADDER = (2, 4, 8)
TENANT_LADDER = (2, 4, 8)
#: Per-tenant slot count for the fleet ladder: tenant count T maps to
#: N_eff = T * FLEET_TENANT_N, so linearity in tenants fits as O(N) in
#: the shared class vocabulary (the fleet's whole-fleet cost must scale
#: with total slots, never faster).
FLEET_TENANT_N = 64

#: Entrypoints the ladder sweeps and the axes each varies. ``n`` is the
#: N_LADDER at K=BASE_K; ``k`` adds the K_LADDER at N=BASE_N (only the
#: central round-body step pays the extra compiles — every other
#: entrypoint shares its round body, and each ladder compile costs
#: seconds of every tier-1 session); ``tenants`` is the fleet ladder over
#: the meshless vmapped step. The mesh-gated GSPMD entrypoints are
#: deliberately absent (see LADDER_ENTRYPOINTS); their base-shape facts
#: still feed the quiescent cost block.
COST_REGISTRY: Dict[str, Dict[str, Any]] = {
    "step": {"axes": ("n", "k")},
    "run_to_decision": {"axes": ("n",)},
    "run_until_membership": {"axes": ("n",)},
    "sync": {"axes": ("n",)},
    # The compact layout's bytes-per-slot is a STEP function of n (the
    # config-derived min_index_dtype widens int8 -> int16 at n=128), so a
    # ladder spanning dtype regimes would conflate policy steps with
    # scaling — the fit refuses it, correctly. The compact ladder stays
    # inside the int16 regime instead: same 4-point fit power, one regime.
    "step_compact": {"axes": ("n",), "n_ladder": (128, 192, 256, 512)},
    "step_telem": {"axes": ("n",)},
    "step_trace": {"axes": ("n",)},
    "fleet_step": {"axes": ("tenants",)},
}

#: Per-fact fit tolerance (max relative residual). Shape-determined facts
#: are tight: argument bytes and collective payloads follow exactly from
#: the program's shapes, so anything their model cannot explain to 2% is
#: a real mixture term. Scheduler-determined facts (buffer assignment,
#: codegen) legitimately wobble; the analytic cost model's FLOPs /
#: bytes-accessed sit in between.
FACT_TOLERANCES = {
    "collective_payload_bytes": 0.02,
    "collective_largest_payload_bytes": 0.02,
    "argument_bytes": 0.02,
    "transfer_ops": 0.02,
    "temp_bytes": 0.35,
    "generated_code_bytes": 0.35,
    "flops": 0.08,
    "bytes_accessed": 0.15,
}
DEFAULT_TOLERANCE = 0.10

#: Facts whose per-point VALUES freeze into the lock and compare exactly
#: (shape-determined — a byte of drift is a program change); the rest
#: compare class-only (their constants wobble across XLA versions).
EXACT_FACTS = (
    "collective_payload_bytes",
    "collective_largest_payload_bytes",
    "argument_bytes",
    "transfer_ops",
)

#: A fit needs at least this many ladder points, and strictly more points
#: than model bases (an exactly-determined system "fits" anything —
#: overfit is how noise would sneak into a class).
MIN_LADDER_POINTS = 3

#: Relative tolerance for the quiescent FLOPs / bytes-accessed comparison
#: (the analytic cost model's constants wobble a little across XLA
#: versions; payload bytes compare exactly).
QUIESCENT_REL_TOL = 0.10

_REGEN_HINT = (
    "if this scaling change is intentional, regenerate via "
    "`python tools/staticcheck.py --update-cost-lock` and review the diff"
)


# -- the fitter -------------------------------------------------------------


def _basis_1(n: float, k: float) -> float:
    return 1.0


def _basis_log(n: float, k: float) -> float:
    return math.log2(n)


def _basis_n(n: float, k: float) -> float:
    return n


def _basis_nk(n: float, k: float) -> float:
    return n * k


def _basis_n2(n: float, k: float) -> float:
    return n * n


def _model_bases(cls: str, k_varies: bool):
    """The basis columns of one class's candidate model, leading term
    LAST. ``O(N*K)`` is only distinguishable when the ladder varies K —
    with K fixed it degenerates to O(N) and is skipped (the O(N) model
    already covers it; classifying O(N*K) off an N-only ladder would be a
    guess)."""
    if cls == "O(1)":
        return [_basis_1]
    if cls == "O(log N)":
        return [_basis_1, _basis_log]
    if cls == "O(N)":
        return [_basis_1, _basis_n]
    if cls == "O(N*K)":
        if not k_varies:
            return None
        return [_basis_1, _basis_n, _basis_nk]
    if cls == "O(N^2)":
        bases = [_basis_1, _basis_n, _basis_n2]
        if k_varies:
            bases.insert(2, _basis_nk)
        return bases
    raise ValueError(f"unknown scaling class {cls!r}")


def _gauss_solve(a: List[List[float]], b: List[float]) -> Optional[List[float]]:
    """Gaussian elimination with partial pivoting; None when singular.
    Pure python (5x5 at most) so the fit — and therefore the lockfile —
    is bit-deterministic with no numerics dependency."""
    m = len(b)
    a = [row[:] for row in a]
    b = b[:]
    for col in range(m):
        piv = max(range(col, m), key=lambda r: abs(a[r][col]))
        if abs(a[piv][col]) < 1e-12:
            return None
        a[col], a[piv] = a[piv], a[col]
        b[col], b[piv] = b[piv], b[col]
        for r in range(m):
            if r != col and a[r][col] != 0.0:
                f = a[r][col] / a[col][col]
                for cc in range(col, m):
                    a[r][cc] -= f * a[col][cc]
                b[r] -= f * b[col]
    return [b[i] / a[i][i] for i in range(m)]


def _lstsq(cols: List[List[float]], y: List[float]) -> Optional[List[float]]:
    """Least squares over column-max-scaled normal equations (the raw
    columns span 1 .. N², so scaling keeps the 5x5 solve conditioned)."""
    m = len(cols)
    pts = len(y)
    scales = [max((abs(v) for v in col), default=0.0) or 1.0 for col in cols]
    ata = [
        [
            sum(cols[i][p] / scales[i] * cols[j][p] / scales[j] for p in range(pts))
            for j in range(m)
        ]
        for i in range(m)
    ]
    aty = [
        sum(cols[i][p] / scales[i] * y[p] for p in range(pts)) for i in range(m)
    ]
    sol = _gauss_solve(ata, aty)
    if sol is None:
        return None
    return [sol[i] / scales[i] for i in range(m)]


def _nnls(cols: List[List[float]], y: List[float]) -> Optional[List[float]]:
    """Non-negative least squares by iterated dropping of the most
    negative column. Costs can only ADD with scale — a model that needs a
    negative N² coefficient to bend around noise is not evidence of an N²
    term, so negative solutions shed columns until none remain."""
    active = list(range(len(cols)))
    while active:
        coef = _lstsq([cols[j] for j in active], y)
        if coef is None:
            return None
        worst = min(range(len(active)), key=lambda i: coef[i])
        if coef[worst] >= -1e-9:
            out = [0.0] * len(cols)
            for i, j in enumerate(active):
                out[j] = max(coef[i], 0.0)
            return out
        active.pop(worst)
    return [0.0] * len(cols)


def fit_scaling(
    points: Sequence[Tuple[Tuple[float, float], float]], tol: float
) -> Dict[str, Any]:
    """Fit one fact's ladder — ``(((n, k), value), ...)`` — to the
    smallest adequately-fitting scaling class.

    Returns ``{"class", "coeff", "residual"}`` on success (``coeff`` is
    the leading-term coefficient) or ``{"error": ...}`` when the ladder is
    too short or no eligible model explains every point within ``tol``
    (the caller turns that into a ``cost-unexplained`` finding — skip,
    don't guess)."""
    pts = [((float(n), float(k)), float(v)) for (n, k), v in points]
    if len(pts) < MIN_LADDER_POINTS:
        return {
            "error": (
                f"ladder too short to classify ({len(pts)} point(s), "
                f"need {MIN_LADDER_POINTS})"
            )
        }
    if all(v == 0.0 for _, v in pts):
        # A fact that is zero at every shape (e.g. collective payload of a
        # single-device program) is a meaningful frozen fact: O(1), zero.
        return {"class": "O(1)", "coeff": 0.0, "residual": 0.0}
    k_varies = len({k for (_n, k), _ in pts}) > 1
    y = [v for _, v in pts]
    best: Optional[Tuple[str, float]] = None
    for cls in CLASSES:
        bases = _model_bases(cls, k_varies)
        if bases is None or len(pts) < len(bases) + 1:
            continue
        cols = [[b(n, k) for (n, k), _ in pts] for b in bases]
        coef = _nnls(cols, y)
        if coef is None:
            continue
        residual = max(
            abs(sum(c * col[p] for c, col in zip(coef, cols)) - y[p])
            / max(abs(y[p]), 1.0)
            for p in range(len(pts))
        )
        if best is None or residual < best[1]:
            best = (cls, residual)
        if residual <= tol:
            return {"class": cls, "coeff": coef[-1], "residual": residual}
    if best is None:
        return {
            "error": (
                f"no eligible scaling model for {len(pts)} ladder point(s) "
                f"(every candidate needs more points than bases)"
            )
        }
    return {
        "error": (
            f"no scaling class explains the ladder: best candidate "
            f"{best[0]} leaves relative residual {best[1]:.3g} > "
            f"tolerance {tol:g}"
        )
    }


# -- ladder collection ------------------------------------------------------


def ladder_points(name: str) -> List[Dict[str, int]]:
    """The geometry points one entrypoint's ladder sweeps, each with the
    effective scale ``n_eff`` the fit regresses against (for the fleet,
    tenants * FLEET_TENANT_N — total slots across the fleet)."""
    axes = COST_REGISTRY[name]["axes"]
    if "tenants" in axes:
        return [
            {
                "n": FLEET_TENANT_N,
                "k": BASE_K,
                "tenants": t,
                "n_eff": t * FLEET_TENANT_N,
            }
            for t in TENANT_LADDER
        ]
    n_ladder = COST_REGISTRY[name].get("n_ladder", N_LADDER)
    pts = [{"n": n, "k": BASE_K, "n_eff": n} for n in n_ladder]
    if "k" in axes:
        pts.extend(
            {"n": BASE_N, "k": k, "n_eff": BASE_N}
            for k in K_LADDER
            if k != BASE_K
        )
    return pts


def point_key(pt: Dict[str, int]) -> str:
    return f"n{pt['n_eff']}_k{pt['k']}"


def entry_cost_facts(entry: Dict[str, Any]) -> Dict[str, float]:
    """The cost-fact vector of one ``extract_facts`` entry. Facts the
    platform did not expose (no memory analysis, no cost analysis) are
    ABSENT, never guessed — the fit skips a fact unless every ladder
    point carries it."""
    rows = entry["rows"]
    memory = entry.get("memory") or {}
    cost = entry.get("cost") or {}
    facts: Dict[str, float] = {
        # Total payload sums tuple operands (hlo_facts prices a variadic
        # all-reduce by the SUM of its operand bytes), so multi-operand
        # fusion cannot hide growth from the ladder fit; the largest
        # single operand rides alongside.
        "collective_payload_bytes": float(sum(r["bytes"] for r in rows)),
        "collective_largest_payload_bytes": float(
            max((r["largest_operand_bytes"] for r in rows), default=0)
        ),
        "transfer_ops": float(sum(entry["transfers"].values())),
    }
    for key in ("argument_bytes", "temp_bytes", "generated_code_bytes"):
        if key in memory:
            facts[key] = float(memory[key])
    for key in ("flops", "bytes_accessed"):
        if key in cost:
            facts[key] = float(cost[key])
    return facts


#: (table, complete) — session cache, one ladder collection per process.
_LADDER_CACHE: Optional[Tuple[Dict[str, List[Dict[str, Any]]], bool]] = None


def collect_ladder(
    force: bool = False, require_mesh: bool = True
) -> Dict[str, List[Dict[str, Any]]]:
    """Compile the ladder and extract cost facts — once per process.

    Returns ``name -> [{"key", "n_eff", "k", "facts"}, ...]``. The base
    point (N=256, K=4) reuses the session's ``collect_facts`` entry (which
    the HLO gate has usually already paid for); every other point compiles
    fresh via ``build_ladder_spec`` with the persistent compilation cache
    scoped OFF (the deserialized-executable heap corruption the HLO gate
    documents applies to donated ladder compiles too). ``require_mesh``
    propagates to the base collection: the GATE needs the full registry
    (its quiescent block reads the sharded step), observational consumers
    (the bench on a single-chip backend) pass False and take whatever the
    process can build."""
    global _LADDER_CACHE
    import jax

    have_mesh = jax.device_count() >= device_program.AUDIT_DEVICES
    if _LADDER_CACHE is not None and not force:
        table, complete = _LADDER_CACHE
        if complete or not require_mesh:
            return table
    base_facts = device_program.collect_facts(require_mesh=require_mesh)
    table: Dict[str, List[Dict[str, Any]]] = {}
    with device_program._scoped_disable_persistent_cache():
        for name in COST_REGISTRY:
            series: List[Dict[str, Any]] = []
            for pt in ladder_points(name):
                is_base = (
                    "tenants" not in pt
                    and pt["n"] == BASE_N
                    and pt["k"] == BASE_K
                    and name in base_facts
                )
                if is_base:
                    entry = base_facts[name]
                else:
                    spec = device_program.build_ladder_spec(
                        name, pt["n"], pt["k"], BASE_C,
                        tenants=pt.get("tenants"),
                    )
                    compiled, _reasons = device_program._compile_program(spec)
                    entry = device_program.extract_facts(
                        compiled, spec["donated_leaves"], pt["n"], BASE_C
                    )
                series.append({
                    "key": point_key(pt),
                    "n_eff": pt["n_eff"],
                    "k": pt["k"],
                    "facts": entry_cost_facts(entry),
                })
            table[name] = series
    _LADDER_CACHE = (table, have_mesh)
    return table


def collect_quiescent_cost(
    require_mesh: bool = True,
) -> Optional[Dict[str, Any]]:
    """The zero-churn round's compiled cost, read off the SHARDED step at
    the audit shape (the dense-round program the sparse restructure must
    shrink): total and hot-loop collective payload bytes (exact), plus
    FLOPs / bytes-accessed where the backend prices them. None when the
    collection has no sharded step (single-chip observational runs)."""
    facts = device_program.collect_facts(require_mesh=require_mesh)
    entry = facts.get("sharded_step")
    if entry is None:
        return None
    rows = entry["rows"]
    out: Dict[str, Any] = {
        "entrypoint": "sharded_step",
        "collective_payload_bytes": int(sum(r["bytes"] for r in rows)),
        "hot_loop_payload_bytes": int(
            sum(r["bytes"] for r in rows if r["location"].startswith("hot-loop"))
        ),
    }
    cost = entry.get("cost") or {}
    for key in ("flops", "bytes_accessed"):
        if key in cost:
            out[key] = cost[key]
    return out


# -- fitting + lock construction --------------------------------------------


def fit_ladder(
    table: Dict[str, List[Dict[str, Any]]]
) -> Tuple[Dict[str, Dict[str, Dict[str, Any]]], List[Tuple[str, str, str]]]:
    """Fit every (entrypoint, fact) series. Returns ``(fits, refusals)``:
    ``fits[name][fact] = {"class", "coeff", "residual", "points"}`` and
    one ``(name, fact, why)`` per refused fit. A fact absent at any ladder
    point is skipped entirely (None-tolerant — a partially-exposed fact is
    not evidence of anything)."""
    fits: Dict[str, Dict[str, Dict[str, Any]]] = {}
    refusals: List[Tuple[str, str, str]] = []
    for name, series in table.items():
        per: Dict[str, Dict[str, Any]] = {}
        fact_names = sorted({f for pt in series for f in pt["facts"]})
        for fact in fact_names:
            if not all(fact in pt["facts"] for pt in series):
                continue
            fitted = fit_scaling(
                [((pt["n_eff"], pt["k"]), pt["facts"][fact]) for pt in series],
                FACT_TOLERANCES.get(fact, DEFAULT_TOLERANCE),
            )
            if "error" in fitted:
                refusals.append((name, fact, fitted["error"]))
                continue
            fitted["points"] = {
                pt["key"]: _as_number(pt["facts"][fact]) for pt in series
            }
            per[fact] = fitted
        fits[name] = per
    return fits, refusals


def _as_number(value: float):
    return int(value) if float(value).is_integer() else float(value)


def _round_sig(value: float, digits: int) -> float:
    return float(f"{float(value):.{digits}g}")


def ceiling_for(name: str) -> str:
    return COST_CEILINGS.get(name, DEFAULT_CEILING)


def superlinear_findings(
    fits: Dict[str, Dict[str, Dict[str, Any]]], loc: Tuple[str, int]
) -> List[Finding]:
    """One ``cost-superlinear`` per (entrypoint, fact) whose fitted class
    exceeds the entrypoint's ceiling — never freezable (update_cost_lock
    refuses it, like a dropped donation)."""
    path, lineno = loc
    findings = []
    for name in sorted(fits):
        ceiling = ceiling_for(name)
        for fact in sorted(fits[name]):
            fit = fits[name][fact]
            if CLASS_RANK[fit["class"]] > CLASS_RANK[ceiling]:
                findings.append(Finding(
                    path, lineno, "cost-superlinear",
                    f"{name}: {fact} fitted {fit['class']} (leading coeff "
                    f"{_round_sig(fit['coeff'], 4)}) exceeds the "
                    f"entrypoint's {ceiling} ceiling — the round body must "
                    f"never scale past O(N*K); fix the program (this budget "
                    f"cannot be locked in)",
                ))
    return findings


def _ladder_config() -> Dict[str, Any]:
    return {
        "base": {"n": BASE_N, "k": BASE_K, "c": BASE_C},
        "n_ladder": list(N_LADDER),
        "n_ladder_overrides": {
            name: list(spec["n_ladder"])
            for name, spec in sorted(COST_REGISTRY.items())
            if "n_ladder" in spec
        },
        "k_ladder": list(K_LADDER),
        "tenant_ladder": list(TENANT_LADDER),
        "fleet_tenant_n": FLEET_TENANT_N,
        "classes": list(CLASSES),
    }


def fits_to_lock(
    fits: Dict[str, Dict[str, Dict[str, Any]]],
    quiescent: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    """The canonical freeze: per-entrypoint fitted classes + rounded
    leading coefficients (+ exact per-point values for shape-determined
    facts), the ladder geometry, and the quiescent cost block. Fully
    deterministic — same facts regenerate the same bytes."""
    lock: Dict[str, Any] = {
        "ladder_config": _ladder_config(),
        "entrypoints": {},
    }
    for name in sorted(fits):
        block: Dict[str, Any] = {}
        for fact in sorted(fits[name]):
            fit = fits[name][fact]
            entry: Dict[str, Any] = {
                "class": fit["class"],
                "coeff": _round_sig(fit["coeff"], 6),
                "residual": _round_sig(fit["residual"], 3),
            }
            if fact in EXACT_FACTS:
                entry["points"] = dict(sorted(fit["points"].items()))
            block[fact] = entry
        lock["entrypoints"][name] = {
            "ceiling": ceiling_for(name), "facts": block,
        }
    if quiescent is not None:
        lock["quiescent_round_cost"] = dict(quiescent)
    return lock


# -- comparison -------------------------------------------------------------


def compare_fact_fit(
    name: str,
    fact: str,
    fit: Dict[str, Any],
    locked: Dict[str, Any],
    loc: Tuple[str, int],
) -> List[Finding]:
    """Drift report for ONE (entrypoint, fact) fit against its locked
    entry: a class that worsened is a scaling regression by name; a class
    that improved, or exact per-point byte drift at the same class, is
    ordinary lock drift."""
    path, lineno = loc
    findings: List[Finding] = []
    old_cls = locked.get("class")
    if old_cls not in CLASS_RANK:
        findings.append(Finding(
            path, lineno, "cost-lock-drift",
            f"{name}: {fact} carries unknown locked class {old_cls!r} — "
            f"{_REGEN_HINT}",
        ))
        return findings
    new_cls = fit["class"]
    if CLASS_RANK[new_cls] > CLASS_RANK[old_cls]:
        findings.append(Finding(
            path, lineno, "cost-scaling-regression",
            f"{name}: {fact} scaling class WORSENED {old_cls} -> {new_cls} "
            f"(leading coeff {_round_sig(fit['coeff'], 4)}, residual "
            f"{_round_sig(fit['residual'], 3)}) — the compiled artifact "
            f"now grows faster with cluster size than the lock permits",
        ))
        return findings
    if CLASS_RANK[new_cls] < CLASS_RANK[old_cls]:
        findings.append(Finding(
            path, lineno, "cost-lock-drift",
            f"{name}: {fact} scaling class improved {old_cls} -> {new_cls} "
            f"— {_REGEN_HINT}",
        ))
        return findings
    if fact in EXACT_FACTS and "points" in locked:
        cur_pts = fit.get("points", {})
        for key in sorted(set(cur_pts) | set(locked["points"])):
            if cur_pts.get(key) != locked["points"].get(key):
                findings.append(Finding(
                    path, lineno, "cost-lock-drift",
                    f"{name}: {fact} at ladder point {key}: "
                    f"{locked['points'].get(key)} in the lock, "
                    f"{cur_pts.get(key)} now — {_REGEN_HINT}",
                ))
    return findings


def compare_quiescent(
    current: Optional[Dict[str, Any]],
    locked: Dict[str, Any],
    lock_path: str,
) -> List[Finding]:
    """Drift report for the ``quiescent_round_cost`` block. Payload bytes
    compare exactly; FLOPs / bytes-accessed under QUIESCENT_REL_TOL and
    presence-gated (a backend that stops pricing them is not drift)."""
    findings: List[Finding] = []
    if current is None:
        return findings
    for key in ("collective_payload_bytes", "hot_loop_payload_bytes"):
        if locked.get(key) != current.get(key):
            findings.append(Finding(
                lock_path, 1, "cost-quiescent",
                f"quiescent_round_cost: {key} {locked.get(key)} in the "
                f"lock, {current.get(key)} now — the zero-churn round's "
                f"collective payload moved; {_REGEN_HINT}",
            ))
    for key in ("flops", "bytes_accessed"):
        if key in locked and key in current:
            old, new = float(locked[key]), float(current[key])
            if abs(new - old) > QUIESCENT_REL_TOL * max(abs(old), 1.0):
                findings.append(Finding(
                    lock_path, 1, "cost-quiescent",
                    f"quiescent_round_cost: {key} drifted beyond "
                    f"{QUIESCENT_REL_TOL:.0%}: {old} in the lock, {new} "
                    f"now — {_REGEN_HINT}",
                ))
    return findings


def compare_cost_lock(
    fits: Dict[str, Dict[str, Dict[str, Any]]],
    quiescent: Optional[Dict[str, Any]],
    locked: Dict[str, Any],
    lock_path: str,
) -> List[Finding]:
    findings: List[Finding] = []
    locked_eps: Dict[str, Any] = locked.get("entrypoints", {})
    for name in sorted(set(fits) | set(locked_eps)):
        if name not in locked_eps:
            findings.append(Finding(
                lock_path, 1, "cost-lock-drift",
                f"entrypoint {name} fitted but has no entry in the cost "
                f"lock — {_REGEN_HINT}",
            ))
            continue
        if name not in fits:
            findings.append(Finding(
                lock_path, 1, "cost-lock-drift",
                f"entrypoint {name} is in the cost lock but no longer "
                f"cost-registered — {_REGEN_HINT}",
            ))
            continue
        locked_facts = locked_eps[name].get("facts", {})
        for fact in sorted(fits[name]):
            if fact not in locked_facts:
                findings.append(Finding(
                    lock_path, 1, "cost-lock-drift",
                    f"{name}: fact {fact} fitted but absent from the cost "
                    f"lock — {_REGEN_HINT}",
                ))
                continue
            findings.extend(compare_fact_fit(
                name, fact, fits[name][fact], locked_facts[fact],
                (lock_path, 1),
            ))
        # A locked fact the platform no longer exposes is skipped, not
        # drift (None-tolerant both ways: locks are generated where the
        # backend prices flops; a leaner backend must still gate what it
        # CAN measure).
    if "quiescent_round_cost" not in locked:
        findings.append(Finding(
            lock_path, 1, "cost-lock-drift",
            f"cost lock carries no quiescent_round_cost block — "
            f"{_REGEN_HINT}",
        ))
    else:
        findings.extend(compare_quiescent(
            quiescent, locked["quiescent_round_cost"], lock_path
        ))
    return findings


# -- tree-mode gate ----------------------------------------------------------


def check_cost_lock(trees: Sequence[Tuple[ast.AST, str]]) -> List[Finding]:
    """Tree-mode gate the driver runs on full sweeps: fit the ladder
    (session-cached compiles) and compare against the committed cost lock.
    Presence-gated on the engine sources exactly like the HLO gate, so
    retargeted test trees never pay a compile."""
    rels = {rel.replace("\\", "/") for _, rel in trees}
    if not all(src in rels for src in device_program.REGISTRY_SOURCES):
        return []
    try:
        table = collect_ladder()
        quiescent = collect_quiescent_cost()
    except RuntimeError as exc:
        return [Finding(COST_LOCK_REL, 1, "cost-lock-drift",
                        f"cannot fit the cost ladder: {exc}")]
    fits, refusals = fit_ladder(table)
    findings: List[Finding] = [
        Finding(
            COST_LOCK_REL, 1, "cost-unexplained",
            f"{name}: {fact} refused to classify — {why}; fix the fact or "
            f"widen the ladder, never guess a class",
        )
        for name, fact, why in refusals
    ]
    findings.extend(superlinear_findings(fits, (COST_LOCK_REL, 1)))
    lock_path = core.REPO / COST_LOCK_REL
    if not lock_path.exists():
        findings.append(Finding(
            COST_LOCK_REL, 1, "cost-lock-drift",
            "cost lockfile missing — generate it via "
            "`python tools/staticcheck.py --update-cost-lock`",
        ))
        return findings
    try:
        locked = json.loads(lock_path.read_text())
    except json.JSONDecodeError as exc:
        findings.append(Finding(
            COST_LOCK_REL, 1, "cost-lock-drift",
            f"cost lockfile is not valid JSON ({exc.msg}) — regenerate via "
            f"`python tools/staticcheck.py --update-cost-lock`",
        ))
        return findings
    if locked.get("ladder_config") != _ladder_config():
        findings.append(Finding(
            COST_LOCK_REL, 1, "cost-lock-drift",
            f"cost lock ladder_config {locked.get('ladder_config')} does "
            f"not match the registry's {_ladder_config()} — {_REGEN_HINT}",
        ))
        return findings
    findings.extend(compare_cost_lock(fits, quiescent, locked, COST_LOCK_REL))
    return findings


def update_cost_lock() -> Tuple[List[Finding], Optional[Path]]:
    """Regenerate the cost lockfile from freshly-fitted ladders. Refuses
    while any fit is unexplained, any fact exceeds its ceiling, or the HLO
    lock's differentials (wide<->compact, trace-on<->trace-off) disagree —
    a scaling the gate would immediately fail, or a ladder measured
    against an engine that no longer matches its own oracles, must be
    fixed, not frozen. Regeneration is byte-identical when nothing
    changed (the fit is pure deterministic arithmetic)."""
    try:
        table = collect_ladder()
        quiescent = collect_quiescent_cost()
    except RuntimeError as exc:
        return [Finding(COST_LOCK_REL, 1, "cost-lock-drift", str(exc))], None
    fits, refusals = fit_ladder(table)
    blocking: List[Finding] = [
        Finding(
            COST_LOCK_REL, 1, "cost-unexplained",
            f"refusing to freeze {name}/{fact}: {why}",
        )
        for name, fact, why in refusals
    ]
    blocking.extend(superlinear_findings(fits, (COST_LOCK_REL, 1)))
    for probe in (
        device_program.compaction_differential_ok,
        device_program.trace_differential_ok,
    ):
        mismatch = probe()
        if mismatch:
            blocking.append(
                Finding(COST_LOCK_REL, 1, "cost-lock-drift", mismatch)
            )
    if quiescent is None:
        blocking.append(Finding(
            COST_LOCK_REL, 1, "cost-quiescent",
            "refusing to freeze a cost lock without quiescent_round_cost — "
            "the sharded step was not in the collection (need the 8-device "
            "mesh)",
        ))
    if blocking:
        return blocking, None
    lock_path = core.REPO / COST_LOCK_REL
    payload = {
        "_comment": (
            "Fitted scaling classes of the registered engine entrypoints "
            "across the N/K/tenant geometry ladders: each fact's class "
            "(O(1)/O(log N)/O(N)/O(N*K)/O(N^2)), leading coefficient, fit "
            "residual, and — for shape-determined facts — the exact "
            "per-point values; plus the zero-churn quiescent_round_cost "
            "block ROADMAP item 3's sparse restructure must shrink. "
            "Generated by `python tools/staticcheck.py --update-cost-lock`; "
            "do not edit by hand — any drift from the live compiled "
            "artifacts fails the staticcheck gate."
        ),
        **fits_to_lock(fits, quiescent),
    }
    lock_path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return [], lock_path


# -- per-file mode (the seeded lint corpus) ---------------------------------


def _program_key_linenos(tree: ast.AST) -> Dict[str, int]:
    """lineno of each string key in the module's COST_AUDIT_PROGRAMS dict
    literal — where corpus findings anchor (the `# expect:` markers sit on
    these lines)."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "COST_AUDIT_PROGRAMS"
            and isinstance(node.value, ast.Dict)
        ):
            return {
                key.value: key.lineno
                for key in node.value.keys
                if isinstance(key, ast.Constant) and isinstance(key.value, str)
            }
    return {}


def check_cost_model(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Corpus mode: compile the module's own miniature programs across its
    inline ladder and compare the fitted classes against its inline
    ``COST_LOCK``. A module defines ``COST_AUDIT_PROGRAMS`` (name -> a
    builder taking ``n`` and returning ``{"jit", "args", ...}``),
    ``COST_LADDER`` (the n values to sweep), and ``COST_LOCK`` (name ->
    ``{"ceiling", "facts": {fact: {"class": ...}}}``; only the facts a
    lock entry names are fitted). Modules without the registry are skipped
    outright — this check never executes ordinary library files."""
    src = source if source is not None else path.read_text()
    if "COST_AUDIT_PROGRAMS" not in src:
        return []
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    linenos = _program_key_linenos(tree)
    if not linenos:
        return []
    rel = core.rel(path)
    namespace: Dict[str, Any] = {"__name__": f"_cost_corpus_{path.stem}"}
    exec(compile(src, str(path), "exec"), namespace)  # noqa: S102 — the
    # corpus is this repo's own fixture tree; per-file mode only ever runs
    # on explicitly-named files, never on sweeps.
    programs = namespace["COST_AUDIT_PROGRAMS"]
    locked = namespace.get("COST_LOCK", {})
    ladder = tuple(namespace.get("COST_LADDER", (8, 16, 32, 64)))
    c = namespace.get("AUDIT_C", 1)
    findings: List[Finding] = []
    with device_program._scoped_disable_persistent_cache():
        for name, builder in programs.items():
            loc = (rel, linenos.get(name, 1))
            entry_lock = locked.get(name, {})
            fact_names = sorted(entry_lock.get("facts", {}))
            series = []
            for n in ladder:
                spec = builder(n)
                compiled, _reasons = device_program._compile_program(spec)
                entry = device_program.extract_facts(
                    compiled, spec.get("donated_leaves", 0), n, c
                )
                series.append((n, entry_cost_facts(entry)))
            ceiling = entry_lock.get("ceiling", DEFAULT_CEILING)
            for fact in fact_names:
                if not all(fact in facts for _n, facts in series):
                    continue
                fitted = fit_scaling(
                    [((n, 1), facts[fact]) for n, facts in series],
                    FACT_TOLERANCES.get(fact, DEFAULT_TOLERANCE),
                )
                if "error" in fitted:
                    findings.append(Finding(
                        *loc, "cost-unexplained",
                        f"{name}: {fact} refused to classify — "
                        f"{fitted['error']}",
                    ))
                    continue
                if CLASS_RANK[fitted["class"]] > CLASS_RANK[ceiling]:
                    findings.append(Finding(
                        *loc, "cost-superlinear",
                        f"{name}: {fact} fitted {fitted['class']} (leading "
                        f"coeff {_round_sig(fitted['coeff'], 4)}) exceeds "
                        f"the entrypoint's {ceiling} ceiling",
                    ))
                    continue
                findings.extend(compare_fact_fit(
                    name, fact, fitted,
                    entry_lock.get("facts", {}).get(fact, {}), loc,
                ))
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))
