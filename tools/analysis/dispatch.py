"""Check family 8: RapidRequest dispatch exhaustiveness.

The fourth hand-kept mirror of the wire schema is the service's dispatch
chain: ``MembershipService.handle_message`` routes every ``RapidRequest``
union member through an ``isinstance`` ladder (the analog of the
reference's protobuf ``oneof`` switch, ``MembershipService.java:174-196``).
Nothing but this analyzer keeps the ladder in sync with the union — a new
message type that never reaches a handler falls through to the trailing
``TypeError`` at runtime, on a peer's schedule, not at build time.

Checks, over any ``rapid_tpu/protocol/`` class defining ``handle_message``:

- ``unreachable-dispatch-arm`` — a request-union member no arm matches
  (tuple aliases like ``CONSENSUS_TYPES`` are resolved through module
  assignments). Exhaustiveness is demanded only of ``async def``
  dispatchers — the transport-facing entry points a ``MessagingServer``
  forwards into; sync sub-dispatchers (``FastPaxos.handle_message``
  routes just the five consensus types behind a trailing ``raise``) are
  partial by design. Members handled by an outer layer on purpose are
  declared with a ``# dispatched-elsewhere: Name`` comment, validated
  against the union so a typo'd or stale exemption fails the gate.
- ``shadowed-arm`` — an arm whose every type was already matched by an
  earlier arm (an exact duplicate, or an earlier ``isinstance`` of a
  superclass): the body is dead code.
- ``dispatch-return`` — an arm resolvably returns something that is not a
  ``RapidResponse`` member. Resolution is conservative (skip-don't-guess):
  direct constructor calls and ``self._helper(...)`` calls are followed
  (through the helper's return annotation, or one level into its return
  statements); awaits, bare names, and foreign calls are left unjudged.

The unions come from the module itself when it defines them (the lint
corpus keeps miniatures in one file), else from ``rapid_tpu/types.py``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import core
from .core import Finding

#: The tree this family applies to (posix-style relative prefixes).
DISPATCH_PREFIXES = ("rapid_tpu/protocol/",)

_TYPES_REL = "rapid_tpu/types.py"

_ELSEWHERE_RE = re.compile(
    r"#\s*dispatched-elsewhere:\s*([A-Za-z_][A-Za-z0-9_]*"
    r"(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)"
)


def _union_from_module(tree: ast.AST, name: str) -> Optional[List[str]]:
    for node in ast.walk(tree):
        targets: List[str] = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if name not in targets or node.value is None:
            continue
        members = core.union_member_names(node.value)
        if members:
            return members
    return None


def _load_unions(tree: ast.AST) -> Tuple[Optional[List[str]], Optional[List[str]], Optional[ast.AST]]:
    """(request union, response union, the tree they came from). Prefers the
    module's own definitions; falls back to rapid_tpu/types.py."""
    req = _union_from_module(tree, "RapidRequest")
    resp = _union_from_module(tree, "RapidResponse")
    if req is not None and resp is not None:
        return req, resp, tree
    types_path = core.REPO / _TYPES_REL
    if not types_path.exists():
        return req, resp, None
    try:
        types_tree = ast.parse(types_path.read_text(), filename=str(types_path))
    except SyntaxError:
        return req, resp, None  # its own syntax-error finding covers this
    if req is None:
        req = _union_from_module(types_tree, "RapidRequest")
    if resp is None:
        resp = _union_from_module(types_tree, "RapidResponse")
    return req, resp, types_tree


def _tuple_aliases(tree: ast.AST) -> Dict[str, List[str]]:
    """Module-level ``NAME = (TypeA, TypeB, ...)`` assignments — the
    CONSENSUS_TYPES idiom the isinstance arms dispatch through."""
    aliases: Dict[str, List[str]] = {}
    for node in getattr(tree, "body", []):
        value = target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        if (
            target is not None
            and isinstance(value, ast.Tuple)
            and value.elts
            and all(isinstance(e, ast.Name) for e in value.elts)
        ):
            aliases[target] = [e.id for e in value.elts]
    return aliases


def _ancestors(class_defs: Dict[str, ast.ClassDef]) -> Dict[str, Set[str]]:
    """name -> transitive base-class names (Name bases only)."""
    direct = {
        name: {b.id for b in node.bases if isinstance(b, ast.Name)}
        for name, node in class_defs.items()
    }
    out: Dict[str, Set[str]] = {}

    def resolve(name: str, seen: Set[str]) -> Set[str]:
        if name in out:
            return out[name]
        if name in seen:
            return set()  # inheritance cycle: malformed input, stop
        seen.add(name)
        acc: Set[str] = set()
        for base in direct.get(name, ()):
            acc.add(base)
            acc |= resolve(base, seen)
        out[name] = acc
        return acc

    for name in direct:
        resolve(name, set())
    return out


def _isinstance_targets(
    test: ast.AST, param: str, aliases: Dict[str, List[str]]
) -> Optional[List[str]]:
    if not (
        isinstance(test, ast.Call)
        and isinstance(test.func, ast.Name)
        and test.func.id == "isinstance"
        and len(test.args) == 2
        and isinstance(test.args[0], ast.Name)
        and test.args[0].id == param
    ):
        return None
    target = test.args[1]
    names: List[str] = []
    elts = target.elts if isinstance(target, ast.Tuple) else [target]
    for elt in elts:
        if isinstance(elt, ast.Name):
            names.extend(aliases.get(elt.id, [elt.id]))
        else:
            return None  # dynamic second argument: must not be judged
    return names


def _collect_arms(
    fn: ast.AST, param: str, aliases: Dict[str, List[str]]
) -> List[Tuple[List[str], ast.If]]:
    """The isinstance ladder: top-level ``if``s of the function body plus
    their ``elif`` continuations, in evaluation order."""
    arms: List[Tuple[List[str], ast.If]] = []
    for stmt in fn.body:
        node = stmt
        while isinstance(node, ast.If):
            names = _isinstance_targets(node.test, param, aliases)
            if names is not None:
                arms.append((names, node))
            node = node.orelse[0] if (
                len(node.orelse) == 1 and isinstance(node.orelse[0], ast.If)
            ) else None
    return arms


def _returns_in(stmts: Sequence[ast.stmt]) -> List[ast.Return]:
    """Return statements belonging to these statements' own function —
    nested def/lambda bodies excluded."""
    out: List[ast.Return] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Return):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    for stmt in stmts:
        walk(stmt)
    return out


class _ReturnResolver:
    """Conservative three-valued resolution of 'does this expression produce
    a RapidResponse member?': True / False / None (unknowable — skip)."""

    def __init__(
        self,
        resp_members: Set[str],
        known_non_response: Set[str],
        methods: Dict[str, ast.AST],
    ) -> None:
        self._resp = resp_members
        self._non_resp = known_non_response
        self._methods = methods

    def resolve(self, expr: Optional[ast.AST], depth: int = 0) -> Optional[bool]:
        if expr is None:
            return False  # a bare `return` hands None to the transport
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Name):
                if func.id in self._resp:
                    return True
                if func.id in self._non_resp:
                    return False
                return None
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in self._methods
                and depth < 2
            ):
                return self._resolve_method(self._methods[func.attr], depth)
        return None

    def _resolve_method(self, method: ast.AST, depth: int) -> Optional[bool]:
        annotation = getattr(method, "returns", None)
        ann_name = None
        if isinstance(annotation, ast.Name):
            ann_name = annotation.id
        elif isinstance(annotation, ast.Constant) and isinstance(annotation.value, str):
            ann_name = annotation.value
        if ann_name is not None:
            if ann_name in self._resp:
                return True
            if ann_name in self._non_resp:
                return False
            return None  # Optional[...] strings, futures, ...: skip
        if annotation is not None:
            return None  # subscripted/attribute annotation: skip
        verdicts = [
            self.resolve(ret.value, depth + 1)
            for ret in _returns_in(method.body)
        ]
        if any(v is False for v in verdicts):
            return False
        if verdicts and all(v is True for v in verdicts):
            return True
        return None


def check_dispatch(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in DISPATCH_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))

    dispatchers = [
        (cls, method)
        for cls in ast.walk(tree)
        if isinstance(cls, ast.ClassDef)
        for method in cls.body
        if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef))
        and method.name == "handle_message"
        and len(method.args.args) >= 2
    ]
    if not dispatchers:
        return []

    req_union, resp_union, union_tree = _load_unions(tree)
    if req_union is None or resp_union is None:
        return []  # no union to be exhaustive over: skip, don't guess

    aliases = _tuple_aliases(tree)
    class_defs = {
        node.name: node for node in ast.walk(tree) if isinstance(node, ast.ClassDef)
    }
    if union_tree is not None and union_tree is not tree:
        for node in ast.walk(union_tree):
            if isinstance(node, ast.ClassDef):
                class_defs.setdefault(node.name, node)
    ancestors = _ancestors(class_defs)

    findings: List[Finding] = []
    exempt: Set[str] = set()
    for match in _ELSEWHERE_RE.finditer(src):
        for name in re.split(r"\s*,\s*", match.group(1)):
            lineno = src[: match.start()].count("\n") + 1
            if name not in req_union:
                findings.append(Finding(
                    rel, lineno, "unreachable-dispatch-arm",
                    f"# dispatched-elsewhere names {name!r}, which is not a "
                    f"RapidRequest union member — stale or typo'd exemption",
                ))
            else:
                exempt.add(name)

    resp_members = set(resp_union)
    known_non_response = (set(req_union) | set(class_defs)) - resp_members

    for cls, method in dispatchers:
        param = method.args.args[1].arg
        arms = _collect_arms(method, param, aliases)
        methods = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        resolver = _ReturnResolver(resp_members, known_non_response, methods)

        def matched_by(member: str, covered: Set[str]) -> bool:
            return member in covered or bool(ancestors.get(member, set()) & covered)

        covered: Set[str] = set()
        for names, arm in arms:
            if names and all(matched_by(n, covered) for n in names):
                findings.append(Finding(
                    rel, arm.lineno, "shadowed-arm",
                    f"{cls.name}.{method.name}: isinstance arm for "
                    f"({', '.join(names)}) is dead — every type already "
                    f"matched by an earlier arm",
                ))
            for ret in _returns_in(arm.body):
                if resolver.resolve(ret.value) is False:
                    desc = ast.unparse(ret.value) if ret.value is not None else "None"
                    findings.append(Finding(
                        rel, ret.lineno, "dispatch-return",
                        f"{cls.name}.{method.name}: arm for "
                        f"({', '.join(names)}) returns {desc}, which is not "
                        f"a RapidResponse member",
                    ))
            covered.update(names)

        if not isinstance(method, ast.AsyncFunctionDef):
            # Sync handle_message = internal sub-dispatcher: shadowing and
            # return-type checks above apply, exhaustiveness does not.
            continue
        for member in req_union:
            if member in exempt:
                continue
            if not matched_by(member, covered):
                findings.append(Finding(
                    rel, method.lineno, "unreachable-dispatch-arm",
                    f"RapidRequest member {member} reaches no isinstance arm "
                    f"in {cls.name}.{method.name} — it falls through to the "
                    f"unidentified-request error; handle it or declare "
                    f"`# dispatched-elsewhere: {member}`",
                ))
    return findings
