"""Check family 5: asyncio concurrency discipline (guarded-by analysis).

The protocol core is serialized by a single ``asyncio.Lock`` "protocol
executor" (``MembershipService._lock``) and the transports by their own
locks; the correctness argument (atomic protocol state transitions feeding
Fast Paxos) rests on that discipline holding everywhere. This analyzer
verifies it statically, per class, over ``rapid_tpu/protocol/`` and
``rapid_tpu/messaging/``:

**Guard model.** A field's guard is learned two ways:

- explicitly, from a ``# guarded-by: <lock>`` comment on (or immediately
  above) the field's initializing assignment, where ``<lock>`` is either a
  same-class ``asyncio.Lock`` attribute or the literal ``event-loop``
  (meaning: protected by cooperative scheduling alone — mutations need no
  lock, but no read→await→write sequence may straddle an await);
- by majority inference: an unannotated field whose mutation sites are
  mostly (>= 2 sites, strictly more than the provably lock-free ones)
  under one ``async with self.<lock>`` is treated as guarded by it.

**Context model (CFG-lite).** Each method gets an entry lock-context via a
fixpoint over the intra-class call graph: public methods and dunders enter
provably lock-free (the event loop calls them directly); ``__init__`` is
single-threaded construction (exempt); a private method inherits the meet
of its intra-class call-site contexts; a method whose reference escapes as
a value (callback registration) — or that is never called intra-class — is
UNKNOWN. Statements inside ``async with self.<lock>`` are lock-held.
Following the staticcheck philosophy (conservative resolution, skip-don't-
guess), a finding is emitted only in *provably* lock-free contexts;
UNKNOWN suppresses, never convicts.

**Checks.**

- ``unguarded-mutation`` — a lock-guarded field mutated (assignment,
  augmented assignment, ``del``, subscript store, or a mutating container
  method call) in a provably lock-free context. A deliberate exception
  carries ``# unguarded-ok: <reason>`` on the line.
- ``interleaving-hazard`` — a guarded field read, then an ``await`` with
  the guard not held across it, then a dependent write: the classic
  check-then-act lost update (two lock acquisitions with an await between,
  or a lock-free ``self.f = await g(self.f)``).
- ``lock-reentrancy`` — ``await self.<m>(...)`` while a lock is held, where
  ``<m>`` (transitively) acquires the same lock: ``asyncio.Lock`` is not
  re-entrant, so this deadlocks the protocol executor.
- ``guarded-by-annotation`` — an annotation that binds to no assignment or
  names an unknown lock (a typo'd annotation must fail the gate, not
  silently guard nothing).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from . import core
from .core import Finding

_MUTATORS = core.MUTATING_CONTAINER_METHODS

CONCURRENCY_PREFIXES = ("rapid_tpu/protocol/", "rapid_tpu/messaging/")

EVENT_LOOP_GUARD = "event-loop"

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_-]*)")
_UNGUARDED_OK_RE = re.compile(r"#\s*unguarded-ok\b")

_INIT_METHODS = frozenset({"__init__", "__post_init__"})


class Ctx(NamedTuple):
    """Lock context: ``held`` is the set of self-lock names PROVABLY held;
    ``unknown`` means additional locks may be held (so "lock-free" cannot
    be proven and mutation findings are suppressed)."""

    held: frozenset
    unknown: bool


_FREE = Ctx(frozenset(), False)
_UNKNOWN = Ctx(frozenset(), True)
_INIT = "init"  # sentinel entry context for constructors


def _self_field(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _iter_no_nested(node: ast.AST):
    """Walk a subtree without descending into nested function scopes (their
    bodies execute at an unknowable later time and context)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield from _iter_no_nested(child)


def _target_mutations(stmt: ast.AST) -> List[Tuple[str, int]]:
    """(field, lineno) for ``self.<field>`` mutated via the TARGETS of one
    assignment/delete statement (plain, augmented, annotated, tuple,
    subscript-store)."""
    out: List[Tuple[str, int]] = []
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        if isinstance(stmt, ast.AnnAssign) and stmt.value is None:
            return out  # bare annotation: no assignment happens
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for target in targets:
            elts = target.elts if isinstance(target, (ast.Tuple, ast.List)) else [target]
            for elt in elts:
                if isinstance(elt, ast.Starred):
                    elt = elt.value
                field = _self_field(elt)
                if field is None and isinstance(elt, ast.Subscript):
                    field = _self_field(elt.value)  # self.f[k] = v
                if field is not None:
                    out.append((field, elt.lineno))
    elif isinstance(stmt, ast.Delete):
        for target in stmt.targets:
            field = _self_field(target)
            if field is None and isinstance(target, ast.Subscript):
                field = _self_field(target.value)  # del self.f[k]
            if field is not None:
                out.append((field, target.lineno))
    return out


def _mutations_in(node: ast.AST) -> List[Tuple[str, int]]:
    """(field, lineno) for every ``self.<field>`` mutation form within
    ``node`` (nested function scopes excluded): assignment targets plus
    mutating container-method calls."""
    out: List[Tuple[str, int]] = []
    for cur in _iter_no_nested(node):
        if isinstance(cur, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
            out.extend(_target_mutations(cur))
        elif isinstance(cur, ast.Call) and isinstance(cur.func, ast.Attribute):
            if cur.func.attr in _MUTATORS:
                field = _self_field(cur.func.value)
                if field is not None:  # self.f.append(...)
                    out.append((field, cur.lineno))
    return out


def _reads_in(node: ast.AST) -> List[Tuple[str, int]]:
    """(field, lineno) for every ``self.<field>`` read (Load) within
    ``node``, plus augmented-assignment targets (read-modify-write)."""
    out: List[Tuple[str, int]] = []
    for cur in _iter_no_nested(node):
        if isinstance(cur, ast.Attribute) and isinstance(cur.ctx, ast.Load):
            field = _self_field(cur)
            if field is not None:
                out.append((field, cur.lineno))
        elif isinstance(cur, ast.AugAssign):
            field = _self_field(cur.target)
            if field is not None:
                out.append((field, cur.lineno))
    return out


def _has_await(node: ast.AST) -> bool:
    return any(isinstance(cur, ast.Await) for cur in _iter_no_nested(node))


class _Site(NamedTuple):
    lineno: int
    ctx: Ctx          # local context within the method (entry not applied)
    nested: bool      # inside a nested function scope


class _MethodEvents(NamedTuple):
    mutations: List[Tuple[str, _Site]]      # field -> site
    calls: List[Tuple[str, _Site]]          # self.<m>() call sites
    awaited_calls: List[Tuple[str, _Site]]  # await self.<m>(...) sites
    acquires: Set[str]                      # locks taken via async with


def _collect_events(method: ast.AST, locks: Set[str], methods: Set[str]) -> _MethodEvents:
    events = _MethodEvents([], [], [], set())

    def visit(node: ast.AST, held: frozenset, unknown: bool, nested: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if node is not method:
                for child in ast.iter_child_nodes(node):
                    visit(child, held, unknown, True)
                return
        if isinstance(node, ast.AsyncWith):
            new_held = set(held)
            new_unknown = unknown
            for item in node.items:
                lock = _self_field(item.context_expr)
                if lock is not None and lock in locks:
                    new_held.add(lock)
                    if not nested:
                        events.acquires.add(lock)
                else:
                    # async with over something we can't prove is (not) a
                    # self-lock: anything may be held inside.
                    new_unknown = True
            for item in node.items:
                visit(item, held, unknown, nested)
            for child in node.body:
                visit(child, frozenset(new_held), new_unknown, nested)
            return
        ctx = Ctx(held, unknown)
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            callee = _self_field(node.value.func)
            if callee is not None and callee in methods:
                events.awaited_calls.append((callee, _Site(node.lineno, ctx, nested)))
        if isinstance(node, ast.Call):
            callee = _self_field(node.func)
            if callee is not None and callee in methods:
                events.calls.append((callee, _Site(node.lineno, ctx, nested)))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
            # Targets only here: mutator CALLS inside the value expression
            # are recorded exactly once by the Call branch during descent.
            for field, lineno in _target_mutations(node):
                events.mutations.append((field, _Site(lineno, ctx, nested)))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                field = _self_field(node.func.value)
                if field is not None:
                    events.mutations.append((field, _Site(node.lineno, ctx, nested)))
        for child in ast.iter_child_nodes(node):
            visit(child, held, unknown, nested)

    for child in ast.iter_child_nodes(method):
        visit(child, frozenset(), False, False)
    return events


def _escaped_methods(class_node: ast.ClassDef, methods: Set[str]) -> Set[str]:
    """Methods referenced as VALUES (``self.m`` not immediately called):
    callback registrations make their execution context unknowable."""
    escaped: Set[str] = set()

    def visit(node: ast.AST, call_func: Optional[ast.AST]) -> None:
        if isinstance(node, ast.Attribute) and node is not call_func:
            field = _self_field(node)
            if field in methods and isinstance(node.ctx, ast.Load):
                escaped.add(field)
        next_call_func = node.func if isinstance(node, ast.Call) else None
        for child in ast.iter_child_nodes(node):
            visit(child, next_call_func if child is next_call_func else None)

    visit(class_node, None)
    return escaped


def _meet(ctxs: List[Ctx]) -> Ctx:
    held = frozenset.intersection(*[c.held for c in ctxs])
    disagree = any(c.held != ctxs[0].held for c in ctxs)
    return Ctx(held, any(c.unknown for c in ctxs) or disagree)


def _combine(entry, local: _Site):
    """Absolute context of a site = method entry context + local regions."""
    if entry == _INIT:
        return _INIT if not local.nested else _UNKNOWN
    if local.nested:
        return _UNKNOWN
    return Ctx(entry.held | local.ctx.held, entry.unknown or local.ctx.unknown)


class _ClassAnalysis:
    def __init__(self, node: ast.ClassDef, rel: str, lines: List[str]) -> None:
        self.node = node
        self.rel = rel
        self.lines = lines
        self.findings: List[Finding] = []
        self.methods: Dict[str, ast.AST] = {
            stmt.name: stmt
            for stmt in node.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.locks = self._find_locks()
        self.guards = self._parse_annotations()  # field -> lock | event-loop
        self.events = {
            name: _collect_events(m, self.locks, set(self.methods))
            for name, m in self.methods.items()
        }
        self.entries = self._entry_contexts()
        self._infer_guards()

    # -- learning ------------------------------------------------------

    def _find_locks(self) -> Set[str]:
        locks: Set[str] = set()
        for cur in ast.walk(self.node):
            if isinstance(cur, ast.Assign) and isinstance(cur.value, ast.Call):
                func = cur.value.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "Lock"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "asyncio"
                ):
                    for target in cur.targets:
                        field = _self_field(target)
                        if field is not None:
                            locks.add(field)
        return locks

    def _parse_annotations(self) -> Dict[str, str]:
        # Field assignments by line range, for binding comments to fields.
        spans: List[Tuple[int, int, str]] = []
        for cur in ast.walk(self.node):
            if isinstance(cur, (ast.Assign, ast.AnnAssign)):
                targets = cur.targets if isinstance(cur, ast.Assign) else [cur.target]
                for target in targets:
                    field = _self_field(target)
                    if field is not None:
                        spans.append((cur.lineno, cur.end_lineno or cur.lineno, field))
        guards: Dict[str, str] = {}
        end = self.node.end_lineno or self.node.lineno
        for lineno in range(self.node.lineno, min(end, len(self.lines)) + 1):
            match = _GUARDED_BY_RE.search(self.lines[lineno - 1])
            if not match:
                continue
            lock = match.group(1)
            field = next(
                (f for lo, hi, f in spans if lo <= lineno <= hi), None
            ) or next(
                # comment-above form: binds to the statement starting next line
                (f for lo, hi, f in spans if lo == lineno + 1), None
            )
            if field is None:
                self.findings.append(
                    Finding(self.rel, lineno, "guarded-by-annotation",
                            "guarded-by comment binds to no self-attribute "
                            "assignment on (or below) this line")
                )
                continue
            if lock != EVENT_LOOP_GUARD and lock not in self.locks:
                self.findings.append(
                    Finding(self.rel, lineno, "guarded-by-annotation",
                            f"guarded-by names {lock!r}, which is not an "
                            f"asyncio.Lock attribute of {self.node.name} "
                            f"(known: {sorted(self.locks) or 'none'}, or "
                            f"{EVENT_LOOP_GUARD!r})")
                )
                continue
            guards[field] = lock
        return guards

    def _entry_contexts(self) -> Dict[str, object]:
        escaped = _escaped_methods(self.node, set(self.methods))
        call_sites: Dict[str, List[Tuple[str, _Site]]] = {m: [] for m in self.methods}
        for caller, events in self.events.items():
            for callee, site in events.calls:
                call_sites[callee].append((caller, site))
        entries: Dict[str, object] = {}
        for name in self.methods:
            if name in _INIT_METHODS:
                entries[name] = _INIT
            elif not name.startswith("_") or (
                name.startswith("__") and name.endswith("__")
            ):
                # Public methods and protocol dunders: the event loop (or
                # application code) calls them directly, holding nothing.
                entries[name] = _FREE
            elif name in escaped or not call_sites[name]:
                entries[name] = _UNKNOWN
        # Fixpoint over the remaining (private, intra-class-called) methods.
        for _ in range(len(self.methods) + 1):
            progressed = False
            for name in self.methods:
                if name in entries:
                    continue
                sites = call_sites[name]
                if any(caller not in entries for caller, _ in sites):
                    continue
                ctxs = [_combine(entries[caller], site) for caller, site in sites]
                non_init = [c for c in ctxs if c != _INIT]
                entries[name] = _meet(non_init) if non_init else _INIT
                progressed = True
            if not progressed:
                break
        for name in self.methods:
            entries.setdefault(name, _UNKNOWN)  # call-graph cycles
        return entries

    def _infer_guards(self) -> None:
        """Majority inference for unannotated fields: mostly-locked mutation
        patterns imply the discipline; the outliers are the findings."""
        if not self.locks:
            return
        per_field: Dict[str, Dict[str, int]] = {}
        free_count: Dict[str, int] = {}
        for name, events in self.events.items():
            for field, site in events.mutations:
                if field in self.guards:
                    continue
                ctx = _combine(self.entries[name], site)
                if ctx == _INIT or ctx == _UNKNOWN:
                    continue
                if ctx.held:
                    for lock in ctx.held:
                        per_field.setdefault(field, {}).setdefault(lock, 0)
                        per_field[field][lock] += 1
                elif not ctx.unknown:
                    free_count[field] = free_count.get(field, 0) + 1
        for field, by_lock in per_field.items():
            best = max(by_lock, key=by_lock.get)
            ties = [k for k, v in by_lock.items() if v == by_lock[best]]
            if len(ties) > 1:
                continue
            if by_lock[best] >= 2 and by_lock[best] > free_count.get(field, 0):
                self.guards[field] = best

    # -- checks --------------------------------------------------------

    def _allowlisted(self, lineno: int) -> bool:
        line = self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""
        return bool(_UNGUARDED_OK_RE.search(line))

    def check_mutations(self) -> None:
        for name, events in self.events.items():
            for field, site in events.mutations:
                guard = self.guards.get(field)
                if guard is None or guard == EVENT_LOOP_GUARD:
                    continue
                ctx = _combine(self.entries[name], site)
                if ctx == _INIT or ctx == _UNKNOWN:
                    continue
                if guard in ctx.held or ctx.unknown:
                    continue
                if self._allowlisted(site.lineno):
                    continue
                self.findings.append(
                    Finding(self.rel, site.lineno, "unguarded-mutation",
                            f"{self.node.name}.{field} is guarded by "
                            f"{guard!r} but mutated here (in {name!r}) in a "
                            "provably lock-free context")
                )

    def check_reentrancy(self) -> None:
        may_acquire: Dict[str, Set[str]] = {
            name: set(events.acquires) for name, events in self.events.items()
        }
        for _ in range(len(self.methods)):
            changed = False
            for name, events in self.events.items():
                for callee, _site in events.awaited_calls:
                    extra = may_acquire.get(callee, set()) - may_acquire[name]
                    if extra:
                        may_acquire[name] |= extra
                        changed = True
            if not changed:
                break
        for name, events in self.events.items():
            entry = self.entries[name]
            entry_held = entry.held if isinstance(entry, Ctx) else frozenset()
            for callee, site in events.awaited_calls:
                if site.nested:
                    continue
                held = entry_held | site.ctx.held
                overlap = held & may_acquire.get(callee, set())
                if overlap:
                    lock = sorted(overlap)[0]
                    self.findings.append(
                        Finding(self.rel, site.lineno, "lock-reentrancy",
                                f"awaiting self.{callee}() while holding "
                                f"{lock!r}, which {callee!r} also acquires — "
                                "asyncio.Lock is not re-entrant; this "
                                "deadlocks")
                    )

    def check_interleaving(self) -> None:
        guarded = set(self.guards)
        if not guarded:
            return
        for name, method in self.methods.items():
            if not isinstance(method, ast.AsyncFunctionDef):
                continue
            if self.entries[name] != _FREE:
                # Entered with a lock (or unknowably): the caller's critical
                # section spans the awaits, so sequencing is its concern.
                continue
            flagged: set = set()
            for field in guarded:
                self._scan_field(
                    method.body, field, self.guards[field],
                    {"read": None, "hazard": None}, flagged,
                )

    def _flag_hazard(self, field: str, lineno: int, flagged: set) -> None:
        if (field, lineno) in flagged:
            return
        flagged.add((field, lineno))
        self.findings.append(
            Finding(self.rel, lineno, "interleaving-hazard",
                    f"{self.node.name}.{field} read before an await and "
                    "written after it without the guard held across — the "
                    "state can change during the await (lost update)")
        )

    def _shields(self, stmt: ast.AST, guard: str) -> bool:
        """Does this ``async with`` hold the FIELD'S OWN guard across its
        body? Only then do its internal awaits stop being hazards — an
        unrelated context manager (a timeout, another lock) yields to the
        event loop just the same. Event-loop-guarded fields have no lock
        that can shield them by definition."""
        if guard == EVENT_LOOP_GUARD or not isinstance(stmt, ast.AsyncWith):
            return False
        return any(
            _self_field(item.context_expr) == guard for item in stmt.items
        )

    def _expr_step(
        self, expr: ast.AST, field: str, state: dict, flagged: set,
        implicit_await: bool = False,
    ) -> None:
        """Advance the scan state over one straight-line expression/statement
        summary: flag pending hazards its writes consume, record its reads,
        and mark an awaited yield point after a live read."""
        reads = [ln for f, ln in _reads_in(expr) if f == field]
        writes = [ln for f, ln in _mutations_in(expr) if f == field]
        has_await = implicit_await or _has_await(expr)
        if has_await and reads and writes:
            # Same-statement hazard: self.f = await g(self.f) — the value
            # is read, the await yields, the store lands late.
            for lineno in writes:
                self._flag_hazard(field, lineno, flagged)
        for lineno in writes:
            if state["hazard"] is not None:
                self._flag_hazard(field, lineno, flagged)
        if reads:
            state["read"] = reads[-1]
        if has_await and state["read"] is not None:
            state["hazard"] = getattr(expr, "lineno", state["read"])

    def _scan_field(
        self, stmts, field: str, guard: str, state: dict, flagged: set
    ) -> None:
        """CFG-lite straight-line scan for ONE guarded field: sibling
        statements execute in order; ``if``/``while`` tests and ``for``
        iterables are straight-line with their siblings (the check-then-act
        read lives in the test), while branch/loop BODIES are scanned
        internally but stay opaque to the parent (a branch-resident read or
        await never convicts a sibling — skip-don't-guess)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.AsyncWith, ast.With)):
                if self._shields(stmt, guard):
                    # The field's own lock is held across the body: internal
                    # awaits are not hazards — but state read here can still
                    # be stale-written in a LATER epoch, and writes here
                    # consume earlier hazards.
                    for lineno in (ln for f, ln in _mutations_in(stmt) if f == field):
                        if state["hazard"] is not None:
                            self._flag_hazard(field, lineno, flagged)
                    reads = [ln for f, ln in _reads_in(stmt) if f == field]
                    if reads:
                        state["read"] = reads[-1]
                else:
                    # Unrelated context manager: transparent. Entering an
                    # async with awaits __aenter__ — a yield point itself.
                    for item in stmt.items:
                        self._expr_step(
                            item.context_expr, field, state, flagged,
                            implicit_await=isinstance(stmt, ast.AsyncWith),
                        )
                    self._scan_field(stmt.body, field, guard, state, flagged)
                continue
            if isinstance(stmt, ast.Try):
                # try bodies execute unconditionally: scan inline (shared
                # state); handlers/orelse are conditional: fresh scans.
                self._scan_field(stmt.body, field, guard, state, flagged)
                for handler in stmt.handlers:
                    self._scan_field(
                        handler.body, field, guard,
                        {"read": None, "hazard": None}, flagged,
                    )
                self._scan_field(
                    stmt.orelse, field, guard,
                    {"read": None, "hazard": None}, flagged,
                )
                self._scan_field(stmt.finalbody, field, guard, state, flagged)
                continue
            header = None
            implicit_await = False
            if isinstance(stmt, (ast.If, ast.While)):
                header = stmt.test
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                header = stmt.iter
                # async-for awaits __anext__ between header and each body run
                implicit_await = isinstance(stmt, ast.AsyncFor)
            elif isinstance(stmt, ast.Match):
                header = stmt.subject
            if header is not None:
                self._expr_step(header, field, state, flagged, implicit_await)
                blocks = (
                    [case.body for case in stmt.cases]
                    if isinstance(stmt, ast.Match)
                    else [stmt.body, stmt.orelse]
                )
                for block in blocks:
                    self._scan_field(
                        block, field, guard,
                        {"read": None, "hazard": None}, flagged,
                    )
                continue
            self._expr_step(stmt, field, state, flagged)


def check_concurrency(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in CONCURRENCY_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()
    findings: List[Finding] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            analysis = _ClassAnalysis(node, rel, lines)
            analysis.check_mutations()
            analysis.check_reentrancy()
            analysis.check_interleaving()
            findings.extend(analysis.findings)
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))
