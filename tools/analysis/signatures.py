"""Check family 2: call-signature conformance against imported runtime
modules.

For call sites whose callee statically resolves to a module-level object of
an imported module (``f(...)`` where ``f`` is module-global in the calling
module, or ``mod.f(...)`` where ``mod`` is a module-level module import),
bind the call's shape (positional arity + keyword names) against
``inspect.signature`` of the real runtime object. Catches wrong-arity
calls, typo'd keywords, and stale references to renamed module attributes —
the highest-value slice of what a type checker does for a dynamically-typed
codebase. Resolution is deliberately conservative: names shadowed in any
enclosing function scope, call sites using ``*args``/``**kwargs``, and
objects whose signature is undiscoverable are all skipped, so every finding
is a real defect, never a maybe.

Importing a module to inspect its runtime surface follows the import-time
platform rules: under pytest, tests/conftest.py has already forced the CPU
backend.
"""

from __future__ import annotations

import ast
import importlib
import inspect
import types
from pathlib import Path
from typing import List, Optional, Tuple

from . import core
from .core import Finding


class _ScopeStack:
    """Tracks, per enclosing function/lambda/comprehension scope, the names
    bound locally — so a module-global resolution is only trusted when no
    enclosing scope shadows the name."""

    def __init__(self) -> None:
        self.stack: List[set] = []

    def shadowed(self, name: str) -> bool:
        return any(name in scope for scope in self.stack)


def _local_bindings(node: ast.AST) -> set:
    """Names bound in THIS function scope (params, assignments, imports,
    inner defs) — without descending into nested function scopes."""
    names = set()
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = node.args
        for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
    body = getattr(node, "body", [])
    stack = list(body) if isinstance(body, list) else []
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(cur.name)
            continue  # nested scope: its internals don't bind here
        if isinstance(cur, ast.Lambda):
            continue
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, (ast.Store, ast.Del)):
            names.add(cur.id)
        # Bindings whose target is a plain str, not a Name node:
        if isinstance(cur, ast.ExceptHandler) and cur.name:
            names.add(cur.name)
        if isinstance(cur, (ast.MatchAs, ast.MatchStar)) and cur.name:
            names.add(cur.name)
        if isinstance(cur, ast.MatchMapping) and cur.rest:
            names.add(cur.rest)
        if isinstance(cur, (ast.Import, ast.ImportFrom)):
            for alias in cur.names:
                if alias.name != "*":
                    names.add(alias.asname or alias.name.split(".")[0])
        if isinstance(cur, (ast.Global, ast.Nonlocal)):
            # Declared non-local: reads go to the outer binding — but for
            # shadow-tracking, treating as local only SKIPS checks (safe).
            names.update(cur.names)
        stack.extend(ast.iter_child_nodes(cur))
    return names


def _module_name_for(path: Path) -> Optional[str]:
    """Import path for a repo file, or None if it isn't importable as a
    module of this repo (scripts are importable top-level: bench, etc.)."""
    try:
        rel = path.resolve().relative_to(core.REPO)
    except ValueError:
        return None
    parts = rel.with_suffix("").parts
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def _bindable(sig: inspect.Signature) -> bool:
    """Signatures with *args/**kwargs accept almost anything; checking them
    would only ever produce noise."""
    return not any(
        p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        for p in sig.parameters.values()
    )


def _try_signature(obj) -> Optional[inspect.Signature]:
    try:
        return inspect.signature(obj)
    except (ValueError, TypeError):
        return None


def _check_one_call(
    call: ast.Call, obj, dotted: str, rel: str, findings: List[Finding]
) -> None:
    if any(isinstance(a, ast.Starred) for a in call.args):
        return
    if any(kw.arg is None for kw in call.keywords):  # **kwargs at site
        return
    sig = _try_signature(obj)
    if sig is None or not _bindable(sig):
        return
    # Bound methods/classmethods accessed via instance aren't resolved here
    # (module-level objects only), so no self-adjustment is needed.
    placeholders = [object()] * len(call.args)
    kwargs = {kw.arg: object() for kw in call.keywords}
    try:
        sig.bind(*placeholders, **kwargs)
    except TypeError as exc:
        findings.append(
            Finding(rel, call.lineno, "call-signature",
                    f"{dotted}{sig} cannot bind this call: {exc}")
        )


def check_call_signatures(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Arity/keyword conformance for statically-resolvable call sites, plus
    existence of ``mod.attr`` references on module-level module imports."""
    src = source if source is not None else path.read_text()
    rel = core.rel(path)
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    mod_name = _module_name_for(path)
    if mod_name is None:
        return []
    try:
        module = importlib.import_module(mod_name)
    except BaseException as exc:  # noqa: BLE001 — any import failure is a finding
        # BaseException, not Exception: pytest.importorskip raises Skipped,
        # which subclasses BaseException so that test code can't swallow it
        # by accident — but here it must not propagate and skip/abort the
        # whole gate.
        if type(exc).__name__ == "Skipped":
            # Module-level importorskip: the module declares an optional
            # dependency this environment lacks (e.g. hypothesis).
            # Un-analyzable here, not broken — pytest skips it the same way.
            return []
        if not isinstance(exc, Exception):
            raise  # KeyboardInterrupt / SystemExit stay fatal
        return [Finding(rel, 1, "import-error", f"cannot import {mod_name}: {exc}")]

    findings: List[Finding] = []
    scopes = _ScopeStack()

    def resolve(expr: ast.AST) -> Tuple[Optional[object], Optional[str]]:
        """(object, dotted-name) for Name / module-attribute chains bound at
        module level and unshadowed; (None, None) when not resolvable."""
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
            if scopes.shadowed(expr.id):
                return None, None
            if expr.id in vars(module):
                return vars(module)[expr.id], expr.id
            return None, None
        if isinstance(expr, ast.Attribute) and isinstance(expr.ctx, ast.Load):
            base, dotted = resolve(expr.value)
            if not isinstance(base, types.ModuleType):
                return None, None  # instance attrs are dynamic; modules aren't
            if getattr(base, "__getattr__", None) is not None:
                return None, None  # module-level __getattr__: unknowable
            if not hasattr(base, expr.attr):
                findings.append(
                    Finding(rel, expr.lineno, "missing-attribute",
                            f"module {dotted!r} has no attribute {expr.attr!r}")
                )
                return None, None
            return getattr(base, expr.attr), f"{dotted}.{expr.attr}"
        return None, None

    def visit(node: ast.AST) -> None:
        is_scope = isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef,
             ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        )
        if is_scope:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
            ):
                # Class bodies execute like function bodies: a name bound
                # earlier in the body shadows the module level for later
                # body-level references. (For functions NESTED in the class
                # the class scope is not on the lookup chain, so treating it
                # as shadowing there only skips a check — never misjudges.)
                scopes.stack.append(_local_bindings(node))
            else:
                targets = set()
                for gen in node.generators:
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name):
                            targets.add(n.id)
                scopes.stack.append(targets)
        if isinstance(node, ast.Call):
            obj, dotted = resolve(node.func)
            if obj is not None:
                _check_one_call(node, obj, dotted, rel, findings)
        elif isinstance(node, ast.Attribute):
            resolve(node)  # existence check on bare module-attr reads
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            scopes.stack.pop()

    visit(tree)
    # Attribute chains nest (resolve recurses), so the same missing
    # attribute can be recorded through both the Call and Attribute hooks.
    return sorted(set(findings), key=lambda f: (f.lineno, f.message))
