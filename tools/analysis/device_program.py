"""Check family 12: compiled-program conformance (the HLO budget gate).

The engine's communication story is a claim about what XLA emits, so this
family checks the compiled artifact itself: every registered jitted engine
entrypoint (the ``VirtualCluster`` dispatch surface plus the
``parallel/mesh.py`` sharded variants under a forced 8-device CPU mesh) is
compiled via ``jax.jit(...).lower().compile()`` and its facts extracted
from ``as_text()`` + ``memory_analysis()``:

- every cross-device collective, classified by kind, payload bytes/class,
  and location (hot-loop / hot-loop-cond / cond / prologue — the
  ``hlo_facts`` classifier that absorbed ``rapid_tpu/parallel/audit.py``);
- host<->device transfer ops (infeed/outfeed/send/recv);
- donation outcomes: each ``donate_argnums`` leaf either aliased in the
  compiled output (``input_output_alias``) or dropped — a drop without an
  explicit registry waiver is a finding, never a frozen fact;
- argument/output/temp/generated-code memory bytes.

The facts freeze into the committed lockfile
``tools/analysis/hlo.lock.json``. Drift — a new hot-loop collective, a
payload-class increase, a lost donation, temp-memory growth beyond
tolerance — fails the gate naming the entrypoint and the delta, until the
developer regenerates via ``python tools/staticcheck.py --update-hlo-lock``
and reviews the diff (the ``wire.lock.json`` workflow, applied to the
compiled program instead of the wire schema).

Compiling is expensive relative to AST checks (~15 s for the six
entrypoints), so facts are collected ONCE per process and cached: the
tree-sweep gate, the lock regenerator, the bench's ``hlo_audit`` stage and
every test share one collection. ``check_device_program`` is the per-file
mode for the seeded lint corpus: a module defining ``HLO_AUDIT_PROGRAMS``
(name -> zero-arg builder returning ``{"jit": jitted, "args": (...),
"donated_leaves": int}``) and ``HLO_LOCK`` is compiled and compared against
its own inline lock — the corpus way to pin an injected hot-loop
all-gather or a dropped donation, finding by finding.
"""

from __future__ import annotations

import ast
import json
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import core, hlo_facts
from .core import Finding

#: The committed freeze of the compiled-program facts, repo-relative.
HLO_LOCK_REL = "tools/analysis/hlo.lock.json"

#: The source files the registry compiles — the tree-mode gate only runs
#: when a sweep actually covers this repo's engine (tests that retarget
#: ``core.REPO`` at a temporary tree must not trigger 15 s of compiles).
REGISTRY_SOURCES = (
    "rapid_tpu/models/virtual_cluster.py",
    "rapid_tpu/parallel/mesh.py",
    "rapid_tpu/tenancy/fleet.py",
)

#: Audit shapes: small enough to compile in seconds, large enough that the
#: payload classes ([n]-scale vs [c,n]-scale) are unambiguous. The mesh
#: axis needs AUDIT_DEVICES to divide AUDIT_N; the 2-D ``('cohort',
#: 'nodes')`` variant reshapes the same devices to (AUDIT_COHORT_DEVICES,
#: AUDIT_DEVICES // AUDIT_COHORT_DEVICES), which must divide AUDIT_C and
#: AUDIT_N respectively.
AUDIT_N = 256
AUDIT_C = 8
AUDIT_K = 4
AUDIT_DEVICES = 8
AUDIT_COHORT_DEVICES = 2
#: The fleet audit: AUDIT_TENANTS tenant clusters over the 3-D
#: ``('tenant', 'cohort', 'nodes')`` reshape of the same devices. The
#: tenant axis leads, so device ids are contiguous per tenant slice —
#: ``AUDIT_TENANT_BLOCK`` devices per tenant — which is what the
#: cross-tenant replica-group check keys on.
AUDIT_TENANTS = 4
AUDIT_FLEET_MESH = (2, 2, 2)
AUDIT_TENANT_BLOCK = AUDIT_DEVICES // AUDIT_FLEET_MESH[0]
#: Ring capacity for the round-trace audit entrypoint: small enough that
#: the ring's argument bytes stay a rounding error next to the state, big
#: enough that the soak below (QUIESCENT_SOAK_ROUNDS rounds) wraps it —
#: the cursor fact is measured across a wrap, not just a partial fill.
AUDIT_TRACE_R = 8

#: Relative tolerance + absolute slack for the temp/codegen memory
#: comparison: XLA's buffer assignment may legitimately wobble a little
#: between versions; growth beyond this is a real regression.
MEMORY_REL_TOL = 0.10
MEMORY_ABS_SLACK = 4096

#: Memory keys compared exactly (shape-determined) vs under tolerance
#: (scheduler-determined).
_EXACT_MEMORY_KEYS = ("argument_bytes", "output_bytes")
_TOLERANT_MEMORY_KEYS = ("temp_bytes", "generated_code_bytes")

_REGEN_HINT = (
    "if this compiled-program change is intentional, regenerate via "
    "`python tools/staticcheck.py --update-hlo-lock` and review the diff"
)


# -- program registry -------------------------------------------------------


def _build_registry() -> "Dict[str, Dict[str, Any]]":
    """name -> {"jit": jitted, "args": tuple, "donated_leaves": int,
    "waiver": Optional[str]} for every registered engine entrypoint, at the
    audit shapes. Imports jax and the engine lazily: the rest of the
    analysis package stays importable without a backend."""
    import jax
    import jax.numpy as jnp

    from rapid_tpu.models.state import initial_telemetry, initial_trace
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        engine_step_impl,
        engine_step_telem_impl,
        engine_step_trace_impl,
        run_to_decision_impl,
        run_until_membership_impl,
        sync_checksum_impl,
    )
    from rapid_tpu.parallel.mesh import (
        make_mesh,
        make_sharded_step,
        make_sharded_step_telem,
        make_sharded_wave,
        shard_faults,
        shard_pytree,
        shard_state,
        telemetry_shardings,
    )

    vc = VirtualCluster.create(
        AUDIT_N - AUDIT_DEVICES, n_slots=AUDIT_N, k=AUDIT_K, h=3, l=1,
        fd_threshold=2, cohorts=AUDIT_C, delivery_spread=2, seed=0,
    )
    vc.assign_cohorts_roundrobin()
    cfg = vc.cfg
    state, faults = vc.state, vc.faults
    state_leaves = len(jax.tree_util.tree_leaves(state))

    # The compact-state twin (ISSUE 13): identical geometry/seed, state
    # stored at the config-derived narrow dtypes. Registered so the lock
    # freezes the per-device argument-byte saving of the [k,n]/[c,n]-
    # dominated entrypoints against the wide layout above — and so any
    # future compiled-program drift of the compact path fails the gate
    # like every other entrypoint.
    vc_c = VirtualCluster.create(
        AUDIT_N - AUDIT_DEVICES, n_slots=AUDIT_N, k=AUDIT_K, h=3, l=1,
        fd_threshold=2, cohorts=AUDIT_C, delivery_spread=2, seed=0,
        compact=True,
    )
    vc_c.assign_cohorts_roundrobin()
    cfg_c = vc_c.cfg
    state_c, faults_c = vc_c.state, vc_c.faults

    registry: Dict[str, Dict[str, Any]] = {
        "step": {
            "jit": jax.jit(
                lambda s, f: engine_step_impl(cfg, s, f), donate_argnums=(0,)
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        },
        "run_to_decision": {
            "jit": jax.jit(
                lambda s, f: run_to_decision_impl(cfg, s, f, jnp.int32(96)),
                donate_argnums=(0,),
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        },
        "run_until_membership": {
            "jit": jax.jit(
                lambda s, f: run_until_membership_impl(
                    cfg, s, f, jnp.int32(AUDIT_N - AUDIT_DEVICES),
                    jnp.int32(192), 8, jnp.int32(0),
                ),
                donate_argnums=(0,),
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        },
        "sync": {
            "jit": jax.jit(sync_checksum_impl),
            "args": (state, faults),
            "donated_leaves": 0,
        },
        # Only the compact STEP is registered (the PR-9 convention that
        # kept the 2-D step unregistered): the wave's argument surface is
        # byte-identical to the step's modulo three trailing int32 control
        # scalars, so the step alone freezes the compaction saving, while
        # a second compact while-loop compile would cost ~10 s of every
        # tier-1 session. The compact wave path stays differentially
        # driven against the wide oracle in tests/test_state_compaction.py
        # (the adverse grid rides check.sh's unfiltered pass).
        "step_compact": {
            "jit": jax.jit(
                lambda s, f: engine_step_impl(cfg_c, s, f), donate_argnums=(0,)
            ),
            "args": (state_c, faults_c),
            "donated_leaves": state_leaves,
        },
    }
    # The telemetry-plane step (ISSUE 16): identical geometry with
    # telemetry=1 and the TelemetryLanes pytree donated alongside the
    # state. Registered so the lock freezes the plane's entire compiled
    # cost — the lanes' argument bytes, ZERO new hot-loop collectives
    # (the digest is a separate boundary dispatch, never traced here),
    # and zero host<->device transfer ops. Only the STEP is registered
    # (the step_compact convention): the telem wave shares the round
    # body and every extra while-loop compile costs ~10 s of tier-1;
    # the wave path is differentially driven against the telemetry=0
    # oracle in tests/test_telemetry_plane.py.
    cfg_t = cfg._replace(telemetry=1)
    telem = initial_telemetry(cfg_t)
    telem_leaves = len(jax.tree_util.tree_leaves(telem))
    registry["step_telem"] = {
        "jit": jax.jit(
            lambda s, t, f: engine_step_telem_impl(cfg_t, s, t, f),
            donate_argnums=(0, 1),
        ),
        "args": (state, telem, faults),
        "donated_leaves": state_leaves + telem_leaves,
    }
    # The round-trace ring step (ISSUE 17): the telemetry geometry with an
    # AUDIT_TRACE_R-slot TraceRing donated alongside the state and lanes.
    # Registered so the lock freezes the ring's entire compiled footprint —
    # its argument bytes, ZERO new hot-loop collectives (ring writes are
    # slot-local dynamic-update-slices; the digest is a boundary dispatch,
    # never traced here) and zero host<->device transfer ops. Only the STEP
    # is registered (the step_telem convention): the fused and fleet trace
    # variants share the round body, and each extra while-loop compile
    # costs ~10 s of tier-1 — those paths are differentially driven
    # against the trace=0 oracle in tests/test_trace_ring.py.
    cfg_tr = cfg_t._replace(trace=AUDIT_TRACE_R)
    trace_ring = initial_trace(cfg_tr)
    trace_leaves = len(jax.tree_util.tree_leaves(trace_ring))
    registry["step_trace"] = {
        "jit": jax.jit(
            lambda s, t, r, f: engine_step_trace_impl(cfg_tr, s, t, r, f),
            donate_argnums=(0, 1, 2),
        ),
        "args": (state, telem, trace_ring, faults),
        "donated_leaves": state_leaves + telem_leaves + trace_leaves,
    }
    if jax.device_count() >= AUDIT_DEVICES:
        mesh = make_mesh(jax.devices()[:AUDIT_DEVICES])
        sh_state = shard_state(state, mesh)
        sh_faults = shard_faults(faults, mesh)
        registry["sharded_step"] = {
            "jit": make_sharded_step(cfg, mesh),
            "args": (sh_state, sh_faults),
            "donated_leaves": state_leaves,
        }
        registry["sharded_wave"] = {
            "jit": make_sharded_wave(cfg, mesh),
            "args": (
                sh_state, sh_faults, jnp.int32(AUDIT_N - AUDIT_DEVICES),
                jnp.int32(192), jnp.int32(0),
            ),
            "donated_leaves": state_leaves,
        }
        # The telemetry step under GSPMD: proves the plane adds zero
        # collectives on a real mesh too (the [c, n] lanes accumulate
        # shard-locally), not just on one device.
        sh_telem = shard_pytree(telem, telemetry_shardings(mesh), mesh=mesh)
        registry["sharded_step_telem"] = {
            "jit": make_sharded_step_telem(cfg_t, mesh),
            "args": (sh_state, sh_telem, sh_faults),
            "donated_leaves": state_leaves + telem_leaves,
        }
        # The 2-D ('cohort', 'nodes') variant — the 1M+ headline bench
        # configuration: same devices, reshaped so the cohort lanes and the
        # [c, n] watermark state genuinely shard over the cohort axis. The
        # 1-D entries above stay registered as the hot-loop baseline the
        # 2-D program is budget-compared against (test_hlo_gate.py). Only
        # the WAVE is registered: it contains the step's entire compiled
        # surface (round body + cond-gated view change + per-cut prologue)
        # and every extra two-axis GSPMD compile costs ~10 s of the tier-1
        # session — the step variant is still differentially driven against
        # the single-device engine in tests/test_parallel_2d.py and by the
        # multichip dry run.
        mesh2d = make_mesh(
            jax.devices()[:AUDIT_DEVICES],
            shape=(AUDIT_COHORT_DEVICES, AUDIT_DEVICES // AUDIT_COHORT_DEVICES),
        )
        sh2_state = shard_state(state, mesh2d)
        sh2_faults = shard_faults(faults, mesh2d)
        registry["sharded2d_wave"] = {
            "jit": make_sharded_wave(cfg, mesh2d),
            "args": (
                sh2_state, sh2_faults, jnp.int32(AUDIT_N - AUDIT_DEVICES),
                jnp.int32(192), jnp.int32(0),
            ),
            "donated_leaves": state_leaves,
        }
        # The multi-tenant fleet pair (rapid_tpu/tenancy) on the 3-D
        # ('tenant', 'cohort', 'nodes') reshape of the same devices:
        # AUDIT_TENANTS independent clusters with per-tenant H/L/fd knob
        # lanes, batched into one program. These entries carry
        # ``tenant_block`` so extract_facts computes the cross-tenant
        # replica-group count — the budget the fleet freezes at ZERO
        # (tenants never communicate; a group spanning two tenant device
        # blocks can never become a frozen fact).
        from jax.sharding import NamedSharding, PartitionSpec

        from rapid_tpu.parallel.mesh import (
            TENANT_AXIS,
            shard_fleet_faults,
            shard_fleet_state,
        )
        from rapid_tpu.tenancy.fleet import (
            TenantFleet,
            knob_shardings,
            make_fleet_step,
            make_fleet_wave,
        )

        tenants = []
        for i in range(AUDIT_TENANTS):
            h, l = ((3, 1), (4, 2))[i % 2]
            tvc = VirtualCluster.create(
                AUDIT_N - AUDIT_DEVICES, n_slots=AUDIT_N, k=AUDIT_K, h=h,
                l=l, fd_threshold=2, cohorts=AUDIT_C, delivery_spread=2,
                seed=i,
            )
            tvc.assign_cohorts_roundrobin()
            tenants.append(tvc)
        fleet = TenantFleet.from_clusters(tenants)
        mesh3d = make_mesh(jax.devices()[:AUDIT_DEVICES], shape=AUDIT_FLEET_MESH)
        fl_state = shard_fleet_state(fleet.state, mesh3d)
        fl_faults = shard_fleet_faults(fleet.faults, mesh3d)
        fl_knobs = jax.tree_util.tree_map(
            jax.device_put, fleet.knobs, knob_shardings(mesh3d)
        )
        lane = NamedSharding(mesh3d, PartitionSpec(TENANT_AXIS))
        targets = jax.device_put(
            jnp.full((AUDIT_TENANTS,), AUDIT_N - AUDIT_DEVICES, jnp.int32),
            lane,
        )
        min_cuts = jax.device_put(
            jnp.zeros((AUDIT_TENANTS,), jnp.int32), lane
        )
        registry["fleet3d_step"] = {
            "jit": make_fleet_step(fleet.cfg, mesh3d),
            "args": (fl_state, fl_faults, fl_knobs),
            "donated_leaves": state_leaves,
            "tenant_block": AUDIT_TENANT_BLOCK,
        }
        registry["fleet3d_wave"] = {
            "jit": make_fleet_wave(fleet.cfg, mesh3d),
            "args": (fl_state, fl_faults, fl_knobs, targets, jnp.int32(64),
                     min_cuts),
            "donated_leaves": state_leaves,
            "tenant_block": AUDIT_TENANT_BLOCK,
        }
    return registry


#: Entrypoints :func:`build_ladder_spec` can rebuild at arbitrary geometry
#: — the single-device dispatch surface plus the meshless vmapped fleet
#: step. The cost-model family (tools/analysis/cost_model.py) sweeps these
#: across its N/K/tenant ladders; the mesh-gated GSPMD entrypoints are
#: deliberately absent (a ladder of sharded compiles would cost minutes of
#: every tier-1 session — their base-shape facts still feed the quiescent
#: cost block via :func:`collect_facts`).
LADDER_ENTRYPOINTS = (
    "step",
    "run_to_decision",
    "run_until_membership",
    "sync",
    "step_compact",
    "step_telem",
    "step_trace",
    "fleet_step",
)


def build_ladder_spec(
    name: str,
    n: int,
    k: int,
    c: int = AUDIT_C,
    tenants: Optional[int] = None,
) -> Dict[str, Any]:
    """One registry-shaped spec (``{"jit", "args", "donated_leaves"}``) for
    a single entrypoint at an arbitrary ``(n, k, c)`` geometry — the
    cost-model ladder plumbing. At the audit geometry this builds exactly
    what :func:`_build_registry` builds for the same name (the cost ladder
    reuses the session's :func:`collect_facts` entry for that point instead
    of recompiling); at every other point the caller compiles fresh via
    :func:`_compile_program`. ``fleet_step`` is the MESHLESS vmapped
    :func:`rapid_tpu.tenancy.fleet.fleet_step_impl` over ``tenants``
    per-tenant clusters of ``n`` slots each — usable without the 8-device
    mesh, which is what keeps the tenant ladder inside the tier-1 budget."""
    import jax
    import jax.numpy as jnp

    if name not in LADDER_ENTRYPOINTS:
        raise ValueError(f"unknown ladder entrypoint {name!r}")

    from rapid_tpu.models.state import initial_telemetry, initial_trace
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        engine_step_impl,
        engine_step_telem_impl,
        engine_step_trace_impl,
        run_to_decision_impl,
        run_until_membership_impl,
        sync_checksum_impl,
    )

    if name == "fleet_step":
        from rapid_tpu.tenancy.fleet import TenantFleet, fleet_step_impl

        clusters = []
        for i in range(int(tenants or 1)):
            h, l = ((3, 1), (4, 2))[i % 2]
            tvc = VirtualCluster.create(
                n - AUDIT_DEVICES, n_slots=n, k=k, h=h, l=l, fd_threshold=2,
                cohorts=c, delivery_spread=2, seed=i,
            )
            tvc.assign_cohorts_roundrobin()
            clusters.append(tvc)
        fleet = TenantFleet.from_clusters(clusters)
        fcfg = fleet.cfg
        return {
            "jit": jax.jit(
                lambda s, f, kb: fleet_step_impl(fcfg, s, f, kb),
                donate_argnums=(0,),
            ),
            "args": (fleet.state, fleet.faults, fleet.knobs),
            "donated_leaves": len(jax.tree_util.tree_leaves(fleet.state)),
        }

    vc = VirtualCluster.create(
        n - AUDIT_DEVICES, n_slots=n, k=k, h=3, l=1, fd_threshold=2,
        cohorts=c, delivery_spread=2, seed=0, compact=(name == "step_compact"),
    )
    vc.assign_cohorts_roundrobin()
    cfg, state, faults = vc.cfg, vc.state, vc.faults
    state_leaves = len(jax.tree_util.tree_leaves(state))
    if name in ("step", "step_compact"):
        return {
            "jit": jax.jit(
                lambda s, f: engine_step_impl(cfg, s, f), donate_argnums=(0,)
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        }
    if name == "run_to_decision":
        return {
            "jit": jax.jit(
                lambda s, f: run_to_decision_impl(cfg, s, f, jnp.int32(96)),
                donate_argnums=(0,),
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        }
    if name == "run_until_membership":
        return {
            "jit": jax.jit(
                lambda s, f: run_until_membership_impl(
                    cfg, s, f, jnp.int32(n - AUDIT_DEVICES),
                    jnp.int32(192), 8, jnp.int32(0),
                ),
                donate_argnums=(0,),
            ),
            "args": (state, faults),
            "donated_leaves": state_leaves,
        }
    if name == "sync":
        return {
            "jit": jax.jit(sync_checksum_impl),
            "args": (state, faults),
            "donated_leaves": 0,
        }
    if name == "step_telem":
        cfg_t = cfg._replace(telemetry=1)
        telem = initial_telemetry(cfg_t)
        return {
            "jit": jax.jit(
                lambda s, t, f: engine_step_telem_impl(cfg_t, s, t, f),
                donate_argnums=(0, 1),
            ),
            "args": (state, telem, faults),
            "donated_leaves": (
                state_leaves + len(jax.tree_util.tree_leaves(telem))
            ),
        }
    if name == "step_trace":
        cfg_tr = cfg._replace(telemetry=1, trace=AUDIT_TRACE_R)
        telem = initial_telemetry(cfg_tr)
        ring = initial_trace(cfg_tr)
        return {
            "jit": jax.jit(
                lambda s, t, r, f: engine_step_trace_impl(cfg_tr, s, t, r, f),
                donate_argnums=(0, 1, 2),
            ),
            "args": (state, telem, ring, faults),
            "donated_leaves": (
                state_leaves
                + len(jax.tree_util.tree_leaves(telem))
                + len(jax.tree_util.tree_leaves(ring))
            ),
        }
    raise ValueError(f"unknown ladder entrypoint {name!r}")


# -- fact extraction --------------------------------------------------------


def extract_facts(
    compiled: Any,
    donated_leaves: int,
    n: int,
    c: int,
    donation_reasons: Optional[List[str]] = None,
    tenant_block: Optional[int] = None,
) -> Dict[str, Any]:
    """All budget-relevant facts of one compiled executable. ``rows`` holds
    the per-collective detail (the evidence-table grain); everything else
    is the lock grain. ``tenant_block`` (devices per tenant slice, fleet
    entrypoints only) additionally counts collectives whose replica groups
    span tenant blocks — the ``cross_tenant_collectives`` fact the fleet
    budget freezes at zero."""
    text = compiled.as_text()
    rows = hlo_facts.audit_collectives(text, n, c)
    collectives: Dict[str, Dict[str, Any]] = {}
    unknown: List[str] = []
    for row in rows:
        key = f"{row['location']}/{row['kind']}"
        entry = collectives.setdefault(key, {"count": 0, "bytes": 0, "max_bytes": 0})
        entry["count"] += 1
        entry["bytes"] += row["bytes"]
        entry["max_bytes"] = max(entry["max_bytes"], row["bytes"])
        unknown.extend(row["unknown_dtypes"])
    for entry in collectives.values():
        # Scale class of the LARGEST single payload in the group: "class
        # increase" means one collective jumped a scale tier ([n] -> [c,n]),
        # not that a count bump nudged the aggregate over a threshold.
        entry["class"] = hlo_facts.payload_class(entry["max_bytes"], n, c)
    aliased = len(hlo_facts.input_output_aliases(text))
    memory = {}
    analysis = None
    try:
        analysis = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — memory analysis is platform-optional
        # (mirrors engine_telemetry.compiled_memory_analysis); the lock
        # simply omits the section and the comparison is presence-gated.
        analysis = None
    if analysis is not None:
        memory = {
            "argument_bytes": int(analysis.argument_size_in_bytes),
            "output_bytes": int(analysis.output_size_in_bytes),
            "temp_bytes": int(analysis.temp_size_in_bytes),
            "generated_code_bytes": int(analysis.generated_code_size_in_bytes),
        }
    facts = {
        "collectives": collectives,
        # Entry-signature bytes per dtype: the artifact-level proof of the
        # state-compaction policy (compact entrypoints carry s8/s16/u8
        # argument lanes; the wide oracle only s32/u32/pred). Informational
        # in the lock — argument_bytes is the exact-compared budget; an
        # unknown dtype here surfaces through the same hlo-unknown-dtype
        # finding as the payload accounting.
        "parameter_dtype_bytes": hlo_facts.entry_parameter_bytes(
            text, unknown=unknown
        ),
        "transfers": hlo_facts.count_transfer_ops(text),
        "donation": {
            "donated_leaves": donated_leaves,
            "aliased": aliased,
            "dropped": max(donated_leaves - aliased, 0),
            "reasons": sorted(set(donation_reasons or [])),
        },
        "memory": memory,
        # Normalized ``compiled.cost_analysis()`` (flops / bytes_accessed
        # where the backend exposes them, None otherwise — never guessed).
        # Informational to the HLO lock (facts_to_lock keeps its explicit
        # key list, so this cannot perturb hlo.lock.json); budget grain for
        # the cost-model ladder fit (tools/analysis/cost_model.py).
        "cost": hlo_facts.compiled_cost_analysis(compiled),
        "unknown_dtypes": sorted(set(unknown)),
        "rows": rows,
    }
    if tenant_block is not None:
        facts["cross_tenant_collectives"] = sum(
            1 for row in rows
            if hlo_facts.groups_cross_blocks(row["groups"], tenant_block)
        )
    return facts


def _compile_program(spec: Dict[str, Any]) -> Tuple[Any, List[str]]:
    """Lower+compile one registry entry, capturing XLA/jax donation
    complaints (the "Some donated buffers were not usable" class) as the
    drop reasons the findings report."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = spec["jit"].lower(*spec["args"]).compile()
    reasons = [
        str(w.message).splitlines()[0]
        for w in caught
        if "donat" in str(w.message).lower()
    ]
    return compiled, reasons


#: (facts, complete) — ``complete`` records whether the sharded mesh
#: entrypoints were included, so a partial (observational) collection can
#: never satisfy the lockfile gate's full-registry requirement.
_FACTS_CACHE: Optional[Tuple[Dict[str, Any], bool]] = None

#: Rounds of the zero-churn telemetry soak behind the
#: ``quiescent_round_activity`` lock fact.
QUIESCENT_SOAK_ROUNDS = 16

_TELEMETRY_FACTS_CACHE: Optional[Dict[str, int]] = None


def collect_telemetry_facts(force: bool = False) -> Dict[str, int]:
    """The telemetry plane's own lock block, measured live:

    - ``lane_bytes_per_device`` — the TelemetryLanes argument bytes at the
      audit geometry (single-device grain; on a mesh the [c, n] lanes split
      by the axis sizes like the state they observe);
    - ``quiescent_round_activity`` — every digest counter EXCEPT ``rounds``
      summed after a :data:`QUIESCENT_SOAK_ROUNDS`-round zero-churn soak.
      A healthy plane reads exactly ZERO here: no churn means no alerts, no
      active subjects, no proposals, no decisions — a nonzero value is a
      phantom-activity bug and can never be frozen (``update_hlo_lock``
      refuses it, like a dropped donation).
    """
    global _TELEMETRY_FACTS_CACHE
    if _TELEMETRY_FACTS_CACHE is not None and not force:
        return _TELEMETRY_FACTS_CACHE
    import numpy as np

    from rapid_tpu.models.state import telemetry_bytes_total
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        telemetry_digest,
    )
    from rapid_tpu.utils.engine_telemetry import TELEMETRY_DIGEST_FIELDS

    with _scoped_disable_persistent_cache():
        vc = VirtualCluster.create(
            AUDIT_N - AUDIT_DEVICES, n_slots=AUDIT_N, k=AUDIT_K, h=3, l=1,
            fd_threshold=2, cohorts=AUDIT_C, delivery_spread=2, seed=0,
            telemetry=True,
        )
        vc.assign_cohorts_roundrobin()
        for _ in range(QUIESCENT_SOAK_ROUNDS):
            vc.step()
        # telemetry-fetch-ok: audit boundary — a one-off gate measurement,
        # not an engine hot path.
        digest = np.asarray(telemetry_digest(vc.telem))
    rounds = int(digest[list(TELEMETRY_DIGEST_FIELDS).index("rounds")])
    _TELEMETRY_FACTS_CACHE = {
        "lane_bytes_per_device": int(telemetry_bytes_total(vc.cfg)),
        "quiescent_rounds": rounds,
        "quiescent_round_activity": int(digest.sum()) - rounds,
    }
    return _TELEMETRY_FACTS_CACHE


_TRACE_FACTS_CACHE: Optional[Dict[str, int]] = None


def collect_trace_facts(force: bool = False) -> Dict[str, int]:
    """The round-trace ring's own lock block, measured live:

    - ``ring_bytes_per_device`` — the TraceRing argument bytes at the audit
      geometry with ``capacity`` = :data:`AUDIT_TRACE_R` slots;
    - ``soak_cursor_delta`` — ring cursor minus the telemetry plane's round
      counter after a :data:`QUIESCENT_SOAK_ROUNDS`-round zero-churn soak
      (which wraps the AUDIT_TRACE_R-slot ring, so the cursor fact covers
      rotation too). A healthy recorder reads exactly ZERO here: every
      round writes exactly one record, wrap or no wrap — a nonzero delta
      is a miscounting recorder and can never be frozen (``update_hlo_lock``
      refuses it, like phantom telemetry activity).
    """
    global _TRACE_FACTS_CACHE
    if _TRACE_FACTS_CACHE is not None and not force:
        return _TRACE_FACTS_CACHE

    from rapid_tpu.models.state import trace_bytes_total
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    with _scoped_disable_persistent_cache():
        vc = VirtualCluster.create(
            AUDIT_N - AUDIT_DEVICES, n_slots=AUDIT_N, k=AUDIT_K, h=3, l=1,
            fd_threshold=2, cohorts=AUDIT_C, delivery_spread=2, seed=0,
            telemetry=True, trace=AUDIT_TRACE_R,
        )
        vc.assign_cohorts_roundrobin()
        for _ in range(QUIESCENT_SOAK_ROUNDS):
            vc.step()
        rounds = int(vc.activity["rounds"])
        cursor = int(vc.trace["rounds_recorded"])
    _TRACE_FACTS_CACHE = {
        "ring_bytes_per_device": int(trace_bytes_total(vc.cfg)),
        "capacity": AUDIT_TRACE_R,
        "soak_cursor_delta": cursor - rounds,
    }
    return _TRACE_FACTS_CACHE


class _scoped_disable_persistent_cache:
    """SCOPED: turn jax's persistent compilation cache OFF for the audit
    compiles, restoring the previous config after.

    Hard-won (root-caused via a reproducible segfault): on this jaxlib's
    CPU backend, SHARDED executables deserialized from the persistent
    cache poison the process — later sharded+donated executions (the
    test_parallel equivalence runs) die in native code. The audit compiles
    the sharded step/wave every process, so with a warm cache it would hit
    exactly that deserialize path. Fresh compiles cost ~15 s once per
    process (the session cache absorbs every later consumer) and keep the
    gate's facts coming from a REAL backend compile — also true inside
    bench.py, which deliberately enables the cache process-wide for its
    own single-device workload. (An earlier revision of this note claimed
    single-device deserialization was fine; the bench ``recovery`` drill's
    bit-identity assertion later DISPROVED that — deserialized
    single-device executables corrupt the heap under donated executions
    too, sometimes a glibc abort and sometimes SILENT scribbling over
    unrelated live buffers. bench.py now scopes the cache OFF around that
    drill exactly the way this class scopes it off around the audit, and
    utils/checkpoint.py settles loaded pytrees into executable-owned
    buffers before any donation.)"""

    def __enter__(self) -> None:
        import jax

        self._restore = False
        try:
            self._prev = jax.config.jax_compilation_cache_dir
            jax.config.update("jax_compilation_cache_dir", None)
            self._restore = True
        except Exception:  # noqa: BLE001 — a jax without the knob has no
            # persistent cache to disable; compile proceeds as before.
            pass

    def __exit__(self, *_exc: Any) -> None:
        import jax

        if not self._restore:
            return
        try:
            jax.config.update("jax_compilation_cache_dir", self._prev)
        except Exception:  # noqa: BLE001 — restoring a knob that could not
            # be set back is the same no-op as never having touched it.
            pass


def collect_facts(
    force: bool = False, require_mesh: bool = True
) -> Dict[str, Any]:
    """Compile every registered entrypoint and extract its facts — once per
    process (compiles dominate the gate's cost; every consumer shares this
    cache).

    ``require_mesh=True`` (the lockfile gate): raises RuntimeError when the
    process cannot provide the 8-device mesh — the gate turns that into a
    loud finding rather than silently passing with sharded entrypoints
    unaudited. ``require_mesh=False`` (observational consumers, e.g. the
    bench's ``hlo_audit`` stage on a single-chip backend): audits whatever
    the registry can build — the four single-device entrypoints always,
    the sharded pair when devices allow. A partial collection never
    satisfies a later full-gate call."""
    global _FACTS_CACHE
    import jax

    have_mesh = jax.device_count() >= AUDIT_DEVICES
    if _FACTS_CACHE is not None and not force:
        facts, complete = _FACTS_CACHE
        if complete or not require_mesh:
            return facts
    if require_mesh and not have_mesh:
        raise RuntimeError(
            f"device_program audit needs {AUDIT_DEVICES} devices, have "
            f"{jax.device_count()} — force them before jax initializes "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICES}, as tests/conftest.py and the staticcheck "
            f"CLI do)"
        )
    with _scoped_disable_persistent_cache():
        registry = _build_registry()
        facts = {}
        for name, spec in registry.items():
            compiled, reasons = _compile_program(spec)
            entry = extract_facts(
                compiled, spec["donated_leaves"], AUDIT_N, AUDIT_C,
                donation_reasons=reasons,
                tenant_block=spec.get("tenant_block"),
            )
            if spec.get("waiver"):
                entry["donation"]["waiver"] = spec["waiver"]
            facts[name] = entry
    _FACTS_CACHE = (facts, have_mesh)
    return facts


# -- lock construction + comparison -----------------------------------------


def facts_to_lock(
    facts: Dict[str, Any],
    telemetry: Optional[Dict[str, int]] = None,
    trace: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """The canonical freeze: per-entrypoint collectives/transfers/donation/
    memory, minus the per-row detail (evidence grain, not budget grain).
    ``telemetry`` (from :func:`collect_telemetry_facts`) adds the plane's
    own block — lane bytes and the zero-churn activity fact; ``trace``
    (from :func:`collect_trace_facts`) adds the ring block — ring bytes,
    audit capacity, and the zero cursor-vs-rounds delta."""
    lock: Dict[str, Any] = {
        "audit_config": {
            "n": AUDIT_N, "c": AUDIT_C, "k": AUDIT_K,
            "devices": AUDIT_DEVICES,
            "cohort_devices": AUDIT_COHORT_DEVICES,
            "tenants": AUDIT_TENANTS,
            "fleet_mesh": list(AUDIT_FLEET_MESH),
        },
        "entrypoints": {},
    }
    for name, entry in sorted(facts.items()):
        donation = {
            k: v for k, v in entry["donation"].items() if k != "reasons"
        }
        lock["entrypoints"][name] = {
            "collectives": entry["collectives"],
            "transfers": entry["transfers"],
            "donation": donation,
            "memory": entry["memory"],
            "parameter_dtype_bytes": entry["parameter_dtype_bytes"],
        }
        if "cross_tenant_collectives" in entry:
            lock["entrypoints"][name]["cross_tenant_collectives"] = entry[
                "cross_tenant_collectives"
            ]
    if telemetry is not None:
        lock["telemetry"] = dict(telemetry)
    if trace is not None:
        lock["trace"] = dict(trace)
    return lock


def compare_telemetry_facts(
    current: Dict[str, int], locked: Dict[str, Any], lock_path: str
) -> List[Finding]:
    """Drift report for the lock's ``telemetry`` block. A nonzero
    quiescent activity is its own finding (a phantom-activity bug — never
    freezable); lane-byte or soak-length drift is ordinary lock drift."""
    findings: List[Finding] = []
    if current["quiescent_round_activity"] != 0:
        findings.append(Finding(
            lock_path, 1, "hlo-quiescent-activity",
            f"telemetry plane counted "
            f"{current['quiescent_round_activity']} unit(s) of activity "
            f"over a {current['quiescent_rounds']}-round ZERO-churn soak — "
            f"phantom activity; the quiescent fact is frozen at zero and "
            f"cannot be locked in",
        ))
    for key in ("lane_bytes_per_device", "quiescent_rounds"):
        if locked.get(key) != current[key]:
            findings.append(Finding(
                lock_path, 1, "hlo-lock-drift",
                f"telemetry block: {key} {locked.get(key)} in the lock, "
                f"{current[key]} now — {_REGEN_HINT}",
            ))
    if locked.get("quiescent_round_activity") != 0:
        findings.append(Finding(
            lock_path, 1, "hlo-lock-drift",
            f"telemetry block: quiescent_round_activity must be frozen at "
            f"0, the lock carries "
            f"{locked.get('quiescent_round_activity')!r} — {_REGEN_HINT}",
        ))
    return findings


def compare_trace_facts(
    current: Dict[str, int], locked: Dict[str, Any], lock_path: str
) -> List[Finding]:
    """Drift report for the lock's ``trace`` block. A nonzero cursor delta
    after the soak is its own finding (a miscounting recorder — never
    freezable); ring-byte or capacity drift is ordinary lock drift."""
    findings: List[Finding] = []
    if current["soak_cursor_delta"] != 0:
        findings.append(Finding(
            lock_path, 1, "hlo-trace-cursor",
            f"trace ring cursor drifted {current['soak_cursor_delta']} "
            f"record(s) from the telemetry round counter over the "
            f"zero-churn soak — every round must write exactly one record; "
            f"the cursor fact is frozen at zero and cannot be locked in",
        ))
    for key in ("ring_bytes_per_device", "capacity"):
        if locked.get(key) != current[key]:
            findings.append(Finding(
                lock_path, 1, "hlo-lock-drift",
                f"trace block: {key} {locked.get(key)} in the lock, "
                f"{current[key]} now — {_REGEN_HINT}",
            ))
    if locked.get("soak_cursor_delta") != 0:
        findings.append(Finding(
            lock_path, 1, "hlo-lock-drift",
            f"trace block: soak_cursor_delta must be frozen at 0, the lock "
            f"carries {locked.get('soak_cursor_delta')!r} — {_REGEN_HINT}",
        ))
    return findings


def _within_tolerance(locked: int, current: int) -> bool:
    slack = max(int(locked * MEMORY_REL_TOL), MEMORY_ABS_SLACK)
    return abs(current - locked) <= slack


def compare_facts(
    name: str,
    entry: Dict[str, Any],
    locked: Dict[str, Any],
    loc: Tuple[str, int],
) -> List[Finding]:
    """Budget-drift report for ONE entrypoint against its locked facts,
    each finding naming the entrypoint and the delta. Sections present in
    the lock are enforced; absent sections are skipped (the corpus locks
    pin only the facts each defect class is about)."""
    path, lineno = loc
    findings: List[Finding] = []

    def fail(check: str, message: str) -> None:
        findings.append(Finding(path, lineno, check, f"{message} — {_REGEN_HINT}"))

    if entry["unknown_dtypes"]:
        findings.append(Finding(
            path, lineno, "hlo-unknown-dtype",
            f"{name}: collective payload uses HLO dtype(s) "
            f"{entry['unknown_dtypes']} missing from hlo_facts.DTYPE_BITS — "
            f"payload accounting cannot size them; add the dtype, do not "
            f"guess",
        ))

    # The fleet's hard budget: tenants never communicate. A collective
    # whose replica groups span tenant device blocks is a finding in its
    # own right — never freezable (update_hlo_lock refuses it, like a
    # dropped donation).
    cross = entry.get("cross_tenant_collectives")
    if cross:
        findings.append(Finding(
            path, lineno, "hlo-cross-tenant-collective",
            f"{name}: {cross} collective(s) carry the tenant axis in their "
            f"replica groups — tenants must never communicate; fix the "
            f"batched program (this budget is frozen at ZERO and cannot be "
            f"locked in)",
        ))
    elif (
        "cross_tenant_collectives" in locked
        and locked["cross_tenant_collectives"] != (cross or 0)
    ):
        fail("hlo-lock-drift",
             f"{name}: cross_tenant_collectives "
             f"{locked['cross_tenant_collectives']} in the lock, "
             f"{cross or 0} now")

    if "collectives" in locked:
        cur = entry["collectives"]
        old = locked["collectives"]
        for key in sorted(set(cur) | set(old)):
            location, kind = key.split("/", 1)
            if key not in old:
                hot = "NEW HOT-LOOP collective" if location.startswith(
                    "hot-loop") else "new collective"
                fail("hlo-collective-budget",
                     f"{name}: {hot} {kind} in location {location} "
                     f"({cur[key]['count']} op(s), {cur[key]['bytes']} bytes, "
                     f"class {cur[key]['class']}) not in the HLO lock")
            elif key not in cur:
                fail("hlo-collective-budget",
                     f"{name}: collective {kind} in location {location} "
                     f"vanished since the HLO lock (was "
                     f"{old[key]['count']} op(s), {old[key]['bytes']} bytes)")
            else:
                rank_old = hlo_facts.PAYLOAD_CLASS_RANK[old[key]["class"]]
                rank_cur = hlo_facts.PAYLOAD_CLASS_RANK[cur[key]["class"]]
                if rank_cur > rank_old:
                    fail("hlo-collective-budget",
                         f"{name}: payload-class INCREASE for {kind} in "
                         f"{location}: {old[key]['class']} -> "
                         f"{cur[key]['class']} (largest payload "
                         f"{old[key].get('max_bytes', old[key]['bytes'])} -> "
                         f"{cur[key]['max_bytes']} bytes)")
                elif (cur[key]["count"], cur[key]["bytes"]) != (
                    old[key]["count"], old[key]["bytes"]
                ):
                    fail("hlo-collective-budget",
                         f"{name}: collective budget drift for {kind} in "
                         f"{location}: {old[key]['count']} op(s)/"
                         f"{old[key]['bytes']} bytes -> "
                         f"{cur[key]['count']} op(s)/{cur[key]['bytes']} "
                         f"bytes")

    if "transfers" in locked:
        cur_t = entry["transfers"]
        old_t = locked["transfers"]
        for op in sorted(set(cur_t) | set(old_t)):
            if cur_t.get(op, 0) != old_t.get(op, 0):
                fail("hlo-transfer-budget",
                     f"{name}: host<->device transfer op {op}: "
                     f"{old_t.get(op, 0)} -> {cur_t.get(op, 0)}")

    if "donation" in locked:
        cur_d = entry["donation"]
        old_d = locked["donation"]
        waiver = cur_d.get("waiver") or old_d.get("waiver")
        if cur_d["dropped"] > 0 and not waiver:
            reasons = "; ".join(cur_d.get("reasons", [])) or "no XLA reason captured"
            findings.append(Finding(
                path, lineno, "hlo-donation-dropped",
                f"{name}: {cur_d['dropped']} of {cur_d['donated_leaves']} "
                f"donated buffer(s) NOT aliased in the compiled output "
                f"({reasons}) — donation silently dropped; fix the "
                f"entrypoint or add an explicit registry waiver",
            ))
        elif (cur_d["donated_leaves"], cur_d["aliased"]) != (
            old_d.get("donated_leaves"), old_d.get("aliased")
        ):
            fail("hlo-lock-drift",
                 f"{name}: donation outcome drift: "
                 f"{old_d.get('aliased')}/{old_d.get('donated_leaves')} "
                 f"aliased in the lock, "
                 f"{cur_d['aliased']}/{cur_d['donated_leaves']} now")

    if "memory" in locked and locked["memory"] and entry["memory"]:
        cur_m = entry["memory"]
        old_m = locked["memory"]
        for key in _EXACT_MEMORY_KEYS:
            if key in old_m and cur_m.get(key) != old_m[key]:
                fail("hlo-memory-budget",
                     f"{name}: {key} {old_m[key]} -> {cur_m.get(key)}")
        for key in _TOLERANT_MEMORY_KEYS:
            if key in old_m and not _within_tolerance(
                old_m[key], cur_m.get(key, 0)
            ):
                direction = (
                    "GREW" if cur_m.get(key, 0) > old_m[key] else "shrank"
                )
                fail("hlo-memory-budget",
                     f"{name}: {key} {direction} beyond tolerance: "
                     f"{old_m[key]} -> {cur_m.get(key)} (allowed ±"
                     f"{max(int(old_m[key] * MEMORY_REL_TOL), MEMORY_ABS_SLACK)}"
                     f" bytes)")
    return findings


def compare_lock(
    facts: Dict[str, Any], locked: Dict[str, Any], lock_path: str
) -> List[Finding]:
    findings: List[Finding] = []
    locked_eps: Dict[str, Any] = locked.get("entrypoints", {})
    for name in sorted(set(facts) | set(locked_eps)):
        if name not in locked_eps:
            findings.append(Finding(
                lock_path, 1, "hlo-lock-drift",
                f"entrypoint {name} compiled but has no entry in the HLO "
                f"lock — {_REGEN_HINT}",
            ))
        elif name not in facts:
            findings.append(Finding(
                lock_path, 1, "hlo-lock-drift",
                f"entrypoint {name} is in the HLO lock but no longer "
                f"registered — {_REGEN_HINT}",
            ))
        else:
            findings.extend(
                compare_facts(name, facts[name], locked_eps[name], (lock_path, 1))
            )
    return findings


# -- tree-mode gate ----------------------------------------------------------


def check_hlo_lock(trees: Sequence[Tuple[ast.AST, str]]) -> List[Finding]:
    """Tree-mode gate the driver runs on full sweeps: compile the registered
    entrypoints (session-cached) and compare against the committed lock.
    Presence-gated on the engine sources being part of the sweep, so tests
    that retarget ``core.REPO`` at temporary trees never pay a compile."""
    rels = {rel.replace("\\", "/") for _, rel in trees}
    if not all(src in rels for src in REGISTRY_SOURCES):
        return []
    try:
        facts = collect_facts()
    except RuntimeError as exc:
        return [Finding(HLO_LOCK_REL, 1, "hlo-lock-drift",
                        f"cannot audit compiled programs: {exc}")]
    lock_path = core.REPO / HLO_LOCK_REL
    if not lock_path.exists():
        return [Finding(
            HLO_LOCK_REL, 1, "hlo-lock-drift",
            "HLO lockfile missing — generate it via "
            "`python tools/staticcheck.py --update-hlo-lock`",
        )]
    try:
        locked = json.loads(lock_path.read_text())
    except json.JSONDecodeError as exc:
        return [Finding(
            HLO_LOCK_REL, 1, "hlo-lock-drift",
            f"HLO lockfile is not valid JSON ({exc.msg}) — regenerate via "
            f"`python tools/staticcheck.py --update-hlo-lock`",
        )]
    audit_cfg = {"n": AUDIT_N, "c": AUDIT_C, "k": AUDIT_K,
                 "devices": AUDIT_DEVICES,
                 "cohort_devices": AUDIT_COHORT_DEVICES,
                 "tenants": AUDIT_TENANTS,
                 "fleet_mesh": list(AUDIT_FLEET_MESH)}
    if locked.get("audit_config") != audit_cfg:
        return [Finding(
            HLO_LOCK_REL, 1, "hlo-lock-drift",
            f"HLO lock audit_config {locked.get('audit_config')} does not "
            f"match the registry's {audit_cfg} — {_REGEN_HINT}",
        )]
    findings = compare_lock(facts, locked, HLO_LOCK_REL)
    if "telemetry" not in locked:
        findings.append(Finding(
            HLO_LOCK_REL, 1, "hlo-lock-drift",
            f"HLO lock carries no telemetry block (lane bytes + the "
            f"zero-churn quiescent fact) — {_REGEN_HINT}",
        ))
    else:
        findings.extend(compare_telemetry_facts(
            collect_telemetry_facts(), locked["telemetry"], HLO_LOCK_REL
        ))
    if "trace" not in locked:
        findings.append(Finding(
            HLO_LOCK_REL, 1, "hlo-lock-drift",
            f"HLO lock carries no trace block (ring bytes + the zero "
            f"cursor-vs-rounds soak fact) — {_REGEN_HINT}",
        ))
    else:
        findings.extend(compare_trace_facts(
            collect_trace_facts(), locked["trace"], HLO_LOCK_REL
        ))
    return findings


def compaction_differential_ok() -> Optional[str]:
    """Run a small mixed crash+join scenario through the WIDE engine and
    the COMPACT engine (same geometry/seed) and compare the widened compact
    state leaf-for-leaf. Returns None on bit-identity, else a message
    naming the first divergent lane. ``update_hlo_lock`` refuses to freeze
    new memory budgets while this disagrees: a compact layout that has
    drifted from its oracle must be fixed, not locked in."""
    import numpy as np

    from rapid_tpu.models.state import widen_state
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    def drive(compact: bool) -> VirtualCluster:
        vc = VirtualCluster.create(
            56, n_slots=64, k=3, h=3, l=1, cohorts=4, fd_threshold=2,
            delivery_spread=1, seed=17, compact=compact,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([1, 9, 20])
        vc.inject_join_wave([60, 61])
        vc.run_until_membership(55, min_cuts=2)
        return vc

    wide, compact = drive(False), drive(True)
    widened = widen_state(compact.cfg, compact.state)
    for field in wide.state._fields:
        a = np.asarray(getattr(wide.state, field))
        b = np.asarray(getattr(widened, field))
        if a.dtype != b.dtype or not (a == b).all():
            return (
                f"wide<->compact differential disagrees on state lane "
                f"{field!r} (crash+join scenario at n=64) — fix the "
                f"compaction layer before regenerating the lock"
            )
    if wide.config_id != compact.config_id:
        return "wide<->compact differential disagrees on the configuration id"
    return None


def trace_differential_ok() -> Optional[str]:
    """Run the compaction differential's crash+join scenario through the
    telemetry engine with the trace ring OFF and ON (same geometry/seed)
    and compare state AND telemetry leaf-for-leaf. Returns None on
    bit-identity, else a message naming the first divergent lane.
    ``update_hlo_lock`` refuses while this disagrees: the ring is
    write-only by construction, so a trace knob that perturbs the engine
    or its telemetry is a recorder bug that must be fixed, not locked
    in."""
    import numpy as np

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    def drive(trace: int) -> VirtualCluster:
        vc = VirtualCluster.create(
            56, n_slots=64, k=3, h=3, l=1, cohorts=4, fd_threshold=2,
            delivery_spread=1, seed=17, telemetry=True, trace=trace,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([1, 9, 20])
        vc.inject_join_wave([60, 61])
        vc.run_until_membership(55, min_cuts=2)
        return vc

    off, on = drive(0), drive(AUDIT_TRACE_R)
    for label, a_tree, b_tree in (
        ("state", off.state, on.state),
        ("telemetry", off.telem, on.telem),
    ):
        for field in a_tree._fields:
            a = np.asarray(getattr(a_tree, field))
            b = np.asarray(getattr(b_tree, field))
            if a.dtype != b.dtype or not (a == b).all():
                return (
                    f"trace-on<->trace-off differential disagrees on "
                    f"{label} lane {field!r} (crash+join scenario at n=64) "
                    f"— the ring must be write-only; fix the trace layer "
                    f"before regenerating the lock"
                )
    if off.config_id != on.config_id:
        return (
            "trace-on<->trace-off differential disagrees on the "
            "configuration id"
        )
    return None


def update_hlo_lock() -> Tuple[List[Finding], Optional[Path]]:
    """Regenerate the lockfile from freshly-collected facts. Refuses while
    an unknown dtype, an unwaived dropped donation, a wide<->compact state
    differential disagreement, or a trace-on<->trace-off differential
    disagreement is present — a budget the gate would immediately fail (or
    a compact layout / trace ring that no longer matches its oracle) must
    be fixed, not frozen."""
    try:
        facts = collect_facts()
    except RuntimeError as exc:
        return [Finding(HLO_LOCK_REL, 1, "hlo-lock-drift", str(exc))], None
    blocking: List[Finding] = []
    for name, entry in sorted(facts.items()):
        blocking.extend(
            f for f in compare_facts(name, entry, {"donation": {}}, (HLO_LOCK_REL, 1))
            if f.check in ("hlo-unknown-dtype", "hlo-donation-dropped",
                           "hlo-cross-tenant-collective")
        )
    mismatch = compaction_differential_ok()
    if mismatch:
        blocking.append(Finding(HLO_LOCK_REL, 1, "hlo-lock-drift", mismatch))
    mismatch_tr = trace_differential_ok()
    if mismatch_tr:
        blocking.append(Finding(HLO_LOCK_REL, 1, "hlo-lock-drift", mismatch_tr))
    telem_facts = collect_telemetry_facts()
    if telem_facts["quiescent_round_activity"] != 0:
        # A zero-churn soak with nonzero activity counters is a telemetry
        # bug, not a fact to freeze.
        blocking.append(Finding(
            HLO_LOCK_REL, 1, "hlo-quiescent-activity",
            f"refusing to freeze quiescent_round_activity="
            f"{telem_facts['quiescent_round_activity']} — the zero-churn "
            f"soak must read exactly zero activity",
        ))
    trace_facts = collect_trace_facts()
    if trace_facts["soak_cursor_delta"] != 0:
        # A ring whose cursor disagrees with the round counter is a
        # recorder bug, not a fact to freeze.
        blocking.append(Finding(
            HLO_LOCK_REL, 1, "hlo-trace-cursor",
            f"refusing to freeze soak_cursor_delta="
            f"{trace_facts['soak_cursor_delta']} — every soak round must "
            f"write exactly one trace record",
        ))
    if blocking:
        return blocking, None
    lock_path = core.REPO / HLO_LOCK_REL
    payload = {
        "_comment": (
            "Frozen compiled-program facts for the registered engine "
            "entrypoints on the forced 8-device CPU mesh: collectives by "
            "location/kind (count, payload bytes, scale class), "
            "host<->device transfer ops, donation outcomes, and XLA memory "
            "analysis. Generated by `python tools/staticcheck.py "
            "--update-hlo-lock`; do not edit by hand — any drift from the "
            "live compiled artifacts fails the staticcheck gate."
        ),
        **facts_to_lock(facts, telemetry=telem_facts, trace=trace_facts),
    }
    lock_path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return [], lock_path


# -- per-file mode (the seeded lint corpus) ---------------------------------


def _program_key_linenos(tree: ast.AST) -> Dict[str, int]:
    """lineno of each string key in the module's HLO_AUDIT_PROGRAMS dict
    literal — where corpus findings anchor (the `# expect:` markers sit on
    these lines)."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "HLO_AUDIT_PROGRAMS"
            and isinstance(node.value, ast.Dict)
        ):
            return {
                key.value: key.lineno
                for key in node.value.keys
                if isinstance(key, ast.Constant) and isinstance(key.value, str)
            }
    return {}


def check_device_program(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    """Corpus mode: compile the module's own miniature programs and compare
    them against its inline ``HLO_LOCK``. Modules without an
    ``HLO_AUDIT_PROGRAMS`` registry are skipped outright (this check never
    executes ordinary library files)."""
    src = source if source is not None else path.read_text()
    if "HLO_AUDIT_PROGRAMS" not in src:
        return []
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    linenos = _program_key_linenos(tree)
    if not linenos:
        return []
    rel = core.rel(path)
    namespace: Dict[str, Any] = {"__name__": f"_hlo_corpus_{path.stem}"}
    exec(compile(src, str(path), "exec"), namespace)  # noqa: S102 — the
    # corpus is this repo's own fixture tree; per-file mode only ever runs
    # on explicitly-named files, never on sweeps.
    programs = namespace["HLO_AUDIT_PROGRAMS"]
    locked = namespace.get("HLO_LOCK", {})
    n = namespace.get("AUDIT_N", AUDIT_N)
    c = namespace.get("AUDIT_C", AUDIT_C)
    findings: List[Finding] = []
    for name, builder in programs.items():
        spec = builder()
        compiled, reasons = _compile_program(spec)
        entry = extract_facts(
            compiled, spec.get("donated_leaves", 0), n, c,
            donation_reasons=reasons,
        )
        if spec.get("waiver"):
            entry["donation"]["waiver"] = spec["waiver"]
        findings.extend(compare_facts(
            name, entry, locked.get(name, {}),
            (rel, linenos.get(name, 1)),
        ))
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))
