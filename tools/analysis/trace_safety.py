"""Check family 6: JAX jit trace-safety (purity + staticness lint).

Functions under ``jax.jit`` execute their Python body ONCE per trace, then
replay the captured computation: a Python side effect fires on trace, not
per call; a wall-clock or RNG-module read bakes one trace-time value into
the compiled program forever; and an ``if``/``while`` on a traced value
raises ``TracerBoolConversionError`` — but only on the first call that
reaches it, which is exactly the kind of latent error the quorum-math
kernels in ``rapid_tpu/ops/`` cannot afford (the decision rule must
vectorize identically on every invocation).

For every function decorated ``@jax.jit`` / ``@partial(jax.jit, ...)`` /
``@functools.partial(jax.jit, ...)`` — or wrapped at module level via
``g = jax.jit(f, ...)`` — this checks:

- ``jit-side-effect`` — ``print`` calls (``jax.debug.print`` is the
  sanctioned spelling), ``global``/``nonlocal`` declarations, mutation of
  closed-over/global containers, stores to free names' attributes or
  subscripts, and trace-time impure reads: ``time.*`` wall clocks,
  ``datetime.now``, and Python-RNG module draws (``random.*``,
  ``np.random.*`` — device RNG goes through ``jax.random`` keys).
- ``jit-traced-branch`` — an ``if``/``while`` whose test reads a traced
  (non-``static_argnames``/``static_argnums``) parameter directly.
  Exempt, because they are resolved at trace time: ``x is None`` /
  ``x is not None`` pytree-structure tests, and ``.shape``/``.ndim``/
  ``.dtype``/``.size`` metadata reads.

Resolution is conservative (skip-don't-guess): only decorations that
statically resolve to ``jax.jit`` through this module's own imports are
analyzed, and only direct parameter reads convict a branch.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from . import core
from .core import Finding

_MUTATORS = core.MUTATING_CONTAINER_METHODS

TRACE_SAFETY_PREFIXES = ("rapid_tpu/ops/",)

_WALL_CLOCK_ATTRS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns",
})

_RNG_ATTRS = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "getrandbits", "gauss", "normalvariate", "seed",
})

_STATIC_METADATA_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Module-level name -> dotted runtime path, for resolving ``jax.jit``
    and ``partial`` spellings through whatever aliases the file uses."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                aliases[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                if alias.name != "*":
                    aliases[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return aliases


def _dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value, aliases)
        return f"{base}.{node.attr}" if base else None
    return None


def _static_params(
    call: Optional[ast.Call], fn: ast.AST
) -> Optional[Set[str]]:
    """Parameter names pinned static by a ``jit``/``partial(jit, ...)``
    call's ``static_argnames``/``static_argnums``; None = unresolvable
    (dynamic spec: skip the function, don't guess)."""
    static: Set[str] = set()
    if call is None:
        return static
    args = fn.args
    positional = [a.arg for a in (*args.posonlyargs, *args.args)]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = kw.value
            if isinstance(names, ast.Constant) and isinstance(names.value, str):
                static.add(names.value)
            elif isinstance(names, (ast.Tuple, ast.List)):
                for elt in names.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        static.add(elt.value)
                    else:
                        return None
            else:
                return None
        elif kw.arg == "static_argnums":
            nums = kw.value
            elts = (
                nums.elts if isinstance(nums, (ast.Tuple, ast.List)) else [nums]
            )
            for elt in elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    if elt.value < len(positional):
                        static.add(positional[elt.value])
                else:
                    return None
    return static


def _jitted_functions(
    tree: ast.AST, aliases: Dict[str, str]
) -> List[Tuple[ast.AST, Set[str]]]:
    """(function node, static param names) for every statically-resolvable
    jit application in the module."""
    out: List[Tuple[ast.AST, Set[str]]] = []
    partials = {"functools.partial", "partial"}
    by_name = {
        n.name: n
        for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _dotted(dec, aliases) == "jax.jit":
                    out.append((node, set()))
                elif (
                    isinstance(dec, ast.Call)
                    and _dotted(dec.func, aliases) in partials
                    and dec.args
                    and _dotted(dec.args[0], aliases) == "jax.jit"
                ):
                    static = _static_params(dec, node)
                    if static is not None:
                        out.append((node, static))
    # Module-level wrapping: g = jax.jit(f, static_argnums=...)
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and _dotted(node.value.func, aliases) == "jax.jit"
            and node.value.args
            and isinstance(node.value.args[0], ast.Name)
            and node.value.args[0].id in by_name
        ):
            fn = by_name[node.value.args[0].id]
            static = _static_params(node.value, fn)
            if static is not None:
                out.append((fn, static))
    return out


def _bound_names(fn: ast.AST) -> Set[str]:
    """Every name bound anywhere within the function's scope tree (params,
    assignments, loop/with/comprehension targets, nested defs and their
    params): mutating one of these is traced-local, not a side effect."""
    bound: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = node.args
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                bound.add(arg.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            if not isinstance(node, ast.Lambda):
                bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name != "*":
                    bound.add(alias.asname or alias.name.split(".")[0])
    return bound


def _check_side_effects(
    fn: ast.AST, rel: str, findings: List[Finding]
) -> None:
    bound = _bound_names(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id == "print" and "print" not in bound:
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"print() inside jitted {fn.name!r} fires once "
                            "per trace, not per call — use jax.debug.print")
                )
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            findings.append(
                Finding(rel, node.lineno, "jit-side-effect",
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                        f"write inside jitted {fn.name!r}: the rebinding "
                        "happens at trace time only")
            )
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            base = node.func.value
            attr = node.func.attr
            if (
                attr in _MUTATORS
                and isinstance(base, ast.Name)
                and base.id not in bound
            ):
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"mutation of closed-over/global container "
                            f"{base.id!r} inside jitted {fn.name!r}: happens "
                            "once per trace, not per call")
                )
            elif (
                isinstance(base, ast.Name)
                and base.id == "time"
                and "time" not in bound
                and attr in _WALL_CLOCK_ATTRS
            ):
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"wall-clock read time.{attr} inside jitted "
                            f"{fn.name!r}: the trace-time value is baked "
                            "into the compiled program")
                )
            elif (
                attr == "now"
                and isinstance(base, ast.Name)
                and base.id == "datetime"
                and "datetime" not in bound
            ):
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"wall-clock read datetime.now inside jitted "
                            f"{fn.name!r}: the trace-time value is baked "
                            "into the compiled program")
                )
            elif (
                attr in _RNG_ATTRS
                and isinstance(base, ast.Name)
                and base.id == "random"
                and "random" not in bound
            ):
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"Python RNG read random.{attr} inside jitted "
                            f"{fn.name!r}: one trace-time draw is baked in — "
                            "use jax.random with an explicit key")
                )
            elif (
                isinstance(base, ast.Attribute)
                and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id in ("np", "numpy")
                and base.value.id not in bound
            ):
                findings.append(
                    Finding(rel, node.lineno, "jit-side-effect",
                            f"numpy RNG read {base.value.id}.random.{attr} "
                            f"inside jitted {fn.name!r}: one trace-time draw "
                            "is baked in — use jax.random with an explicit key")
                )
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for target in targets:
                inner = target
                while isinstance(inner, (ast.Subscript, ast.Attribute)):
                    inner = inner.value
                if (
                    isinstance(inner, ast.Name)
                    and inner is not target
                    and inner.id not in bound
                ):
                    findings.append(
                        Finding(rel, node.lineno, "jit-side-effect",
                                f"store into closed-over/global {inner.id!r} "
                                f"inside jitted {fn.name!r}: happens once per "
                                "trace, not per call")
                    )


def _is_none_guard_names(test: ast.AST) -> Set[int]:
    """ids of Name nodes used only as ``x is None`` / ``x is not None``
    operands — pytree-structure tests resolved at trace time."""
    exempt: Set[int] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            operands = [node.left, *node.comparators]
            if any(
                isinstance(o, ast.Constant) and o.value is None for o in operands
            ):
                for o in operands:
                    if isinstance(o, ast.Name):
                        exempt.add(id(o))
    return exempt


def _check_traced_branches(
    fn: ast.AST, static: Set[str], rel: str, findings: List[Finding]
) -> None:
    params = set()
    a = fn.args
    for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        params.add(arg.arg)
    traced = params - static
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        exempt = _is_none_guard_names(node.test)
        metadata_bases = {
            id(attr.value)
            for attr in ast.walk(node.test)
            if isinstance(attr, ast.Attribute)
            and attr.attr in _STATIC_METADATA_ATTRS
        }
        for name in ast.walk(node.test):
            if (
                isinstance(name, ast.Name)
                and name.id in traced
                and id(name) not in exempt
                and id(name) not in metadata_bases
            ):
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(
                    Finding(rel, node.lineno, "jit-traced-branch",
                            f"`{kind}` in jitted {fn.name!r} tests traced "
                            f"parameter {name.id!r} — trace-time Python "
                            "control flow cannot branch on device values "
                            "(add it to static_argnames, or use jnp.where/"
                            "lax.cond)")
                )
                break
    return None


def check_trace_safety(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in TRACE_SAFETY_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    aliases = _import_aliases(tree)
    findings: List[Finding] = []
    seen_fns = set()
    for fn, static in _jitted_functions(tree, aliases):
        key = (fn.lineno, frozenset(static))
        if key in seen_fns:
            continue
        seen_fns.add(key)
        _check_side_effects(fn, rel, findings)
        _check_traced_branches(fn, static, rel, findings)
    return sorted(set(findings), key=lambda f: (f.lineno, f.check, f.message))
