"""Check family 10: determinism discipline — no unseeded randomness.

The chaos-simulation subsystem's contract is that a whole run is a pure
function of one seed; that only holds if no library component silently
draws from entropy. Every randomness consumer in ``rapid_tpu/`` must
either accept an injectable ``random.Random`` (the ``rng=`` seam gossip,
consensus jitter, and the broadcaster all expose) or construct one from a
deterministic identity-derived seed.

Caught spellings:

- ``random.Random()`` with no seed argument — an entropy-seeded instance;
- module-level draws (``random.random()``, ``random.choice(...)``,
  ``random.shuffle(...)``, ...) — they share the module's global
  entropy-seeded generator;
- ``from random import choice``-style imports of the module-level draw
  functions (the aliased call is the same global generator);
- ``numpy.random.default_rng()`` with no seed, and legacy module-level
  ``np.random.<draw>(...)`` calls.

A deliberate exception carries ``# unseeded-ok: <reason>`` on the
offending line (e.g. a public-API default where no identity exists to
derive a seed from and every in-library caller threads a seeded rng).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional

from . import core
from .core import Finding

#: The tree this discipline applies to (posix-style relative prefix).
DETERMINISM_PREFIXES = ("rapid_tpu/",)

#: Module-level draw functions of the stdlib ``random`` module (all share
#: the global entropy-seeded generator). ``Random``/``SystemRandom`` are
#: class names, caught separately; ``seed`` is included — re-seeding the
#: GLOBAL generator is still global mutable randomness state.
_MODULE_DRAWS = frozenset({
    "betavariate", "choice", "choices", "expovariate", "gammavariate",
    "gauss", "getrandbits", "lognormvariate", "normalvariate",
    "paretovariate", "randbytes", "randint", "random", "randrange",
    "sample", "seed", "shuffle", "triangular", "uniform",
    "vonmisesvariate", "weibullvariate",
})

_ALLOW_RE = re.compile(r"#\s*unseeded-ok:")

_GUIDANCE = (
    "thread an injectable seeded random.Random (or derive the seed from the "
    "component's identity); simulated runs must be pure functions of their seed"
)


def _is_numpy_random(value: ast.AST) -> bool:
    """``np.random`` / ``numpy.random`` attribute chains."""
    return (
        isinstance(value, ast.Attribute)
        and value.attr == "random"
        and isinstance(value.value, ast.Name)
        and value.value.id in ("np", "numpy")
    )


def check_determinism(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in DETERMINISM_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()

    def allowed(lineno: int) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return bool(_ALLOW_RE.search(line))

    findings: List[Finding] = []

    def report(lineno: int, what: str) -> None:
        if not allowed(lineno):
            findings.append(
                Finding(rel, lineno, "unseeded-random", f"{what} — {_GUIDANCE}")
            )

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            value = func.value
            if isinstance(value, ast.Name) and value.id == "random":
                if func.attr == "SystemRandom":
                    # Always a finding, seeded-looking or not: SystemRandom
                    # IGNORES its seed argument and draws OS entropy.
                    report(node.lineno, "random.SystemRandom() draws OS entropy "
                                        "(any seed argument is ignored)")
                elif func.attr == "Random":
                    if not node.args and not node.keywords:
                        report(node.lineno, "random.Random() without a seed")
                elif func.attr in _MODULE_DRAWS:
                    report(
                        node.lineno,
                        f"module-level random.{func.attr}() draws from the "
                        "global entropy-seeded generator",
                    )
            elif _is_numpy_random(value):
                if func.attr in ("default_rng", "RandomState"):
                    # Instance constructors: a finding only when unseeded.
                    if not node.args and not node.keywords:
                        report(node.lineno, f"np.random.{func.attr}() without a seed")
                elif func.attr not in ("Generator", "SeedSequence", "PCG64"):
                    report(
                        node.lineno,
                        f"module-level np.random.{func.attr}() draws from "
                        "numpy's global generator",
                    )
        elif isinstance(node, ast.ImportFrom) and node.module == "random":
            drawn = [a.name for a in node.names if a.name in _MODULE_DRAWS]
            if drawn:
                report(
                    node.lineno,
                    f"importing {', '.join(drawn)} from random aliases the "
                    "global entropy-seeded generator",
                )
    return findings
