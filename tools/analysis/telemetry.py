"""Check family 15: device telemetry plane discipline.

The telemetry plane (rapid_tpu/models/state.py ``TelemetryLanes``) lives
on device and is fetched ONLY at declared host-sync boundaries — sync,
the stream driver's drain seam, fleet health scans, and the HLO audit.
An undeclared fetch is a blocking device round trip smuggled onto a hot
path, exactly the defect the sharding family's host-sync checks exist
for; the lanes get their own family because their fetch surface (the
``telemetry_digest`` jits) is narrower and checkable with zero false
positives.

Two checks:

- ``telemetry-unmarked-fetch`` (per file): every host materialization of
  the lanes — a call to ``telemetry_digest`` / ``fleet_telemetry_digest``,
  or ``np.asarray`` / ``np.array`` / ``jax.device_get`` over an
  expression that references telemetry lanes — must carry a
  ``# telemetry-fetch-ok: <why this is a sync boundary>`` marker on the
  call line or within the three lines above it.
- ``telemetry-lane-drift`` (full tree): the ``TelemetryLanes`` field set
  is mirrored here as a literal (wire_schema-style) and pinned against
  both the NamedTuple's declared fields and the ``TELEMETRY_LANE_SPECS``
  geometry table — adding a lane without updating every consumer
  (digest layout, partition rules, exposition vocabulary) fails the
  gate instead of silently dropping the lane from the digest.

The round-trace ring (ISSUE 17, ``TraceRing`` / ``TRACE_LANE_SPECS``)
rides the same family: its digest fetchers (``trace_digest`` /
``fleet_trace_digest``) and ``tr_*`` lane references fall under the same
``telemetry-unmarked-fetch`` marker discipline, and the ring's field set
gets its own analyzer mirror (``TRACE_LANE_FIELDS``) pinned by the same
``telemetry-lane-drift`` check — the ring is a refinement of the
telemetry plane, not a new observability channel with new rules.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Tuple

from . import core
from .core import Finding

#: Trees the fetch discipline applies to. Tests are exempt — a test
#: fetching the digest IS the boundary it is probing.
TELEMETRY_PREFIXES = ("rapid_tpu/", "bench.py", "tools/", "examples/")

#: The literal mirror of ``TelemetryLanes``'s fields, in declaration
#: order. Must match rapid_tpu/models/state.py exactly — the gate pins
#: both directions, so this tuple is the analyzer-side half of the same
#: never-drift contract wire.lock.json plays for the codec mirrors.
TELEMETRY_LANE_FIELDS = (
    "tl_rounds",
    "tl_alerts",
    "tl_active",
    "tl_invalidated",
    "tl_proposals",
    "tl_tally_sum",
    "tl_fast_decisions",
    "tl_classic_decisions",
    "tl_conflict_rounds",
    "tl_undecided_hist",
)

#: The literal mirror of ``TraceRing``'s fields, in declaration order —
#: the nine per-round lanes, then the cursor pair. Pinned against both
#: the NamedTuple and ``TRACE_LANE_SPECS`` exactly like the telemetry
#: mirror above.
TRACE_LANE_FIELDS = (
    "tr_round",
    "tr_epoch",
    "tr_active",
    "tr_alerts",
    "tr_proposals",
    "tr_tally",
    "tr_path",
    "tr_conflict",
    "tr_undecided",
    "tr_cursor",
    "tr_wraps",
)

STATE_REL = "rapid_tpu/models/state.py"
FETCH_MARKER = "telemetry-fetch-ok"
#: The marker may sit on the call line or this many lines above it (the
#: prose half of the comment typically wraps onto a second line).
MARKER_WINDOW = 3

#: The jitted digest entrypoints — calling one IS the device fetch.
_DIGEST_FETCHERS = frozenset({
    "telemetry_digest", "fleet_telemetry_digest",
    "trace_digest", "fleet_trace_digest",
})
#: Host materializers that become a lane fetch when fed lane references.
_MATERIALIZERS = frozenset({"asarray", "array", "device_get"})


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _mentions_lanes(node: ast.AST) -> bool:
    """True if the expression references device telemetry lanes: an
    attribute or name spelled ``telem`` (the lanes pytree by convention)
    or ``trace_ring`` (the device ring by convention — bare ``trace`` is
    deliberately NOT matched: it names decoded host-side summaries), or
    any ``tl_*`` / ``tr_*`` lane field."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name is not None and (
            name in ("telem", "trace_ring")
            or name.startswith("tl_")
            or name.startswith("tr_")
        ):
            return True
    return False


def _has_marker(lines: List[str], lineno: int) -> bool:
    lo = max(0, lineno - 1 - MARKER_WINDOW)
    return any(FETCH_MARKER in line for line in lines[lo:lineno])


def check_telemetry(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in TELEMETRY_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if FETCH_MARKER not in src and "telem" not in src and "trace" not in src:
        return []  # cheap bail: nothing lane-shaped in this file
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()
    findings: List[Finding] = []
    flagged: set = set()  # one finding per line — np.asarray(digest(...))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        if name in _DIGEST_FETCHERS:
            fetch = True
        elif name in _MATERIALIZERS:
            fetch = any(_mentions_lanes(arg) for arg in node.args)
        else:
            fetch = False
        if fetch and node.lineno in flagged:
            continue
        if fetch and not _has_marker(lines, node.lineno):
            flagged.add(node.lineno)
            findings.append(Finding(
                rel, node.lineno, "telemetry-unmarked-fetch",
                "telemetry-lane fetch outside a declared boundary — a "
                "blocking device round trip; move it to a host-sync seam "
                "(sync / drain / health_scan) and annotate it with "
                "'# telemetry-fetch-ok: <why>'",
            ))
    return findings


def _class_fields(tree: ast.AST, name: str) -> Optional[Tuple[List[str], int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            fields = [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
            return fields, node.lineno
    return None


def _spec_keys(
    tree: ast.AST, var_name: str = "TELEMETRY_LANE_SPECS"
) -> Optional[Tuple[List[str], int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == var_name):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        keys = [
            k.value for k in node.value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
        return keys, node.lineno
    return None


#: (NamedTuple name, geometry-table name, analyzer mirror) — one row per
#: device observability plane pinned by ``check_lane_mirror``.
_LANE_MIRRORS = (
    ("TelemetryLanes", "TELEMETRY_LANE_SPECS", TELEMETRY_LANE_FIELDS),
    ("TraceRing", "TRACE_LANE_SPECS", TRACE_LANE_FIELDS),
)


def check_lane_mirror(trees: List[Tuple[ast.AST, str]]) -> List[Finding]:
    """Full-tree check: pin the analyzer's lane mirrors against the live
    ``TelemetryLanes`` / ``TraceRing`` declarations AND their
    ``*_LANE_SPECS`` geometry tables. Presence-gated on state.py being in
    the sweep, so retargeted test trees skip it."""
    state_tree = next((t for t, rel in trees if rel == STATE_REL), None)
    if state_tree is None:
        return []
    findings: List[Finding] = []
    for cls_name, spec_name, mirror_fields in _LANE_MIRRORS:
        mirror = list(mirror_fields)
        got = _class_fields(state_tree, cls_name)
        if got is None:
            findings.append(Finding(
                STATE_REL, 1, "telemetry-lane-drift",
                f"{cls_name} class not found — the analyzer's lane mirror "
                f"(tools/analysis/telemetry.py) has nothing to pin against",
            ))
            continue
        fields, lineno = got
        if fields != mirror:
            findings.append(Finding(
                STATE_REL, lineno, "telemetry-lane-drift",
                f"{cls_name} fields {fields} do not match the analyzer "
                f"mirror {mirror} — update tools/analysis/telemetry.py AND "
                f"every lane consumer (digest layout, partition rules, "
                f"exposition vocabulary) together",
            ))
        spec = _spec_keys(state_tree, spec_name)
        if spec is None:
            findings.append(Finding(
                STATE_REL, 1, "telemetry-lane-drift",
                f"{spec_name} literal dict not found in state.py — the "
                f"lane geometry table must stay a plain literal so the "
                f"gate can read it",
            ))
        else:
            keys, lineno = spec
            if keys != mirror:
                findings.append(Finding(
                    STATE_REL, lineno, "telemetry-lane-drift",
                    f"{spec_name} keys {keys} do not match the analyzer "
                    f"mirror {mirror} — the geometry table and the "
                    f"NamedTuple must list the same lanes in the same "
                    f"order",
                ))
    return findings
