"""Check family 15: device telemetry plane discipline.

The telemetry plane (rapid_tpu/models/state.py ``TelemetryLanes``) lives
on device and is fetched ONLY at declared host-sync boundaries — sync,
the stream driver's drain seam, fleet health scans, and the HLO audit.
An undeclared fetch is a blocking device round trip smuggled onto a hot
path, exactly the defect the sharding family's host-sync checks exist
for; the lanes get their own family because their fetch surface (the
``telemetry_digest`` jits) is narrower and checkable with zero false
positives.

Two checks:

- ``telemetry-unmarked-fetch`` (per file): every host materialization of
  the lanes — a call to ``telemetry_digest`` / ``fleet_telemetry_digest``,
  or ``np.asarray`` / ``np.array`` / ``jax.device_get`` over an
  expression that references telemetry lanes — must carry a
  ``# telemetry-fetch-ok: <why this is a sync boundary>`` marker on the
  call line or within the three lines above it.
- ``telemetry-lane-drift`` (full tree): the ``TelemetryLanes`` field set
  is mirrored here as a literal (wire_schema-style) and pinned against
  both the NamedTuple's declared fields and the ``TELEMETRY_LANE_SPECS``
  geometry table — adding a lane without updating every consumer
  (digest layout, partition rules, exposition vocabulary) fails the
  gate instead of silently dropping the lane from the digest.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Tuple

from . import core
from .core import Finding

#: Trees the fetch discipline applies to. Tests are exempt — a test
#: fetching the digest IS the boundary it is probing.
TELEMETRY_PREFIXES = ("rapid_tpu/", "bench.py", "tools/", "examples/")

#: The literal mirror of ``TelemetryLanes``'s fields, in declaration
#: order. Must match rapid_tpu/models/state.py exactly — the gate pins
#: both directions, so this tuple is the analyzer-side half of the same
#: never-drift contract wire.lock.json plays for the codec mirrors.
TELEMETRY_LANE_FIELDS = (
    "tl_rounds",
    "tl_alerts",
    "tl_active",
    "tl_invalidated",
    "tl_proposals",
    "tl_tally_sum",
    "tl_fast_decisions",
    "tl_classic_decisions",
    "tl_conflict_rounds",
    "tl_undecided_hist",
)

STATE_REL = "rapid_tpu/models/state.py"
FETCH_MARKER = "telemetry-fetch-ok"
#: The marker may sit on the call line or this many lines above it (the
#: prose half of the comment typically wraps onto a second line).
MARKER_WINDOW = 3

#: The jitted digest entrypoints — calling one IS the device fetch.
_DIGEST_FETCHERS = frozenset({"telemetry_digest", "fleet_telemetry_digest"})
#: Host materializers that become a lane fetch when fed lane references.
_MATERIALIZERS = frozenset({"asarray", "array", "device_get"})


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _mentions_lanes(node: ast.AST) -> bool:
    """True if the expression references telemetry lanes: an attribute or
    name spelled ``telem`` (the lanes pytree by convention) or any
    ``tl_*`` lane field."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name is not None and (name == "telem" or name.startswith("tl_")):
            return True
    return False


def _has_marker(lines: List[str], lineno: int) -> bool:
    lo = max(0, lineno - 1 - MARKER_WINDOW)
    return any(FETCH_MARKER in line for line in lines[lo:lineno])


def check_telemetry(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in TELEMETRY_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if FETCH_MARKER not in src and "telem" not in src:
        return []  # cheap bail: nothing lane-shaped in this file
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    lines = src.splitlines()
    findings: List[Finding] = []
    flagged: set = set()  # one finding per line — np.asarray(digest(...))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        if name in _DIGEST_FETCHERS:
            fetch = True
        elif name in _MATERIALIZERS:
            fetch = any(_mentions_lanes(arg) for arg in node.args)
        else:
            fetch = False
        if fetch and node.lineno in flagged:
            continue
        if fetch and not _has_marker(lines, node.lineno):
            flagged.add(node.lineno)
            findings.append(Finding(
                rel, node.lineno, "telemetry-unmarked-fetch",
                "telemetry-lane fetch outside a declared boundary — a "
                "blocking device round trip; move it to a host-sync seam "
                "(sync / drain / health_scan) and annotate it with "
                "'# telemetry-fetch-ok: <why>'",
            ))
    return findings


def _class_fields(tree: ast.AST, name: str) -> Optional[Tuple[List[str], int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            fields = [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
            return fields, node.lineno
    return None


def _spec_keys(tree: ast.AST) -> Optional[Tuple[List[str], int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
        else:
            continue
        if not (isinstance(target, ast.Name)
                and target.id == "TELEMETRY_LANE_SPECS"):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        keys = [
            k.value for k in node.value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
        return keys, node.lineno
    return None


def check_lane_mirror(trees: List[Tuple[ast.AST, str]]) -> List[Finding]:
    """Full-tree check: pin the analyzer's lane mirror against the live
    ``TelemetryLanes`` declaration AND the ``TELEMETRY_LANE_SPECS``
    geometry table. Presence-gated on state.py being in the sweep, so
    retargeted test trees skip it."""
    state_tree = next((t for t, rel in trees if rel == STATE_REL), None)
    if state_tree is None:
        return []
    findings: List[Finding] = []
    mirror = list(TELEMETRY_LANE_FIELDS)
    got = _class_fields(state_tree, "TelemetryLanes")
    if got is None:
        findings.append(Finding(
            STATE_REL, 1, "telemetry-lane-drift",
            "TelemetryLanes class not found — the analyzer's lane mirror "
            "(tools/analysis/telemetry.py TELEMETRY_LANE_FIELDS) has "
            "nothing to pin against",
        ))
        return findings
    fields, lineno = got
    if fields != mirror:
        findings.append(Finding(
            STATE_REL, lineno, "telemetry-lane-drift",
            f"TelemetryLanes fields {fields} do not match the analyzer "
            f"mirror {mirror} — update tools/analysis/telemetry.py AND "
            f"every lane consumer (digest layout, PARTITION_RULES, "
            f"exposition vocabulary) together",
        ))
    spec = _spec_keys(state_tree)
    if spec is None:
        findings.append(Finding(
            STATE_REL, 1, "telemetry-lane-drift",
            "TELEMETRY_LANE_SPECS literal dict not found in state.py — "
            "the lane geometry table must stay a plain literal so the "
            "gate can read it",
        ))
    else:
        keys, lineno = spec
        if keys != mirror:
            findings.append(Finding(
                STATE_REL, lineno, "telemetry-lane-drift",
                f"TELEMETRY_LANE_SPECS keys {keys} do not match the "
                f"analyzer mirror {mirror} — the geometry table and the "
                f"NamedTuple must list the same lanes in the same order",
            ))
    return findings
