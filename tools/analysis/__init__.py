"""Resolution-grade static analysis for this repo, as a package.

The reference fails its build on error-prone (-Werror), findbugs, and
checkstyle findings (root pom.xml + build-common/); the AST style gate in
tests/test_lint.py covers the checkstyle analog, and this package plays the
error-prone role — the class of checks that needs RESOLUTION, not just
syntax. This environment ships no ruff/mypy/pyflakes, so the tier is built
on the stdlib (``ast``, ``symtable``, ``inspect``).

Check families (one module each; ``core`` owns the driver/CLI/Finding):

1. ``names``        — undefined names (symtable scope resolution)
2. ``signatures``   — call-signature conformance vs imported runtime modules
3. ``clocks``       — clock-injection discipline (protocol + monitoring)
4. ``deadcode``     — dead module-level definitions (tree-wide liveness)
5. ``concurrency``  — asyncio guarded-by discipline, interleaving hazards,
                      lock re-entrancy (protocol + messaging)
6. ``trace_safety`` — JAX jit purity/staticness (ops)
7. ``wire_schema``  — the four hand-kept wire-schema mirrors cross-checked
                      and frozen in ``wire.lock.json`` (types/codec/proto)
8. ``dispatch``     — RapidRequest dispatch exhaustiveness, shadowed arms,
                      and response return types (protocol)
9. ``taskflow``     — async failure-path hygiene: leaked tasks, swallowed
                      exceptions, cancellation swallows, unawaited
                      coroutines (whole library)
10. ``determinism`` — no unseeded randomness in the library: every rng is
                      injectable or identity-seeded, so simulated chaos
                      runs (rapid_tpu/sim) are pure functions of one seed
11. ``ledger``      — run-ledger vocabulary discipline (LedgerEvent /
                      STAGE_NAMES)
12. ``device_program`` — the compiled artifact itself: every registered
                      jitted engine entrypoint compiled on a forced
                      8-device CPU mesh, its collectives/transfers/
                      donation/memory facts frozen in ``hlo.lock.json``
13. ``sharding``    — source seams that produce bad compiled programs:
                      partition-spec coverage of the engine state pytree,
                      host syncs inside traced hot paths AND anywhere in
                      the streaming pipeline (rapid_tpu/serving — every
                      blocking read there is a declared fetch boundary or
                      a finding), jit callsites that forget buffer
                      donation or invite retraces (ops/models/parallel)

``staticcheck --families`` prints this catalog; ``--update-wire-lock`` /
``--update-hlo-lock`` regenerate the lockfiles after an intentional
schema / compiled-budget change.

Shared philosophy: conservative resolution, zero-false-positive findings,
skip-don't-guess. Run via ``python tools/staticcheck.py`` (the compatible
CLI shim) or the build gate in tests/test_staticcheck.py.
"""

from __future__ import annotations

from . import core
from .chaosvocab import check_chaosvocab
from .clocks import CLOCK_DISCIPLINE_PREFIXES, check_clock_injection
from .concurrency import CONCURRENCY_PREFIXES, check_concurrency
from .cost_model import (
    COST_LOCK_REL,
    check_cost_lock,
    check_cost_model,
    collect_ladder,
    fit_scaling,
    update_cost_lock,
)
from .core import (
    ALL_CHECK_NAMES,
    DEFAULT_ROOTS,
    FAMILIES,
    Finding,
    iter_files,
    main,
    run,
)
from .dataflow import (
    DATAFLOW_LOCK_REL,
    check_dataflow,
    check_dataflow_lock,
    collect_dataflow,
    update_dataflow_lock,
)
from .deadcode import check_dead_definitions
from .determinism import DETERMINISM_PREFIXES, check_determinism
from .device_program import (
    HLO_LOCK_REL,
    check_device_program,
    check_hlo_lock,
    collect_facts,
    update_hlo_lock,
)
from .dispatch import DISPATCH_PREFIXES, check_dispatch
from .ledger import LEDGER_PREFIXES, check_ledger
from .names import check_undefined_names
from .sharding import (
    SHARDING_PREFIXES,
    STREAM_PREFIXES,
    check_partition_specs,
    check_sharding,
)
from .signatures import check_call_signatures
from .taskflow import TASKFLOW_PREFIXES, check_taskflow
from .telemetry import (
    TELEMETRY_LANE_FIELDS,
    TELEMETRY_PREFIXES,
    check_lane_mirror,
    check_telemetry,
)
from .trace_safety import TRACE_SAFETY_PREFIXES, check_trace_safety
from .wire_schema import (
    LOCK_REL,
    WIRE_FILES,
    check_wire_lock,
    check_wire_schema,
    update_wire_lock,
)

__all__ = [
    "ALL_CHECK_NAMES",
    "CLOCK_DISCIPLINE_PREFIXES",
    "CONCURRENCY_PREFIXES",
    "COST_LOCK_REL",
    "DATAFLOW_LOCK_REL",
    "DEFAULT_ROOTS",
    "DETERMINISM_PREFIXES",
    "DISPATCH_PREFIXES",
    "FAMILIES",
    "Finding",
    "HLO_LOCK_REL",
    "LEDGER_PREFIXES",
    "LOCK_REL",
    "SHARDING_PREFIXES",
    "STREAM_PREFIXES",
    "TASKFLOW_PREFIXES",
    "TELEMETRY_LANE_FIELDS",
    "TELEMETRY_PREFIXES",
    "TRACE_SAFETY_PREFIXES",
    "WIRE_FILES",
    "check_call_signatures",
    "check_chaosvocab",
    "check_clock_injection",
    "check_concurrency",
    "check_cost_lock",
    "check_cost_model",
    "check_dataflow",
    "check_dataflow_lock",
    "check_dead_definitions",
    "check_determinism",
    "check_device_program",
    "check_dispatch",
    "check_hlo_lock",
    "check_lane_mirror",
    "check_ledger",
    "check_partition_specs",
    "check_sharding",
    "check_taskflow",
    "check_telemetry",
    "check_trace_safety",
    "check_undefined_names",
    "check_wire_lock",
    "check_wire_schema",
    "collect_dataflow",
    "collect_facts",
    "collect_ladder",
    "core",
    "fit_scaling",
    "iter_files",
    "main",
    "run",
    "update_cost_lock",
    "update_dataflow_lock",
    "update_hlo_lock",
    "update_wire_lock",
]
