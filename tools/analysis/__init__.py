"""Resolution-grade static analysis for this repo, as a package.

The reference fails its build on error-prone (-Werror), findbugs, and
checkstyle findings (root pom.xml + build-common/); the AST style gate in
tests/test_lint.py covers the checkstyle analog, and this package plays the
error-prone role — the class of checks that needs RESOLUTION, not just
syntax. This environment ships no ruff/mypy/pyflakes, so the tier is built
on the stdlib (``ast``, ``symtable``, ``inspect``).

Check families (one module each; ``core`` owns the driver/CLI/Finding):

1. ``names``        — undefined names (symtable scope resolution)
2. ``signatures``   — call-signature conformance vs imported runtime modules
3. ``clocks``       — clock-injection discipline (protocol + monitoring)
4. ``deadcode``     — dead module-level definitions (tree-wide liveness)
5. ``concurrency``  — asyncio guarded-by discipline, interleaving hazards,
                      lock re-entrancy (protocol + messaging)
6. ``trace_safety`` — JAX jit purity/staticness (ops)

Shared philosophy: conservative resolution, zero-false-positive findings,
skip-don't-guess. Run via ``python tools/staticcheck.py`` (the compatible
CLI shim) or the build gate in tests/test_staticcheck.py.
"""

from __future__ import annotations

from . import core
from .clocks import CLOCK_DISCIPLINE_PREFIXES, check_clock_injection
from .concurrency import CONCURRENCY_PREFIXES, check_concurrency
from .core import (
    ALL_CHECK_NAMES,
    DEFAULT_ROOTS,
    Finding,
    iter_files,
    main,
    run,
)
from .deadcode import check_dead_definitions
from .names import check_undefined_names
from .signatures import check_call_signatures
from .trace_safety import TRACE_SAFETY_PREFIXES, check_trace_safety

__all__ = [
    "ALL_CHECK_NAMES",
    "CLOCK_DISCIPLINE_PREFIXES",
    "CONCURRENCY_PREFIXES",
    "DEFAULT_ROOTS",
    "Finding",
    "TRACE_SAFETY_PREFIXES",
    "check_call_signatures",
    "check_clock_injection",
    "check_concurrency",
    "check_dead_definitions",
    "check_trace_safety",
    "check_undefined_names",
    "core",
    "iter_files",
    "main",
    "run",
]
