"""Check family 11: run-ledger vocabulary discipline.

The bench run ledger (rapid_tpu/utils/ledger.py) is only renderable because
its event names come from the registered ``LedgerEvent`` enum and its stage
names from the ``STAGE_NAMES`` registry — the exact discipline the flight
recorder's ``EventName`` rule enforces in tests/test_lint.py. A free-form
string would silently fork the vocabulary: perfview's stage timeline and the
watchdog's per-stage budgets would stop seeing the event.

Two checks, applied only to files that import ``rapid_tpu.utils.ledger``
(so unrelated ``.emit()``/``.stage()`` methods elsewhere are never touched):

- ``ledger-event-name``: every ``*.emit(...)`` call names its event as
  ``LedgerEvent.<registered member>`` (or forwards an already-checked
  ``event`` parameter);
- ``ledger-stage-name``: every ``*.stage(...)`` call's name is a string
  literal found in ``STAGE_NAMES`` (parameterize stages via fields like
  ``n=``, never by minting names at runtime).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from . import core
from .core import Finding

#: Trees the discipline applies to (the ledger's writers live here).
LEDGER_PREFIXES = ("rapid_tpu/", "bench.py", "tools/", "examples/")


def _imports_ledger(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("utils.ledger"):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.endswith("utils.ledger") for a in node.names):
                return True
    return False


def check_ledger(
    path: Path,
    source: Optional[str] = None,
    tree: "Optional[ast.AST]" = None,
) -> List[Finding]:
    rel = core.rel(path)
    posix = rel.replace("\\", "/")
    if not any(posix.startswith(p) for p in LEDGER_PREFIXES):
        return []
    src = source if source is not None else path.read_text()
    if tree is None:
        tree = ast.parse(src, filename=str(path))
    # In scope: importers of the ledger module, and the module itself (its
    # own internal emit calls follow the same discipline).
    if not (_imports_ledger(tree) or posix == "rapid_tpu/utils/ledger.py"):
        return []

    # The registered vocabularies come from the runtime module itself (the
    # same never-drift rule as test_lint's EventName import).
    from rapid_tpu.utils.ledger import STAGE_NAMES, LedgerEvent

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr == "emit":
            arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "event"), None
            )
            ok = (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "LedgerEvent"
                and arg.attr in LedgerEvent.__members__
            )
            # Forwarding an already-validated parameter (a helper whose own
            # caller is checked) is fine — mirror of the recorder rule.
            forwards = isinstance(arg, ast.Name) and arg.id == "event"
            if not (ok or forwards):
                findings.append(Finding(
                    rel, node.lineno, "ledger-event-name",
                    "ledger emit() event must be a LedgerEvent member "
                    "(registered vocabulary; free-form names break perfview)",
                ))
        elif node.func.attr == "stage":
            arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"), None
            )
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value not in STAGE_NAMES:
                    findings.append(Finding(
                        rel, node.lineno, "ledger-stage-name",
                        f"stage {arg.value!r} is not in the registered "
                        "STAGE_NAMES vocabulary (rapid_tpu/utils/ledger.py)",
                    ))
            else:
                findings.append(Finding(
                    rel, node.lineno, "ledger-stage-name",
                    "ledger stage() name must be a string literal from "
                    "STAGE_NAMES (parameterize via fields, not the name)",
                ))
    return findings
