#!/bin/bash
# Session-long TPU evidence watcher: probe the axon tunnel until it answers,
# then atomically capture the full evidence set and commit it.
#
#   nohup bash tools/tpu_watch.sh round4 >> /tmp/tpu_watch_r4.log 2>&1 &
#
# The tunnel wedges for hours at a time (bench.py watchdog docstring) and
# live windows are rare and short, so the moment a probe succeeds we go
# straight into tools/capture_tpu_evidence.sh, sync every finished artifact
# into evidence/$ROUND/ as it lands (a mid-capture wedge must not lose the
# stages that DID finish), and commit. The sync loop runs alongside the
# capture so even a killed session leaves committed evidence behind.
set -u
ROUND="${1:-round4}"
OUT="/tmp/tpu_evidence_${ROUND}"
cd "$(dirname "$0")/.."
DEST="evidence/$ROUND"

sync_evidence() {
  mkdir -p "$DEST"
  local changed=0
  for f in bench.json bench_tuned.json microbench.json microbench_slope.json \
           autotune.jsonl bootstrap.json sweep.jsonl pytest_tpu.log bench.log; do
    if [ -s "$OUT/$f" ] && ! cmp -s "$OUT/$f" "$DEST/$f" 2>/dev/null; then
      cp "$OUT/$f" "$DEST/$f" && changed=1
    fi
  done
  return $((1 - changed))
}

commit_evidence() {
  # Retry around a concurrent index lock from the interactive session.
  for _ in 1 2 3 4 5; do
    if git add "$DEST" 2>/dev/null && \
       git -c user.name=distsys-graft -c user.email=graft@localhost \
         commit -m "Capture live TPU evidence ($ROUND watcher)" -- "$DEST" 2>/dev/null; then
      echo "$(date -u +%H:%M:%S) committed $DEST"
      return 0
    fi
    sleep 23
  done
  echo "$(date -u +%H:%M:%S) commit failed; files staged in $DEST"
}

bash tools/tunnel_probe.sh 180 90 || exit 1

echo "$(date -u +%H:%M:%S) tunnel alive; capturing to $OUT"
OUT="$OUT" bash tools/capture_tpu_evidence.sh &
CAP_PID=$!
while kill -0 "$CAP_PID" 2>/dev/null; do
  sleep 120
  sync_evidence && commit_evidence
done
wait "$CAP_PID"
CAP_RC=$?
sync_evidence && commit_evidence
echo "$(date -u +%H:%M:%S) CAPTURE DONE rc=$CAP_RC"
