// Native host-runtime components: XXH64 and batch ring-key construction.
//
// The host side of the framework hashes every endpoint K times to build the
// ring permutations (semantics of MembershipView.AddressComparator,
// MembershipView.java:562-587). At 100K endpoints x K=10 rings that is 1M+
// seeded hashes on the bootstrap path; this C library computes them at memory
// bandwidth. Exposed through ctypes (rapid_tpu/utils/_native.py) with a
// pure-Python fallback producing bit-identical values.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t round1(uint64_t acc, uint64_t lane) {
  acc += lane * P2;
  acc = rotl(acc, 31);
  return acc * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round1(0, val);
  return acc * P1 + P4;
}

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64-le)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t avalanche(uint64_t h) {
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

uint64_t xxh64(const uint8_t* data, uint64_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = round1(v1, read64(p));
      v2 = round1(v2, read64(p + 8));
      v3 = round1(v3, read64(p + 16));
      v4 = round1(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }

  h += len;

  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= static_cast<uint64_t>(*p) * P5;
    h = rotl(h, 11) * P1;
    ++p;
  }
  return avalanche(h);
}

inline uint64_t xxh64_int(int64_t value, uint64_t seed) {
  uint8_t buf[8];
  std::memcpy(buf, &value, 8);
  return xxh64(buf, 8, seed);
}

}  // namespace

extern "C" {

uint64_t rapid_xxh64(const uint8_t* data, uint64_t len, uint64_t seed) {
  return xxh64(data, len, seed);
}

// Ring key for one endpoint on one ring:
//   xxh64(hostname, seed) * 31 + xxh64(le64(port), seed)
// (semantics of AddressComparator.computeHash, MembershipView.java:579-582).
uint64_t rapid_ring_key(const uint8_t* hostname, uint64_t hostname_len,
                        int32_t port, uint64_t seed) {
  return xxh64(hostname, hostname_len, seed) * 31ULL +
         xxh64_int(static_cast<int64_t>(port), seed);
}

// Batch ring keys for n endpoints x k rings. Hostnames are packed into one
// blob with offsets[i]..offsets[i+1] delimiting endpoint i's hostname bytes.
// out is row-major [k, n].
void rapid_ring_keys_batch(const uint8_t* blob, const uint64_t* offsets,
                           const int32_t* ports, uint64_t n, uint32_t k,
                           uint64_t* out) {
  for (uint32_t ring = 0; ring < k; ++ring) {
    const uint64_t seed = ring;
    uint64_t* row = out + static_cast<uint64_t>(ring) * n;
    for (uint64_t i = 0; i < n; ++i) {
      const uint8_t* host = blob + offsets[i];
      const uint64_t len = offsets[i + 1] - offsets[i];
      row[i] = xxh64(host, len, seed) * 31ULL +
               xxh64_int(static_cast<int64_t>(ports[i]), seed);
    }
  }
}

// Configuration-id fold (semantics of Configuration.getConfigurationId,
// MembershipView.java:544-556): hash = hash*37 + xxh64(field) over sorted
// node ids then ring-0-ordered endpoints.
uint64_t rapid_configuration_id(const uint64_t* id_high, const uint64_t* id_low,
                                uint64_t n_ids, const uint8_t* blob,
                                const uint64_t* offsets, const int32_t* ports,
                                uint64_t n_endpoints) {
  uint64_t h = 1;
  for (uint64_t i = 0; i < n_ids; ++i) {
    h = h * 37 + xxh64_int(static_cast<int64_t>(id_high[i]), 0);
    h = h * 37 + xxh64_int(static_cast<int64_t>(id_low[i]), 0);
  }
  for (uint64_t i = 0; i < n_endpoints; ++i) {
    h = h * 37 + xxh64(blob + offsets[i], offsets[i + 1] - offsets[i], 0);
    h = h * 37 + xxh64_int(static_cast<int64_t>(ports[i]), 0);
  }
  return h;
}

}  // extern "C"
