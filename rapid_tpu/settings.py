"""Framework configuration.

Mirrors the reference's ``Settings`` knob set (``Settings.java:21-112``) and
fixes its one structural gap: the protocol constants K/H/L were hardcoded in
``Cluster.java:72-74``; here they are first-class configuration.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Settings:
    # Protocol constants (reference defaults: Cluster.java:72-74).
    k: int = 10
    h: int = 9
    l: int = 4

    # Messaging (GrpcClient.java:55-59).
    rpc_timeout_ms: int = 1000
    rpc_default_retries: int = 5
    rpc_join_timeout_ms: int = 5000
    rpc_probe_timeout_ms: int = 1000

    # Protocol timing (MembershipService.java:75-78, FastPaxos.java:46).
    failure_detector_interval_ms: int = 1000
    batching_window_ms: int = 100
    consensus_fallback_base_delay_ms: int = 1000

    # Join client (Cluster.java:71).
    join_attempts: int = 5

    # Leave (MembershipService.java:78).
    leave_message_timeout_ms: int = 1500

    # Protocol-level delivery liveness. The reference guarantees message
    # delivery inside the transport (bounded retries, Retries.java:43-90;
    # channel retry wrapper GrpcClient.java:106-115), so its protocol can
    # fire every broadcast exactly once. Transports here may be lossy (the
    # UDP hybrid ships one-way traffic as droppable datagrams), so the
    # equivalent guarantee lives at the protocol level instead:
    # - alert batches for the current configuration are re-broadcast on this
    #   cadence while the cut they announce is still unresolved (0 = off);
    # - a node that suspects it is stale (undecided proposal, unresolved cut,
    #   or traffic stamped with a configuration id it does not know) pulls
    #   the current configuration from a peer over the reliable path on this
    #   cadence (0 = off).
    alert_redelivery_interval_ms: int = 1000
    config_sync_interval_ms: int = 2000
    # Anti-entropy heartbeat: even with NO local suspicion a member pulls a
    # peer's configuration this often (0 = off). This is the only mechanism
    # that reaches a member which missed a decision AND has no local
    # evidence of it AND receives no traffic at all afterwards (e.g. its
    # ingress was partitioned through the decision and the cluster went
    # quiescent) — suspicion-based sync and evidence pulls both need some
    # signal; this needs none. Deliberately slow, and cheap when current:
    # the pull carries the requester's configuration id, so an up-to-date
    # peer answers with a compact "unchanged" response instead of streaming
    # the full O(N) configuration (protocol/service.py::_catch_up; native
    # topology only — java-topology clusters keep the joiner's -1 sentinel
    # because a reference JVM peer has no unchanged fast path).
    config_sync_idle_interval_ms: int = 30_000

    # Two-level hierarchical membership (rapid_tpu/hier; ROADMAP item 3).
    # 0 = flat Rapid (every alert/vote fans out cluster-wide). > 0 = cohort
    # mode: the membership is deterministically partitioned into cohorts of
    # roughly this size (seeded by hier_seed, rebalanced only at
    # reconfiguration); failure detection, alert broadcast, and the fast
    # consensus round are scoped to the cohort, and a small delegate
    # committee serializes cohort cut proposals into the single cluster-wide
    # configuration chain. Cluster-wide knob: every member must agree on
    # both values or nodes compute different cohort maps and the fast path
    # degrades to anti-entropy catch-up.
    hier_target_cohort_size: int = 0
    hier_seed: int = 0

    # Topology mode: "native" (tpu-first default: 8-byte port hashing,
    # unsigned key/identifier ordering) or "java" (reference-exact ring
    # ordering and configuration-id fold, MembershipView.java:544-587 —
    # required for mixed clusters with the Java implementation over the
    # interop transport). Cluster-wide: every member must use the same mode
    # or configuration ids diverge immediately.
    topology: str = "native"

    def validate(self) -> None:
        if not (self.k >= 3 and self.k >= self.h >= self.l >= 1):
            raise ValueError(
                f"K/H/L must satisfy K>=3 and K>=H>=L>=1, got K={self.k} H={self.h} L={self.l}"
            )
        from rapid_tpu.protocol.view import TOPOLOGIES

        if self.topology not in TOPOLOGIES:
            raise ValueError(
                f"topology must be one of {TOPOLOGIES}, got {self.topology!r}"
            )
        if self.hier_target_cohort_size < 0 or self.hier_target_cohort_size == 1:
            # A 1-member cohort could never detect its own failure; 0 means
            # flat mode, >= 2 is a real hierarchy.
            raise ValueError(
                "hier_target_cohort_size must be 0 (flat) or >= 2, got "
                f"{self.hier_target_cohort_size}"
            )
