"""Framework dataclasses <-> reference-wire protobuf messages."""

from __future__ import annotations

from typing import Tuple

from rapid_tpu.interop.proto_schema import proto_class
from rapid_tpu import types as t

_REQUEST_FIELDS = {
    t.PreJoinMessage: "preJoinMessage",
    t.JoinMessage: "joinMessage",
    t.BatchedAlertMessage: "batchedAlertMessage",
    t.ProbeMessage: "probeMessage",
    t.FastRoundPhase2bMessage: "fastRoundPhase2bMessage",
    t.Phase1aMessage: "phase1aMessage",
    t.Phase1bMessage: "phase1bMessage",
    t.Phase2aMessage: "phase2aMessage",
    t.Phase2bMessage: "phase2bMessage",
    t.LeaveMessage: "leaveMessage",
    t.CohortCutMessage: "cohortCutMessage",
    t.DelegateDecisionMessage: "delegateDecisionMessage",
    t.GlobalTierMessage: "globalTierMessage",
}

_RESPONSE_FIELDS = {
    t.JoinResponse: "joinResponse",
    t.Response: "response",
    t.ConsensusResponse: "consensusResponse",
    t.ProbeResponse: "probeResponse",
}

_S64 = 1 << 63
_U64 = 1 << 64


def _i64(value: int) -> int:
    value &= _U64 - 1
    return value - _U64 if value >= _S64 else value


def _u64(value: int) -> int:
    return value & (_U64 - 1)


def _ep(ep: t.Endpoint):
    out = proto_class("Endpoint")()
    out.hostname = ep.hostname.encode("utf-8")
    out.port = ep.port
    return out


def _ep_back(msg) -> t.Endpoint:
    return t.Endpoint(bytes(msg.hostname).decode("utf-8"), msg.port)


def _nid(nid: t.NodeId):
    out = proto_class("NodeId")()
    out.high = _i64(nid.high)
    out.low = _i64(nid.low)
    return out


def _nid_back(msg) -> t.NodeId:
    return t.NodeId(_u64(msg.high), _u64(msg.low))


def _md(metadata: Tuple[Tuple[str, bytes], ...]):
    out = proto_class("Metadata")()
    for key, value in metadata:
        out.metadata[key] = value
    return out


def _md_back(msg) -> Tuple[Tuple[str, bytes], ...]:
    return tuple(sorted((k, bytes(v)) for k, v in msg.metadata.items()))


def _rank(rank: t.Rank):
    out = proto_class("Rank")()
    out.round = rank.round
    out.nodeIndex = rank.node_index
    return out


def _rank_back(msg) -> t.Rank:
    return t.Rank(msg.round, msg.nodeIndex)


def _alert(a: t.AlertMessage):
    out = proto_class("AlertMessage")()
    out.edgeSrc.CopyFrom(_ep(a.edge_src))
    out.edgeDst.CopyFrom(_ep(a.edge_dst))
    out.edgeStatus = int(a.edge_status)
    out.configurationId = _i64(a.configuration_id)
    out.ringNumber.extend(a.ring_numbers)
    if a.node_id is not None:
        out.nodeId.CopyFrom(_nid(a.node_id))
    if a.metadata:
        out.metadata.CopyFrom(_md(a.metadata))
    return out


def _alert_back(msg) -> t.AlertMessage:
    return t.AlertMessage(
        edge_src=_ep_back(msg.edgeSrc),
        edge_dst=_ep_back(msg.edgeDst),
        edge_status=t.EdgeStatus(msg.edgeStatus),
        configuration_id=msg.configurationId,
        ring_numbers=tuple(msg.ringNumber),
        node_id=_nid_back(msg.nodeId) if msg.HasField("nodeId") else None,
        metadata=_md_back(msg.metadata),
    )


def request_to_proto(request: t.RapidRequest):
    envelope = proto_class("RapidRequest")()
    field = _REQUEST_FIELDS[type(request)]
    sub = getattr(envelope, field)
    if isinstance(request, t.PreJoinMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.nodeId.CopyFrom(_nid(request.node_id))
    elif isinstance(request, t.JoinMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.nodeId.CopyFrom(_nid(request.node_id))
        sub.ringNumber.extend(request.ring_numbers)
        sub.configurationId = _i64(request.configuration_id)
        sub.metadata.CopyFrom(_md(request.metadata))
    elif isinstance(request, t.BatchedAlertMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        for alert in request.messages:
            sub.messages.add().CopyFrom(_alert(alert))
    elif isinstance(request, t.ProbeMessage):
        sub.sender.CopyFrom(_ep(request.sender))
    elif isinstance(request, t.FastRoundPhase2bMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        for ep in request.endpoints:
            sub.endpoints.add().CopyFrom(_ep(ep))
    elif isinstance(request, t.Phase1aMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        sub.rank.CopyFrom(_rank(request.rank))
    elif isinstance(request, t.Phase1bMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        sub.rnd.CopyFrom(_rank(request.rnd))
        sub.vrnd.CopyFrom(_rank(request.vrnd))
        for ep in request.vval:
            sub.vval.add().CopyFrom(_ep(ep))
    elif isinstance(request, t.Phase2aMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        sub.rnd.CopyFrom(_rank(request.rnd))
        for ep in request.vval:
            sub.vval.add().CopyFrom(_ep(ep))
    elif isinstance(request, t.Phase2bMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        sub.rnd.CopyFrom(_rank(request.rnd))
        for ep in request.endpoints:
            sub.endpoints.add().CopyFrom(_ep(ep))
    elif isinstance(request, t.LeaveMessage):
        sub.sender.CopyFrom(_ep(request.sender))
    elif isinstance(request, t.CohortCutMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        sub.cohort = request.cohort
        for ep in request.endpoints:
            sub.endpoints.add().CopyFrom(_ep(ep))
        for ep in request.joiner_eps:
            sub.joinerEps.add().CopyFrom(_ep(ep))
        for nid in request.joiner_ids:
            sub.joinerIds.add().CopyFrom(_nid(nid))
    elif isinstance(request, t.DelegateDecisionMessage):
        sub.sender.CopyFrom(_ep(request.sender))
        sub.configurationId = _i64(request.configuration_id)
        for ep in request.endpoints:
            sub.endpoints.add().CopyFrom(_ep(ep))
        for ep in request.joiner_eps:
            sub.joinerEps.add().CopyFrom(_ep(ep))
        for nid in request.joiner_ids:
            sub.joinerIds.add().CopyFrom(_nid(nid))
    elif isinstance(request, t.GlobalTierMessage):
        if isinstance(request.payload, (t.GlobalTierMessage, t.GossipMessage)):
            # One level of nesting only — the same contract the native codec
            # enforces; serializing deeper here would emit frames a
            # native-codec peer refuses to decode.
            raise ValueError("nested envelope in GlobalTierMessage payload")
        sub.sender.CopyFrom(_ep(request.sender))
        sub.payload.CopyFrom(request_to_proto(request.payload))
    else:  # pragma: no cover
        raise TypeError(type(request))
    return envelope


def request_from_proto(envelope) -> t.RapidRequest:
    which = envelope.WhichOneof("content")
    if which is None:
        raise ValueError("empty RapidRequest envelope (no content set)")
    sub = getattr(envelope, which)
    if which == "preJoinMessage":
        return t.PreJoinMessage(_ep_back(sub.sender), _nid_back(sub.nodeId))
    if which == "joinMessage":
        return t.JoinMessage(
            sender=_ep_back(sub.sender),
            node_id=_nid_back(sub.nodeId),
            ring_numbers=tuple(sub.ringNumber),
            configuration_id=sub.configurationId,
            metadata=_md_back(sub.metadata),
        )
    if which == "batchedAlertMessage":
        return t.BatchedAlertMessage(
            _ep_back(sub.sender), tuple(_alert_back(m) for m in sub.messages)
        )
    if which == "probeMessage":
        return t.ProbeMessage(_ep_back(sub.sender))
    if which == "fastRoundPhase2bMessage":
        return t.FastRoundPhase2bMessage(
            _ep_back(sub.sender), sub.configurationId,
            tuple(_ep_back(e) for e in sub.endpoints),
        )
    if which == "phase1aMessage":
        return t.Phase1aMessage(_ep_back(sub.sender), sub.configurationId, _rank_back(sub.rank))
    if which == "phase1bMessage":
        return t.Phase1bMessage(
            _ep_back(sub.sender), sub.configurationId, _rank_back(sub.rnd),
            _rank_back(sub.vrnd), tuple(_ep_back(e) for e in sub.vval),
        )
    if which == "phase2aMessage":
        return t.Phase2aMessage(
            _ep_back(sub.sender), sub.configurationId, _rank_back(sub.rnd),
            tuple(_ep_back(e) for e in sub.vval),
        )
    if which == "phase2bMessage":
        return t.Phase2bMessage(
            _ep_back(sub.sender), sub.configurationId, _rank_back(sub.rnd),
            tuple(_ep_back(e) for e in sub.endpoints),
        )
    if which == "leaveMessage":
        return t.LeaveMessage(_ep_back(sub.sender))
    if which == "cohortCutMessage":
        return t.CohortCutMessage(
            sender=_ep_back(sub.sender),
            configuration_id=sub.configurationId,
            cohort=sub.cohort,
            endpoints=tuple(_ep_back(e) for e in sub.endpoints),
            joiner_eps=tuple(_ep_back(e) for e in sub.joinerEps),
            joiner_ids=tuple(_nid_back(n) for n in sub.joinerIds),
        )
    if which == "delegateDecisionMessage":
        return t.DelegateDecisionMessage(
            sender=_ep_back(sub.sender),
            configuration_id=sub.configurationId,
            endpoints=tuple(_ep_back(e) for e in sub.endpoints),
            joiner_eps=tuple(_ep_back(e) for e in sub.joinerEps),
            joiner_ids=tuple(_nid_back(n) for n in sub.joinerIds),
        )
    if which == "globalTierMessage":
        if sub.payload.WhichOneof("content") == "globalTierMessage":
            # One level of nesting only, mirroring the native codec's decode
            # guard (unbounded recursion is a parser DoS).
            raise ValueError("nested envelope in GlobalTierMessage payload")
        return t.GlobalTierMessage(
            sender=_ep_back(sub.sender),
            payload=request_from_proto(sub.payload),
        )
    raise ValueError(f"empty or unknown RapidRequest content: {which}")


def response_to_proto(response: t.RapidResponse):
    envelope = proto_class("RapidResponse")()
    field = _RESPONSE_FIELDS[type(response)]
    sub = getattr(envelope, field)
    if isinstance(response, t.JoinResponse):
        sub.sender.CopyFrom(_ep(response.sender))
        sub.statusCode = int(response.status_code)
        sub.configurationId = _i64(response.configuration_id)
        for ep in response.endpoints:
            sub.endpoints.add().CopyFrom(_ep(ep))
        for nid in response.identifiers:
            sub.identifiers.add().CopyFrom(_nid(nid))
        for ep in response.metadata_keys:
            sub.metadataKeys.add().CopyFrom(_ep(ep))
        for md in response.metadata_values:
            sub.metadataValues.add().CopyFrom(_md(md))
    elif isinstance(response, t.ProbeResponse):
        sub.status = int(response.status)
    else:
        sub.SetInParent()  # Response / ConsensusResponse are empty
    return envelope


def response_from_proto(envelope) -> t.RapidResponse:
    which = envelope.WhichOneof("content")
    if which is None:
        raise ValueError("empty RapidResponse envelope (no content set)")
    sub = getattr(envelope, which)
    if which == "joinResponse":
        return t.JoinResponse(
            sender=_ep_back(sub.sender),
            status_code=t.JoinStatusCode(sub.statusCode),
            configuration_id=sub.configurationId,
            endpoints=tuple(_ep_back(e) for e in sub.endpoints),
            identifiers=tuple(_nid_back(n) for n in sub.identifiers),
            metadata_keys=tuple(_ep_back(e) for e in sub.metadataKeys),
            metadata_values=tuple(_md_back(m) for m in sub.metadataValues),
        )
    if which == "response":
        return t.Response()
    if which == "consensusResponse":
        return t.ConsensusResponse()
    if which == "probeResponse":
        return t.ProbeResponse(t.NodeStatus(sub.status))
    raise ValueError(f"empty or unknown RapidResponse content: {which}")
