"""Reference-wire interop: protobuf schema/conversions (protobuf-only) and
the gRPC transport (needs grpcio — imported lazily so the conversion paths
work without it)."""


def __getattr__(name):
    if name in ("GrpcClient", "GrpcServer"):
        from rapid_tpu.interop import grpc_transport

        return getattr(grpc_transport, name)
    raise AttributeError(f"module 'rapid_tpu.interop' has no attribute {name!r}")


__all__ = ["GrpcClient", "GrpcServer"]
