from rapid_tpu.interop.grpc_transport import GrpcClient, GrpcServer

__all__ = ["GrpcClient", "GrpcServer"]
