"""Runtime-built protobuf schema for reference interop.

The reference's wire protocol is protobuf over gRPC: one unary RPC
``remoting.MembershipService/sendRequest(RapidRequest) -> RapidResponse``
with the message/field layout documented in SURVEY §2.4 (source IDL:
``rapid/src/main/proto/rapid.proto``). To interoperate on the wire, field
numbers and types must match exactly — they are reproduced here as a
programmatic ``FileDescriptorProto`` (no copied .proto file, no protoc
dependency), from which real protobuf message classes are materialized at
import time via ``message_factory``.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_F = descriptor_pb2.FieldDescriptorProto

_POOL = descriptor_pool.DescriptorPool()


def _msg(name, *fields):
    m = descriptor_pb2.DescriptorProto()
    m.name = name
    for f in fields:
        m.field.add().CopyFrom(f)
    return m


def _field(name, number, ftype, label=_F.LABEL_OPTIONAL, type_name=None, oneof=None):
    f = _F()
    f.name = name
    f.number = number
    f.type = ftype
    f.label = label
    if type_name:
        f.type_name = type_name
    if oneof is not None:
        f.oneof_index = oneof
    return f


def _build_file() -> descriptor_pb2.FileDescriptorProto:
    fd = descriptor_pb2.FileDescriptorProto()
    fd.name = "rapid_interop.proto"
    fd.package = "remoting"
    fd.syntax = "proto3"

    T, L = _F, _F  # noqa: N806 — terse aliases for the table below

    # Endpoint { bytes hostname = 1; int32 port = 2; }
    fd.message_type.add().CopyFrom(_msg(
        "Endpoint",
        _field("hostname", 1, T.TYPE_BYTES),
        _field("port", 2, T.TYPE_INT32),
    ))
    # NodeId { int64 high = 1; int64 low = 2; }
    fd.message_type.add().CopyFrom(_msg(
        "NodeId",
        _field("high", 1, T.TYPE_INT64),
        _field("low", 2, T.TYPE_INT64),
    ))
    # Metadata { map<string, bytes> metadata = 1; }  (map = repeated nested entry)
    metadata = _msg(
        "Metadata",
        _field("metadata", 1, T.TYPE_MESSAGE, L.LABEL_REPEATED,
               ".remoting.Metadata.MetadataEntry"),
    )
    entry = _msg(
        "MetadataEntry",
        _field("key", 1, T.TYPE_STRING),
        _field("value", 2, T.TYPE_BYTES),
    )
    entry.options.map_entry = True
    metadata.nested_type.add().CopyFrom(entry)
    fd.message_type.add().CopyFrom(metadata)

    # Enums
    for enum_name, values in (
        ("JoinStatusCode", ["HOSTNAME_ALREADY_IN_RING", "UUID_ALREADY_IN_RING",
                            "SAFE_TO_JOIN", "CONFIG_CHANGED", "MEMBERSHIP_REJECTED"]),
        ("EdgeStatus", ["UP", "DOWN"]),
        ("NodeStatus", ["OK", "BOOTSTRAPPING"]),
    ):
        e = fd.enum_type.add()
        e.name = enum_name
        for i, value_name in enumerate(values):
            v = e.value.add()
            v.name = value_name
            v.number = i

    ep = ".remoting.Endpoint"
    nid = ".remoting.NodeId"
    md = ".remoting.Metadata"

    fd.message_type.add().CopyFrom(_msg(
        "PreJoinMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("nodeId", 2, T.TYPE_MESSAGE, type_name=nid),
        _field("ringNumber", 3, T.TYPE_INT32, L.LABEL_REPEATED),
        _field("configurationId", 4, T.TYPE_INT64),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "JoinMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("nodeId", 2, T.TYPE_MESSAGE, type_name=nid),
        _field("ringNumber", 3, T.TYPE_INT32, L.LABEL_REPEATED),
        _field("configurationId", 4, T.TYPE_INT64),
        _field("metadata", 5, T.TYPE_MESSAGE, type_name=md),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "JoinResponse",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("statusCode", 2, T.TYPE_ENUM, type_name=".remoting.JoinStatusCode"),
        _field("configurationId", 3, T.TYPE_INT64),
        _field("endpoints", 4, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("identifiers", 5, T.TYPE_MESSAGE, L.LABEL_REPEATED, nid),
        _field("metadataKeys", 6, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("metadataValues", 7, T.TYPE_MESSAGE, L.LABEL_REPEATED, md),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "AlertMessage",
        _field("edgeSrc", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("edgeDst", 2, T.TYPE_MESSAGE, type_name=ep),
        _field("edgeStatus", 3, T.TYPE_ENUM, type_name=".remoting.EdgeStatus"),
        _field("configurationId", 4, T.TYPE_INT64),
        _field("ringNumber", 5, T.TYPE_INT32, L.LABEL_REPEATED),
        _field("nodeId", 6, T.TYPE_MESSAGE, type_name=nid),
        _field("metadata", 7, T.TYPE_MESSAGE, type_name=md),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "BatchedAlertMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("messages", 3, T.TYPE_MESSAGE, L.LABEL_REPEATED, ".remoting.AlertMessage"),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "ProbeMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("payload", 3, T.TYPE_BYTES, L.LABEL_REPEATED),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "ProbeResponse",
        _field("status", 1, T.TYPE_ENUM, type_name=".remoting.NodeStatus"),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "FastRoundPhase2bMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("endpoints", 3, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "Rank",
        _field("round", 1, T.TYPE_INT32),
        _field("nodeIndex", 2, T.TYPE_INT32),
    ))
    rank = ".remoting.Rank"
    fd.message_type.add().CopyFrom(_msg(
        "Phase1aMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("rank", 3, T.TYPE_MESSAGE, type_name=rank),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "Phase1bMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("rnd", 3, T.TYPE_MESSAGE, type_name=rank),
        _field("vrnd", 4, T.TYPE_MESSAGE, type_name=rank),
        _field("vval", 5, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "Phase2aMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("rnd", 3, T.TYPE_MESSAGE, type_name=rank),
        _field("vval", 5, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "Phase2bMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("rnd", 3, T.TYPE_MESSAGE, type_name=rank),
        _field("endpoints", 4, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
    ))
    fd.message_type.add().CopyFrom(_msg("LeaveMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
    ))
    fd.message_type.add().CopyFrom(_msg("Response"))
    fd.message_type.add().CopyFrom(_msg("ConsensusResponse"))

    # Hierarchical-membership extension (rapid_tpu/hier): not part of the
    # reference IDL — a reference JVM peer never speaks these — but mirrored
    # here so the wire surface has exactly one schema story and the
    # wire_schema/staticcheck gate can cross-check all four mirrors. The
    # envelope field numbers equal the native codec tags (12-14), continuing
    # the reference's numbering convention.
    fd.message_type.add().CopyFrom(_msg(
        "CohortCutMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("cohort", 3, T.TYPE_INT32),
        _field("endpoints", 4, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("joinerEps", 5, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("joinerIds", 6, T.TYPE_MESSAGE, L.LABEL_REPEATED, nid),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "DelegateDecisionMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("configurationId", 2, T.TYPE_INT64),
        _field("endpoints", 3, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("joinerEps", 4, T.TYPE_MESSAGE, L.LABEL_REPEATED, ep),
        _field("joinerIds", 5, T.TYPE_MESSAGE, L.LABEL_REPEATED, nid),
    ))
    fd.message_type.add().CopyFrom(_msg(
        "GlobalTierMessage",
        _field("sender", 1, T.TYPE_MESSAGE, type_name=ep),
        _field("payload", 2, T.TYPE_MESSAGE, type_name=".remoting.RapidRequest"),
    ))

    # RapidRequest / RapidResponse oneof envelopes.
    request = _msg(
        "RapidRequest",
        _field("preJoinMessage", 1, T.TYPE_MESSAGE, type_name=".remoting.PreJoinMessage", oneof=0),
        _field("joinMessage", 2, T.TYPE_MESSAGE, type_name=".remoting.JoinMessage", oneof=0),
        _field("batchedAlertMessage", 3, T.TYPE_MESSAGE,
               type_name=".remoting.BatchedAlertMessage", oneof=0),
        _field("probeMessage", 4, T.TYPE_MESSAGE, type_name=".remoting.ProbeMessage", oneof=0),
        _field("fastRoundPhase2bMessage", 5, T.TYPE_MESSAGE,
               type_name=".remoting.FastRoundPhase2bMessage", oneof=0),
        _field("phase1aMessage", 6, T.TYPE_MESSAGE, type_name=".remoting.Phase1aMessage", oneof=0),
        _field("phase1bMessage", 7, T.TYPE_MESSAGE, type_name=".remoting.Phase1bMessage", oneof=0),
        _field("phase2aMessage", 8, T.TYPE_MESSAGE, type_name=".remoting.Phase2aMessage", oneof=0),
        _field("phase2bMessage", 9, T.TYPE_MESSAGE, type_name=".remoting.Phase2bMessage", oneof=0),
        _field("leaveMessage", 10, T.TYPE_MESSAGE, type_name=".remoting.LeaveMessage", oneof=0),
        # 11 is the native gossip envelope (no proto mirror by design).
        _field("cohortCutMessage", 12, T.TYPE_MESSAGE,
               type_name=".remoting.CohortCutMessage", oneof=0),
        _field("delegateDecisionMessage", 13, T.TYPE_MESSAGE,
               type_name=".remoting.DelegateDecisionMessage", oneof=0),
        _field("globalTierMessage", 14, T.TYPE_MESSAGE,
               type_name=".remoting.GlobalTierMessage", oneof=0),
    )
    request.oneof_decl.add().name = "content"
    fd.message_type.add().CopyFrom(request)

    response = _msg(
        "RapidResponse",
        _field("joinResponse", 1, T.TYPE_MESSAGE, type_name=".remoting.JoinResponse", oneof=0),
        _field("response", 2, T.TYPE_MESSAGE, type_name=".remoting.Response", oneof=0),
        _field("consensusResponse", 3, T.TYPE_MESSAGE,
               type_name=".remoting.ConsensusResponse", oneof=0),
        _field("probeResponse", 4, T.TYPE_MESSAGE, type_name=".remoting.ProbeResponse", oneof=0),
    )
    response.oneof_decl.add().name = "content"
    fd.message_type.add().CopyFrom(response)
    return fd


# Register the runtime-built descriptor file; the pool retains it (the
# binding would never be read — registration is the point).
_POOL.Add(_build_file())

_CLASSES = {
    name: message_factory.GetMessageClass(_POOL.FindMessageTypeByName(f"remoting.{name}"))
    for name in (
        "Endpoint", "NodeId", "Metadata", "PreJoinMessage", "JoinMessage", "JoinResponse",
        "AlertMessage", "BatchedAlertMessage", "ProbeMessage", "ProbeResponse",
        "FastRoundPhase2bMessage", "Rank", "Phase1aMessage", "Phase1bMessage",
        "Phase2aMessage", "Phase2bMessage", "LeaveMessage", "Response",
        "ConsensusResponse", "CohortCutMessage", "DelegateDecisionMessage",
        "GlobalTierMessage", "RapidRequest", "RapidResponse",
    )
}


def proto_class(name: str):
    """The materialized protobuf class for ``remoting.<name>``."""
    return _CLASSES[name]


GRPC_METHOD = "/remoting.MembershipService/sendRequest"
