"""gRPC transport speaking the reference's wire protocol.

Implements the messaging SPI over the exact RPC the reference serves —
``remoting.MembershipService/sendRequest`` (rapid.proto:9-11) with
protobuf-encoded ``RapidRequest``/``RapidResponse`` envelopes. By default
compatibility is at the RPC/wire layer only, because the tpu-native topology
orders rings differently from Java (our ``ring_key`` hashes the port as
8 bytes and sorts keys/identifiers unsigned; the reference hashes 4-byte
ints and compares signed, ``MembershipView.java:579-587``), so configuration
ids and observer sets would diverge. ``Settings(topology="java")`` closes
that gap: it switches the ring ordering and configuration-id fold to
reference-exact semantics (rapid_tpu.protocol.view.TOPOLOGY_JAVA, pinned in
tests/test_view_java_compat.py), making mixed Java/rapid_tpu clusters over
this transport possible in principle. Either way the transport buys the
reference's operational surface — gRPC tooling, interceptors, proxies.
Built on grpc.aio with a generic method handler (no generated stubs; the
schema is materialized at runtime, rapid_tpu.interop.proto_schema).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Optional

import grpc
import grpc.aio

from rapid_tpu.errors import ShuttingDownError
from rapid_tpu.interop.convert import (
    request_from_proto,
    request_to_proto,
    response_from_proto,
    response_to_proto,
)
from rapid_tpu.interop.proto_schema import GRPC_METHOD, proto_class
from rapid_tpu.messaging.base import MessagingClient, MessagingServer
from rapid_tpu.messaging.retries import call_with_retries
from rapid_tpu.messaging.stats import TransportStats
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinMessage,
    NodeStatus,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidRequest,
    RapidResponse,
)

LOG = logging.getLogger(__name__)

_SERVICE = "remoting.MembershipService"
_METHOD = "sendRequest"

#: Trace-context header. rapid.proto has no trace field and its descriptors
#: are frozen by the golden wire fixtures (tests/test_wire_fixtures.py), so
#: the correlation key travels as gRPC metadata — invisible to the message
#: schema, ignored by a reference peer, re-attached to the native request on
#: our side.
TRACE_METADATA_KEY = "rapid-trace-id"


def _trace_metadata(request: RapidRequest):
    trace_id = getattr(request, "trace_id", None)
    if trace_id is None:
        return None
    return ((TRACE_METADATA_KEY, str(trace_id)),)


def _attach_trace(request: RapidRequest, invocation_metadata) -> RapidRequest:
    if not hasattr(request, "trace_id") or invocation_metadata is None:
        return request
    for key, value in invocation_metadata:
        if key == TRACE_METADATA_KEY:
            try:
                return dataclasses.replace(request, trace_id=int(value))
            except (ValueError, TypeError):
                return request
    return request


def _serialize_response(response_proto) -> bytes:
    return response_proto.SerializeToString()


def _deserialize_request(data: bytes):
    msg = proto_class("RapidRequest")()
    msg.ParseFromString(data)
    return msg


class GrpcServer(MessagingServer):
    """grpc.aio server exposing the reference's single unary RPC."""

    def __init__(self, listen_address: Endpoint) -> None:
        self.listen_address = listen_address
        self._service = None
        self._server: Optional[grpc.aio.Server] = None
        self.stats = TransportStats()  # paper Table 2 accounting

    def set_membership_service(self, service) -> None:
        self._service = service

    async def start(self) -> None:
        server = grpc.aio.server()

        async def send_request(request_proto, context):
            self.stats.rx(request_proto.ByteSize())
            request = _attach_trace(
                request_from_proto(request_proto), context.invocation_metadata()
            )
            if self._service is None:
                if isinstance(request, ProbeMessage):
                    # BOOTSTRAPPING probes before the service exists
                    # (GrpcServer.java:77-96).
                    out = response_to_proto(ProbeResponse(status=NodeStatus.BOOTSTRAPPING))
                    self.stats.tx(out.ByteSize())
                    return out
                await context.abort(grpc.StatusCode.UNAVAILABLE, "bootstrapping")
            response = await self._service.handle_message(request)
            out = response_to_proto(response)
            self.stats.tx(out.ByteSize())
            return out

        handler = grpc.unary_unary_rpc_method_handler(
            send_request,
            request_deserializer=_deserialize_request,
            response_serializer=_serialize_response,
        )
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, {_METHOD: handler}),)
        )
        bound = server.add_insecure_port(
            f"{self.listen_address.hostname}:{self.listen_address.port}"
        )
        if bound == 0:
            # Match the TCP transport's contract: bind failures raise.
            raise OSError(f"could not bind gRPC server to {self.listen_address}")
        await server.start()
        self._server = server

    async def shutdown(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)
            self._server = None


class GrpcClient(MessagingClient):
    """grpc.aio client with a channel cache and per-message-type deadlines
    (GrpcClient.java:85-95, 194-203)."""

    # The reference's rapid.proto has no gossip envelope; GossipBroadcaster
    # refuses this transport at wiring time (see rapid_tpu.messaging.gossip).
    supports_gossip = False

    def __init__(self, my_addr: Endpoint, settings: Optional[Settings] = None) -> None:
        self.my_addr = my_addr
        self._settings = settings if settings is not None else Settings()
        self._channels: Dict[Endpoint, grpc.aio.Channel] = {}
        self._shut_down = False
        self.stats = TransportStats()  # paper Table 2 accounting

    def _timeout_s_for(self, request: RapidRequest) -> float:
        if isinstance(request, (JoinMessage, PreJoinMessage)):
            return self._settings.rpc_join_timeout_ms / 1000.0
        if isinstance(request, ProbeMessage):
            return self._settings.rpc_probe_timeout_ms / 1000.0
        return self._settings.rpc_timeout_ms / 1000.0

    def _channel(self, remote: Endpoint) -> grpc.aio.Channel:
        channel = self._channels.get(remote)
        if channel is None:
            channel = grpc.aio.insecure_channel(f"{remote.hostname}:{remote.port}")
            self._channels[remote] = channel
        return channel

    async def _attempt(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        if self._shut_down:
            raise ShuttingDownError(f"client {self.my_addr} is shut down")
        channel = self._channel(remote)
        call = channel.unary_unary(
            GRPC_METHOD,
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=lambda data: _parse_response(data),
        )
        request_proto = request_to_proto(request)
        self.stats.tx(request_proto.ByteSize())
        response_proto = await call(
            request_proto,
            timeout=self._timeout_s_for(request),
            metadata=_trace_metadata(request),
        )
        self.stats.rx(response_proto.ByteSize())
        return response_from_proto(response_proto)

    async def send(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        return await call_with_retries(
            lambda: self._attempt(remote, request), self._settings.rpc_default_retries
        )

    async def send_best_effort(
        self, remote: Endpoint, request: RapidRequest
    ) -> Optional[RapidResponse]:
        try:
            return await self._attempt(remote, request)
        except ShuttingDownError:
            raise
        except Exception:  # noqa: BLE001 — the best-effort contract
            # (IMessagingClient.java:25-49): one attempt, None on any
            # transport failure; only shutdown races propagate (above).
            return None

    async def shutdown(self) -> None:
        self._shut_down = True
        for channel in self._channels.values():
            await channel.close()
        self._channels.clear()


def _parse_response(data: bytes):
    msg = proto_class("RapidResponse")()
    msg.ParseFromString(data)
    return msg
