"""Leaderless Fast Paxos: count identical cut proposals; fall back to classic
Paxos on a jittered timer.

Semantics follow ``FastPaxos.java``: every node broadcasts its proposal as an
implicit fast-round phase2b vote; a node decides once it has seen
``N - F`` votes total *and* ``N - F`` votes for one identical proposal, where
``F = floor((N-1)/4)`` (``FastPaxos.java:125-156``). Each ``propose`` also arms
a classic-round fallback after an expovariate jitter with rate 1/N over a base
delay (``FastPaxos.java:200-203``), cancelled on decision.

The same tally runs batched on TPU in ``rapid_tpu.ops.consensus``; this class
is the per-node host engine and oracle.
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, Optional, Sequence, Set, Tuple

from rapid_tpu.protocol.paxos import BroadcastFn, OnDecideFn, Paxos, SendFn
from rapid_tpu.utils.flight_recorder import EventName, FlightRecorder
from rapid_tpu.types import (
    ConsensusResponse,
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    RapidRequest,
    RapidResponse,
)
from rapid_tpu.utils.clock import CancelHandle, Clock

BASE_DELAY_MS = 1000


def fast_paxos_quorum(n: int) -> int:
    """N - F with F = floor((N-1)/4) (FastPaxos.java:145-146)."""
    return n - (n - 1) // 4


class FastPaxos:
    def __init__(
        self,
        my_addr: Endpoint,
        configuration_id: int,
        membership_size: int,
        broadcast_fn: BroadcastFn,
        send_fn: SendFn,
        on_decide: OnDecideFn,
        clock: Clock,
        consensus_fallback_base_delay_ms: int = BASE_DELAY_MS,
        rng: Optional[random.Random] = None,
        vote_tally=None,
        on_classic_round=None,
        recorder: Optional[FlightRecorder] = None,
        trace_supplier: Optional[Callable[[], Optional[int]]] = None,
    ) -> None:
        self.my_addr = my_addr
        self.configuration_id = configuration_id
        self.n = membership_size
        self._broadcast = broadcast_fn
        self._clock = clock
        self._base_delay_ms = consensus_fallback_base_delay_ms
        # Identity-seeded default (the service always injects its own rng;
        # this covers direct construction): decorrelated across nodes AND
        # configurations, reproducible across runs.
        self._rng = (
            rng
            if rng is not None
            else random.Random(f"paxos:{my_addr}:{configuration_id}")
        )
        # Pluggable tally: None = host hash-map counting; a DeviceVoteTally
        # turns each vote into a device-array write with the quorum check on
        # the accelerator (rapid_tpu.protocol.device_vote_tally).
        self._vote_tally = vote_tally
        # Observer hook: fires when the jittered fallback actually engages a
        # classic round (i.e. the fast round failed to clear in time). The
        # membership service routes this to the declared-but-never-fired
        # reference event VIEW_CHANGE_ONE_STEP_FAILED.
        self._on_classic_round = on_classic_round
        self._votes_per_proposal: Dict[Tuple[Endpoint, ...], int] = {}
        self._votes_received: Set[Endpoint] = set()
        self.decided = False
        #: Which path produced the decision: "fast" (round-1 quorum of
        #: identical votes) or "classic" (the fallback's Paxos learner).
        #: None until decided. The service labels the agreement-phase
        #: histogram with it — the fast/classic split arXiv:1308.1358
        #: identifies as the boundary worth measuring.
        self.decided_path: Optional[str] = None
        self._fallback_task: Optional[CancelHandle] = None
        self._cancelled = False
        self._my_proposal: Optional[Tuple[Endpoint, ...]] = None
        # Classic rounds escalate 2, 3, 4, ... on every liveness tick until a
        # decision lands — the host-side twin of the engine's per-epoch
        # classic-attempt rotation (models/virtual_cluster.py classic_epoch).
        self._next_classic_round = 2

        # Observability: the service's flight recorder + trace-context
        # supplier; every outgoing vote and the decision event carry the
        # membership change's correlation key.
        self._recorder = recorder
        self._trace = trace_supplier if trace_supplier is not None else (lambda: None)

        def on_decide_wrapped(hosts: Tuple[Endpoint, ...]) -> None:
            if self.decided:
                return
            self.decided = True
            # The classic learner (Paxos.handle_phase2b) latches its own
            # decided flag before invoking us; the fast-round tally calls
            # straight in — so the inner engine's flag tells the paths apart.
            self.decided_path = "classic" if self.paxos.decided else "fast"
            if self._fallback_task is not None:
                self._fallback_task.cancel()
            if self._recorder is not None:
                self._recorder.record(
                    EventName.CONSENSUS_DECIDED,
                    config_id=self.configuration_id,
                    trace_id=self._trace(),
                    proposal=[str(node) for node in hosts],
                    path=self.decided_path,
                )
            on_decide(hosts)

        self._on_decide = on_decide_wrapped
        self.paxos = Paxos(
            my_addr, configuration_id, membership_size, broadcast_fn, send_fn,
            on_decide_wrapped, recorder=recorder, trace_supplier=trace_supplier,
        )

    def propose(
        self, proposal: Sequence[Endpoint], recovery_delay_ms: Optional[float] = None
    ) -> None:
        """Vote for ``proposal`` in the fast round and arm the classic-round
        fallback (FastPaxos.java:94-108).

        Unlike the reference — whose transport guarantees delivery, so one
        broadcast and one single-shot fallback suffice — the fallback here is
        a recurring liveness tick: every firing re-broadcasts the fast-round
        vote (receivers dedup by sender) and escalates one classic round,
        re-armed with fresh jitter until the decision lands. One lost
        datagram therefore costs one fallback period, never liveness."""
        proposal = tuple(proposal)
        self._my_proposal = proposal
        self.paxos.register_fast_round_vote(proposal)
        self._broadcast(
            FastRoundPhase2bMessage(
                sender=self.my_addr,
                configuration_id=self.configuration_id,
                endpoints=proposal,
                trace_id=self._trace(),
            )
        )
        self._arm_liveness(recovery_delay_ms)

    def _arm_liveness(self, delay_ms: Optional[float] = None) -> None:
        if self._cancelled or self.decided:
            return
        if delay_ms is None:
            delay_ms = self._random_delay_ms()
        self._fallback_task = self._clock.call_later_ms(delay_ms, self._liveness_tick)

    def _liveness_tick(self) -> None:
        if self._cancelled or self.decided:
            return
        if self._my_proposal is not None:
            # Re-offer our fast-round vote: a late quorum can still decide in
            # round 1, and it re-seeds vval for any classic coordinator.
            self._broadcast(
                FastRoundPhase2bMessage(
                    sender=self.my_addr,
                    configuration_id=self.configuration_id,
                    endpoints=self._my_proposal,
                    trace_id=self._trace(),
                )
            )
        self.start_classic_paxos_round()
        self._arm_liveness()

    def handle_message(self, request: RapidRequest) -> RapidResponse:
        """Route the five consensus message types (FastPaxos.java:163-184)."""
        if isinstance(request, FastRoundPhase2bMessage):
            self._handle_fast_round_vote(request)
        elif isinstance(request, Phase1aMessage):
            self.paxos.handle_phase1a(request)
        elif isinstance(request, Phase1bMessage):
            self.paxos.handle_phase1b(request)
        elif isinstance(request, Phase2aMessage):
            self.paxos.handle_phase2a(request)
        elif isinstance(request, Phase2bMessage):
            self.paxos.handle_phase2b(request)
        else:
            raise TypeError(f"unexpected consensus message: {type(request)!r}")
        return ConsensusResponse()

    def _handle_fast_round_vote(self, msg: FastRoundPhase2bMessage) -> None:
        """FastPaxos.java:125-156."""
        if msg.configuration_id != self.configuration_id:
            return
        if self.decided:
            return
        proposal = tuple(msg.endpoints)
        if self._recorder is not None:
            self._recorder.record(
                EventName.FAST_ROUND_VOTE_RX,
                config_id=self.configuration_id,
                trace_id=msg.trace_id if msg.trace_id is not None else self._trace(),
                voter=str(msg.sender),
            )
        if self._vote_tally is not None:
            winner = self._vote_tally.add_vote(msg.sender, proposal)
            if winner is not None:
                self._on_decide(winner)
            return
        if msg.sender in self._votes_received:
            return
        self._votes_received.add(msg.sender)
        count = self._votes_per_proposal.get(proposal, 0) + 1
        self._votes_per_proposal[proposal] = count
        quorum = fast_paxos_quorum(self.n)
        if len(self._votes_received) >= quorum and count >= quorum:
            self._on_decide(proposal)

    def start_classic_paxos_round(self) -> None:
        """Fallback entry: classic rounds start at round 2 and escalate by
        one on each re-entry (FastPaxos.java:189-195 starts round 2 exactly
        once; the escalation is this implementation's liveness replacement
        for the reference's reliable transport)."""
        if not self.decided:
            if self._on_classic_round is not None:
                # Fires per classic round started (the metric's meaning);
                # the service gates the once-per-configuration
                # VIEW_CHANGE_ONE_STEP_FAILED event itself.
                self._on_classic_round()
            if self._recorder is not None:
                # One event per engagement AND per escalation: the round
                # number distinguishes them in the merged timeline.
                self._recorder.record(
                    EventName.CLASSIC_ROUND_START,
                    config_id=self.configuration_id,
                    trace_id=self._trace(),
                    round=self._next_classic_round,
                )
            self.paxos.start_phase1a(self._next_classic_round)
            self._next_classic_round += 1

    def cancel_fallback(self) -> None:
        self._cancelled = True
        if self._fallback_task is not None:
            self._fallback_task.cancel()

    def _random_delay_ms(self) -> float:
        """Expovariate jitter with rate 1/N over the base delay, keeping the
        expected number of concurrent classic coordinators ~constant
        (FastPaxos.java:200-203)."""
        jitter_rate = 1.0 / max(self.n, 1)
        jitter = -1000.0 * math.log(1.0 - self._rng.random()) / jitter_rate
        return jitter + self._base_delay_ms
