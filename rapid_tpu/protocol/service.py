"""The membership service: single protocol engine per endpoint.

Orchestration semantics follow ``MembershipService.java``: one serialized
protocol context handles every message (the reference serializes via a
single-thread executor, ``SharedResources.java:53``; here an asyncio lock),
owns alert batching (100 ms quiescence window), join bookkeeping, failure-
detector scheduling, and view-change application.

Message flow (MembershipService.java:174-196): every RapidRequest enters
``handle_message``; alerts feed the cut detector; a released cut becomes a
Fast Paxos proposal; the decision mutates the K-ring view, notifies
subscribers, re-arms failure detectors, and unblocks joiners.
"""

from __future__ import annotations

import asyncio
import logging
import random
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rapid_tpu.errors import NodeNotInRingError
from rapid_tpu.messaging.base import Broadcaster, MessagingClient, UnicastToAllBroadcaster
from rapid_tpu.monitoring.base import EdgeFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.events import ClusterEvents, ClusterStatusChange, NodeStatusChange
from rapid_tpu.protocol.fast_paxos import FastPaxos
from rapid_tpu.protocol.metadata import FrozenMetadata, MetadataManager
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    CohortCutMessage,
    DelegateDecisionMessage,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    GlobalTierMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidRequest,
    RapidResponse,
    Response,
)
from rapid_tpu.utils import exposition
from rapid_tpu.utils.clock import AsyncioClock, Clock
from rapid_tpu.utils.flight_recorder import EventName, FlightRecorder, mint_trace_id
from rapid_tpu.utils.health import NodeHealth
from rapid_tpu.utils.metrics import Metrics

LOG = logging.getLogger(__name__)

CONSENSUS_TYPES = (
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)

#: Hierarchical-membership traffic (rapid_tpu/hier). The flat service
#: acknowledges-and-ignores these (a flat node can share a wire with a
#: hierarchical cluster without raising); HierMembershipService overrides
#: ``_handle_hier_message`` with the real cohort/global-tier routing.
HIER_TYPES = (CohortCutMessage, DelegateDecisionMessage, GlobalTierMessage)

#: Member-initiated config pulls ride the join phase-2 handler stamped with
#: the requester's CURRENT configuration id: an up-to-date peer recognizes a
#: member asking about the configuration it already inhabits and answers with
#: a compact "unchanged" response instead of streaming the full O(N)
#: configuration (the idle heartbeat fires every 30 s on every member — at
#: production N the difference is the whole cost of the heartbeat). A peer on
#: a DIFFERENT configuration takes the mismatch branch and streams, exactly
#: as it does for the joiner's -1 sentinel after HOSTNAME_ALREADY_IN_RING
#: (Cluster.java:374-381).
#:
#: Java-topology clusters keep the -1 sentinel instead: that mode exists so a
#: reference JVM peer can share the ring (PARITY.md), and the reference's
#: phase-2 handler has no unchanged fast path — a config-id MATCH there parks
#: the response future behind a (filtered, never-decided) UP alert until RPC
#: timeout and pollutes the peer's alert stream every heartbeat. The sentinel
#: is guaranteed to mismatch, so any implementation answers immediately.
#: Native-topology clusters cannot contain reference peers (ring orders and
#: configuration ids diverge at the first hash), so the optimization is safe
#: exactly where it is enabled.
CATCH_UP_CONFIG_ID = -1

#: Alert batches are re-broadcast unconditionally this many times (our own
#: copy of the original broadcast may itself have been lost, leaving us with
#: no local evidence that a cut is pending), then only while the cut detector
#: or consensus still shows the cut unresolved, capped at _MAX_REDELIVERIES so
#: a permanently sub-L straggler report cannot generate traffic forever.
_UNCONDITIONAL_REDELIVERIES = 5
_MAX_REDELIVERIES = 30

#: Config-sync pulls per configuration when the only suspicion is an
#: unresolved cut report (a permanently sub-L straggler would otherwise pull
#: a full membership snapshot every interval forever — same rationale as
#: _MAX_REDELIVERIES). The stronger suspicions — an undecided proposal, or a
#: decision we could not apply — stay uncapped: those states MUST resolve and
#: the traffic stops the moment they do.
_MAX_REPORT_ONLY_SYNC_PULLS = 30

#: Futile decided-config catch-up pulls before the wedge is escalated to an
#: ERROR log + flight-recorder event + metrics counter. The pulls themselves
#: stay uncapped (a decision we could not apply MUST eventually resolve, and
#: the only path is a pull), but a cluster that crashed between deciding and
#: answering leaves this node retrying forever — after this many futile
#: attempts that retry loop becomes an observable incident instead of a
#: silent one.
_WEDGED_PULLS_ERROR_THRESHOLD = 100

#: Phase-decomposed convergence SLO timer (utils/metrics.py phase family):
#: one membership change splits into detection (first alert evidence ->
#: proposal release, i.e. the H-watermark crossing that frees the cut),
#: agreement (proposal -> consensus decision, labeled fast/classic by which
#: path decided — the boundary arXiv:1308.1358 measures), and delivery
#: (decision -> view applied + subscribers notified). Rendered as
#: ``rapid_view_change_phase_ms_bucket{phase=...}`` histograms.
_PHASE_TIMER = "view_change_phase"
_MARK_DETECTION = "vc_phase_detection"
_MARK_AGREEMENT = "vc_phase_agreement"
_MARK_DELIVERY = "vc_phase_delivery"


class MembershipService:
    def __init__(
        self,
        my_addr: Endpoint,
        cut_detector: MultiNodeCutDetector,
        view: MembershipView,
        settings: Settings,
        client: MessagingClient,
        fd_factory: EdgeFailureDetectorFactory,
        metadata_map: Optional[Dict[Endpoint, FrozenMetadata]] = None,
        subscriptions: Optional[Dict[ClusterEvents, List]] = None,
        clock: Optional[Clock] = None,
        broadcaster: Optional[Broadcaster] = None,
        rng: Optional[random.Random] = None,
        vote_tally_factory=None,
        node_id: Optional[NodeId] = None,
    ) -> None:
        self.my_addr = my_addr
        # This node's own identifier. Required for the config catch-up path
        # (the pull rides the join phase-2 config-stream branch, which
        # authenticates membership by endpoint + identifier); without it the
        # service falls back to reference-style KICKED recovery.
        self.node_id = node_id
        self.settings = settings
        # The `# guarded-by:` comments below are machine-checked annotations
        # (tools/analysis/concurrency.py): a field marked `_lock` may only
        # be MUTATED while the protocol executor is held; one marked
        # `event-loop` is confined to cooperative scheduling (no lock
        # required, but no read->await->write may straddle an await).
        self.view = view  # guarded-by: _lock
        self.cut_detector = cut_detector
        self.client = client
        self.fd_factory = fd_factory
        self.clock = clock if clock is not None else AsyncioClock()
        # Identity-seeded default: per-node jitter streams stay decorrelated
        # (different endpoints, different seeds) but every run of the same
        # node is reproducible — the determinism-audit contract the chaos
        # subsystem (rapid_tpu/sim) builds on. Callers wanting entropy can
        # still inject random.Random(None) explicitly.
        self.rng = rng if rng is not None else random.Random(f"rapid:{my_addr}")
        self.metadata_manager = MetadataManager()  # guarded-by: _lock
        if metadata_map:
            self.metadata_manager.add_metadata(metadata_map)
        self.broadcaster = (
            broadcaster if broadcaster is not None else UnicastToAllBroadcaster(client, self.rng)
        )
        # vote_tally_factory(membership_size) -> tally object, re-created per
        # configuration (e.g. rapid_tpu.protocol.device_vote_tally.DeviceVoteTally).
        self._vote_tally_factory = vote_tally_factory
        self.subscriptions: Dict[ClusterEvents, List] = {event: [] for event in ClusterEvents}
        if subscriptions:
            for event, callbacks in subscriptions.items():
                self.subscriptions[event].extend(callbacks)

        # The protocol clock is the metrics clock: timers/marks measure
        # simulated time correctly under ManualClock (wall clock would skew
        # every phase SLO in simulated-time tests and engines).
        self.metrics = Metrics(now_ms=self.clock.now_ms)
        self._convergence_timing = False  # guarded-by: _lock
        self._lock = asyncio.Lock()  # the "protocol executor"
        self._joiners_to_respond_to: Dict[Endpoint, List[asyncio.Future]] = {}  # guarded-by: _lock
        self._joiner_uuid: Dict[Endpoint, NodeId] = {}  # guarded-by: _lock
        self._joiner_metadata: Dict[Endpoint, FrozenMetadata] = {}  # guarded-by: _lock
        self._announced_proposal = False  # guarded-by: _lock
        self._send_queue: List[AlertMessage] = []  # guarded-by: _lock
        self._last_enqueue_ms: float = -1.0  # guarded-by: _lock
        self._background_tasks: List[asyncio.Task] = []  # guarded-by: event-loop
        self._fd_tasks: List[asyncio.Task] = []  # guarded-by: event-loop
        self._fd_generation = 0  # guarded-by: event-loop
        self._stopped = False  # guarded-by: event-loop
        # Delivery-liveness state (droppable transports; settings.py):
        # alerts broadcast for the current configuration (redelivery buffer),
        # catch-up bookkeeping, and the config-id history used to tell
        # straggler traffic from evidence of an unknown configuration.
        self._alerts_sent: List[AlertMessage] = []  # guarded-by: _lock
        self._redeliveries_this_config = 0  # guarded-by: _lock
        self._catch_up_inflight = False  # guarded-by: event-loop
        self._catch_up_tasks: Set[asyncio.Task] = set()  # guarded-by: event-loop
        # Edge-failure notifications spawned from failure-detector callbacks:
        # tracked so the loop cannot garbage-collect one mid-flight and so
        # shutdown can cancel-and-await instead of orphaning them.
        self._edge_notify_tasks: Set[asyncio.Task] = set()  # guarded-by: event-loop
        self._last_catch_up_ms = float("-inf")  # guarded-by: event-loop
        self._last_beacon_ms = float("-inf")  # guarded-by: event-loop
        # Idle-heartbeat timer starts at construction: a fresh node is
        # current by definition and owes no immediate anti-entropy pull.
        self._last_idle_sync_ms = self.clock.now_ms()  # guarded-by: event-loop
        self._decision_pending_catch_up = False  # guarded-by: _lock
        self._kicked_signalled = False  # guarded-by: _lock
        self._report_only_sync_pulls = 0  # guarded-by: _lock
        self._undecided_suspicion_ticks = 0  # guarded-by: _lock
        self._wedged_pulls = 0  # guarded-by: _lock
        self._one_step_failed_notified = False  # guarded-by: _lock
        self._known_config_ids: "OrderedDict[int, bool]" = OrderedDict()  # guarded-by: _lock
        self._remember_config_id(self.view.configuration_id)

        # Observability: per-node flight recorder (utils/flight_recorder.py)
        # and the trace-context key for the membership change currently in
        # flight — minted at the first local alert, adopted from the first
        # traced inbound message, cleared when the view change commits.
        self.recorder = FlightRecorder(node=str(my_addr), clock=self.clock)
        self._trace_id: Optional[int] = None  # guarded-by: _lock
        if hasattr(self.cut_detector, "bind_recorder"):
            self.cut_detector.bind_recorder(self.recorder, lambda: self._trace_id)

        self.broadcaster.set_membership(self.view.ring(0))
        self._fast_paxos = self._new_fast_paxos()  # guarded-by: _lock

        # The recording opens with the configuration this node entered
        # (bootstrap or join): a merged timeline then shows every node, even
        # one that crashes before it ever witnesses a membership change.
        self.recorder.record(
            EventName.VIEW_CHANGE,
            config_id=self.view.configuration_id,
            membership_size=self.view.membership_size,
            changes=0,
            origin="startup",
        )

        # Inform the application that the start/join completed
        # (MembershipService.java:162-168).
        self._notify(ClusterEvents.VIEW_CHANGE, self._initial_view_change())

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        """Arm the alert batcher, delivery-liveness loops, and failure
        detectors."""
        self._background_tasks.append(asyncio.ensure_future(self._alert_batcher_loop()))
        self._background_tasks.append(asyncio.ensure_future(self._alert_redelivery_loop()))
        self._background_tasks.append(asyncio.ensure_future(self._config_sync_loop()))
        self._create_failure_detectors()

    async def shutdown(self) -> None:
        self._stopped = True
        self._fast_paxos.cancel_fallback()
        fd_tasks = self._cancel_failure_detectors()
        # Snapshot-and-clear BEFORE awaiting (the interleaving-hazard
        # analysis caught the old shape — read into gather, clear() after
        # it — which would silently drop any task appended mid-await).
        background_tasks, self._background_tasks = self._background_tasks, []
        for task in background_tasks:
            task.cancel()
        catch_up_tasks = list(self._catch_up_tasks)
        for task in catch_up_tasks:
            task.cancel()
        notify_tasks = list(self._edge_notify_tasks)
        for task in notify_tasks:
            task.cancel()
        # Await detectors too: a mid-tick probe must finish (or unwind) before
        # the client underneath it is shut down.
        await asyncio.gather(
            *background_tasks, *fd_tasks, *catch_up_tasks, *notify_tasks,
            return_exceptions=True,
        )
        await self.client.shutdown()

    # ------------------------------------------------------------------
    # accessors (Cluster API surface)
    # ------------------------------------------------------------------

    @property
    def membership(self) -> List[Endpoint]:
        return self.view.ring(0)

    @property
    def membership_size(self) -> int:
        return self.view.membership_size

    def get_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return self.metadata_manager.get_all_metadata()

    def register_subscription(self, event: ClusterEvents, callback) -> None:
        self.subscriptions[event].append(callback)

    # ------------------------------------------------------------------
    # observability surface (utils/exposition.py)
    # ------------------------------------------------------------------

    def health(self) -> NodeHealth:
        """This node's health state (utils/health.py vocabulary), derived
        from the protocol's own suspicion machinery — worst condition wins:

        - WEDGED: the decided-config catch-up escalated (futile pulls past
          the error threshold), or the node was evicted (KICKED) — both need
          the application/operator to rejoin or restart;
        - CATCHING_UP: a decided configuration could not be applied locally
          and is being pulled from peers;
        - PROPOSING: a cut proposal announced, consensus undecided;
        - DETECTING: edge reports held below H, or alerts queued to send;
        - STABLE: none of the above.
        """
        if self._kicked_signalled or self._wedged_pulls >= _WEDGED_PULLS_ERROR_THRESHOLD:
            return NodeHealth.WEDGED
        if self._decision_pending_catch_up:
            return NodeHealth.CATCHING_UP
        if self._consensus_pending():
            return NodeHealth.PROPOSING
        if self._send_queue or self.cut_detector.has_pending_reports():
            return NodeHealth.DETECTING
        return NodeHealth.STABLE

    def telemetry_snapshot(self, recorder_tail: Optional[int] = None) -> Dict[str, object]:
        """One unified telemetry snapshot: protocol metrics, health state,
        transport accounting (when the client keeps ``TransportStats``), and
        the flight recording. ``recorder_tail`` bounds the events included
        (None = the whole ring). This dict is the artifact the standalone
        agent's ``--metrics-dump`` writes and ``tools/traceview.py`` /
        ``tools/clustertop.py`` consume."""
        stats = getattr(self.client, "stats", None)
        return {
            "node": str(self.my_addr),
            "configuration_id": self.view.configuration_id,
            "membership_size": self.view.membership_size,
            "health": self.health().value,
            "trace_id": self._trace_id,
            "metrics": self.metrics.summary(),
            "transport": {"client": stats.snapshot() if stats is not None else None},
            "recorder": self.recorder.snapshot(tail=recorder_tail),
        }

    def prometheus_text(self) -> str:
        """The node's telemetry in Prometheus text exposition format, under
        the stable metric names pinned by tests/test_observability.py."""
        return exposition.prometheus_text(self.telemetry_snapshot(recorder_tail=0))

    # ------------------------------------------------------------------
    # message entry point (MembershipService.java:174-196)
    # ------------------------------------------------------------------

    async def handle_message(self, request: RapidRequest) -> RapidResponse:
        # dispatched-elsewhere: GossipMessage — gossip envelopes are
        # unwrapped by the broadcaster's router facade (messaging/gossip.py
        # GossipRouter, installed via Cluster._server_handler) which relays
        # and then forwards only the PAYLOAD here; a raw GossipMessage never
        # reaches this chain. The dispatch analyzer verifies the exemption.
        if isinstance(request, ProbeMessage):
            # Probes bypass the protocol context (MembershipService.java:449-452).
            return ProbeResponse()
        if isinstance(request, PreJoinMessage):
            async with self._lock:
                return self._handle_pre_join(request)
        if isinstance(request, JoinMessage):
            async with self._lock:
                future = self._handle_join_phase2(request)
            if isinstance(future, asyncio.Future):
                return await future
            return future
        if isinstance(request, BatchedAlertMessage):
            self._note_config_evidence(request)
            async with self._lock:
                return self._handle_batched_alerts(request)
        if isinstance(request, CONSENSUS_TYPES):
            self._note_config_evidence(request)
            async with self._lock:
                self._adopt_trace(request.trace_id)
                return self._fast_paxos.handle_message(request)
        if isinstance(request, LeaveMessage):
            async with self._lock:
                self._edge_failure_notification(
                    request.sender, self.view.configuration_id
                )
            return Response()
        if isinstance(request, HIER_TYPES):
            self._note_config_evidence(request)
            async with self._lock:
                return self._handle_hier_message(request)
        raise TypeError(f"unidentified request type {type(request)!r}")

    # ------------------------------------------------------------------
    # join protocol, server side
    # ------------------------------------------------------------------

    # ------------------------------------------------------------------
    # hierarchy seams (rapid_tpu/hier overrides these; flat defaults here)
    # ------------------------------------------------------------------

    def _handle_hier_message(self, request: RapidRequest) -> RapidResponse:
        """Cohort-cut / delegate / global-tier traffic. Flat mode has no
        hierarchy: acknowledge and ignore (a stray hier frame must not crash
        a flat node). HierMembershipService overrides with real routing."""
        LOG.debug(
            "%s ignoring hierarchical message %s (flat topology)",
            self.my_addr, type(request).__name__,
        )
        return Response()

    def _monitor_topology(self):
        """Who monitors whom: an object answering ``subjects_of`` /
        ``observers_of`` / ``expected_observers_of`` / ``ring_numbers``.
        Flat mode monitors over the full K-ring view; hierarchical mode
        returns the cohort-scoped topology (rapid_tpu/hier/cohorts.py)."""
        return self.view

    def _cut_view(self):
        """The view the cut detector's implicit edge invalidation walks:
        the full view in flat mode, the node's cohort mini-view in
        hierarchical mode (ring numbers must come from the same ring space
        the explicit alerts used)."""
        return self.view

    def _consensus_pending(self) -> bool:
        """True while a membership change this node knows about is agreed
        but not yet applied — the suspicion signal the redelivery and
        config-sync loops (and the health model) act on. Hierarchical mode
        extends it with 'cohort decided, global decision outstanding'."""
        return self._announced_proposal and not self._fast_paxos.decided

    def _adopt_trace(self, trace_id: Optional[int]) -> None:
        """Dapper-style context propagation, receive side: the first traced
        message about the in-flight membership change donates its trace id,
        so every node's recording of that change shares one correlation key
        even when the local node never saw the originating alert."""
        if self._trace_id is None and trace_id is not None:
            self._trace_id = trace_id

    def _handle_pre_join(self, msg: PreJoinMessage) -> JoinResponse:
        """Phase 1 at the seed (MembershipService.java:203-224)."""
        status = self.view.is_safe_to_join(msg.sender, msg.node_id)
        endpoints: Tuple[Endpoint, ...] = ()
        if status in (JoinStatusCode.SAFE_TO_JOIN, JoinStatusCode.HOSTNAME_ALREADY_IN_RING):
            # Gatekeepers come from the monitoring topology: the full view's
            # predecessor rings in flat mode, the joiner's target cohort's
            # rings in hierarchical mode.
            endpoints = tuple(self._monitor_topology().expected_observers_of(msg.sender))
        LOG.info(
            "join at seed %s for %s: %s (config %d, size %d)",
            self.my_addr, msg.sender, status.name,
            self.view.configuration_id, self.view.membership_size,
        )
        return JoinResponse(
            sender=self.my_addr,
            status_code=status,
            configuration_id=self.view.configuration_id,
            endpoints=endpoints,
        )

    def _handle_join_phase2(self, msg: JoinMessage):
        """Phase 2 at an observer (MembershipService.java:232-289). Returns
        either an immediate JoinResponse or a future resolved after consensus."""
        current_config = self.view.configuration_id
        if current_config == msg.configuration_id:
            if self.view.is_host_present(msg.sender) and self.view.is_identifier_present(
                msg.node_id
            ):
                # Not a joiner: an existing member's config-sync pull stamped
                # with the configuration we both inhabit (configuration ids
                # are content hashes — equal id means identical view).
                # Answer compactly instead of enqueueing a to-be-filtered UP
                # alert or streaming the full O(N) configuration.
                self.metrics.inc("config_pull_unchanged_served")
                return JoinResponse(
                    sender=self.my_addr,
                    status_code=JoinStatusCode.SAFE_TO_JOIN,
                    configuration_id=current_config,
                )
            future: asyncio.Future = asyncio.get_event_loop().create_future()
            self._joiners_to_respond_to.setdefault(msg.sender, []).append(future)
            alert = AlertMessage(
                edge_src=self.my_addr,
                edge_dst=msg.sender,
                edge_status=EdgeStatus.UP,
                configuration_id=current_config,
                ring_numbers=msg.ring_numbers,
                node_id=msg.node_id,
                metadata=msg.metadata,
            )
            self._enqueue_alert(alert)
            return future

        # Configuration changed between phase 1 and 2
        # (MembershipService.java:255-286).
        config = self.view.configuration
        if self.view.is_host_present(msg.sender) and self.view.is_identifier_present(msg.node_id):
            # The cluster already admitted this joiner; stream it the config.
            metadata = self.metadata_manager.get_all_metadata()
            return JoinResponse(
                sender=self.my_addr,
                status_code=JoinStatusCode.SAFE_TO_JOIN,
                configuration_id=config.configuration_id,
                endpoints=config.endpoints,
                identifiers=config.node_ids,
                metadata_keys=tuple(metadata.keys()),
                metadata_values=tuple(metadata.values()),
            )
        if self.view.is_identifier_present(msg.node_id):
            # Known identifier, absent host: identifier history is
            # append-only, so this view is at-or-past the sender's EVICTION —
            # a pre-join stale view would never have seen its identifier.
            # Return the configuration as verifiable eviction proof (the
            # sender checks identifiers ⊇ its own ∧ itself ∉ endpoints); a
            # plain joiner retrying phase 2 ignores the payload and retries
            # phase 1 on the status code as before.
            return JoinResponse(
                sender=self.my_addr,
                status_code=JoinStatusCode.CONFIG_CHANGED,
                configuration_id=config.configuration_id,
                endpoints=config.endpoints,
                identifiers=config.node_ids,
            )
        return JoinResponse(
            sender=self.my_addr,
            status_code=JoinStatusCode.CONFIG_CHANGED,
            configuration_id=config.configuration_id,
        )

    # ------------------------------------------------------------------
    # alert pipeline (MembershipService.java:300-354)
    # ------------------------------------------------------------------

    def _handle_batched_alerts(self, batch: BatchedAlertMessage) -> Response:
        self.metrics.inc("alerts_received", len(batch.messages))
        config_id = self.view.configuration_id
        self._adopt_trace(batch.trace_id)
        self.recorder.record(
            EventName.ALERT_BATCH_RX,
            config_id=config_id,
            trace_id=batch.trace_id if batch.trace_id is not None else self._trace_id,
            sender=str(batch.sender),
            alerts=len(batch.messages),
        )
        valid = [
            self._extract_joiner_details(msg)
            for msg in batch.messages
            if self._filter_alert(msg, config_id)
        ]
        if valid and not self._announced_proposal:
            # Detection phase opens at the first alert evidence of this
            # change — received here, or enqueued locally (_enqueue_alert).
            # Same staleness policy as the convergence timer: a mark left by
            # evidence that never led to a proposal (one spurious FD blip,
            # tally below L) would otherwise inflate a much later change's
            # detection sample by hours.
            now = self.clock.now_ms()
            if (
                not self.metrics.has_mark(_MARK_DETECTION)
                or self.metrics.elapsed_since_ms(_MARK_DETECTION, now)
                > self._stale_evidence_ms()
            ):
                self.metrics.mark(_MARK_DETECTION, now)
        if self._announced_proposal:
            # We already initiated consensus and cannot revise our proposal.
            return Response()

        # One batched detector pass (host hash-map or device kernel —
        # DeviceCutDetector overrides aggregate_batch with a fused kernel).
        # The invalidation view matches the ring space the alerts reported
        # in (_cut_view: full view flat, cohort mini-view hierarchical).
        proposal = self.cut_detector.aggregate_batch(valid, self._cut_view())

        if proposal:
            LOG.info("%s proposing membership change of size %d", self.my_addr, len(proposal))
            self.metrics.inc("proposals_announced")
            self.recorder.record(
                EventName.FAST_ROUND_PROPOSAL,
                config_id=config_id,
                trace_id=self._trace_id,
                proposal=[str(node) for node in proposal],
            )
            self._announced_proposal = True
            now = self.clock.now_ms()
            if not self._convergence_timing:
                self._convergence_timing = True
                self.metrics.mark("view_change_convergence", now)
            # Detection phase closes at the H-threshold crossing that
            # released this cut (the detector frees the proposal in the same
            # synchronous pass); agreement opens with the proposal.
            if self.metrics.has_mark(_MARK_DETECTION):
                self.metrics.record_ms(
                    _PHASE_TIMER,
                    self.metrics.elapsed_since_ms(_MARK_DETECTION, now),
                    phase="detection",
                )
                self.metrics.clear_mark(_MARK_DETECTION)
            self.metrics.mark(_MARK_AGREEMENT, now)
            self._notify(
                ClusterEvents.VIEW_CHANGE_PROPOSAL,
                ClusterStatusChange(
                    configuration_id=config_id,
                    membership=tuple(self.view.ring(0)),
                    status_changes=tuple(self._status_changes_for(proposal)),
                ),
            )
            self._fast_paxos.propose(tuple(self.view.ring_zero_sorted(proposal)))
        return Response()

    def _filter_alert(self, msg: AlertMessage, config_id: int) -> bool:
        """Config-id check + the once-in/once-out invariant
        (MembershipService.java:644-675)."""
        if msg.configuration_id != config_id:
            return False
        if msg.edge_status == EdgeStatus.UP and self.view.is_host_present(msg.edge_dst):
            return False
        if msg.edge_status == EdgeStatus.DOWN and not self.view.is_host_present(msg.edge_dst):
            return False
        return True

    def _extract_joiner_details(self, msg: AlertMessage) -> AlertMessage:
        """Save joiner UUID/metadata for the eventual ring add
        (MembershipService.java:677-685)."""
        if msg.edge_status == EdgeStatus.UP:
            if msg.node_id is not None:
                self._joiner_uuid[msg.edge_dst] = msg.node_id
            self._joiner_metadata[msg.edge_dst] = msg.metadata
        return msg

    # ------------------------------------------------------------------
    # consensus decision (MembershipService.java:385-444)
    # ------------------------------------------------------------------

    def _decide_view_change(self, proposal: Tuple[Endpoint, ...]) -> None:
        now = self.clock.now_ms()
        if self.metrics.has_mark(_MARK_AGREEMENT):
            # Agreement phase closes at the consensus decision, labeled by
            # the path that decided it (fast quorum vs classic fallback) —
            # the boundary where the fast path stops paying for itself.
            path = self._fast_paxos.decided_path or "fast"
            self.metrics.record_ms(
                _PHASE_TIMER,
                self.metrics.elapsed_since_ms(_MARK_AGREEMENT, now),
                phase=f"agreement/{path}",
            )
            self.metrics.clear_mark(_MARK_AGREEMENT)
        LOG.info(
            "%s decide view change in config %d (%d nodes): %s",
            self.my_addr, self.view.configuration_id, self.view.membership_size,
            [str(p) for p in proposal],
        )
        # Validate BEFORE mutating anything: alert broadcasts are best-effort
        # (single-attempt, and the UDP hybrid transport ships them as
        # droppable datagrams), so a decision can name a joiner whose UP alert
        # we never saw — leaving us without its UUID. Applying a partial view
        # would fork this node from the cluster; applying half a view and
        # raising mid-loop (the reference NPEs here,
        # MembershipService.java:401-404) would strand it with no failure
        # detectors. Apply nothing and recover instead.
        missing = [
            node
            for node in proposal
            if not self.view.is_host_present(node) and node not in self._joiner_uuid
        ]
        if missing:
            self._recover_from_unknown_joiners(missing)
            return
        # Delivery phase: decision -> view applied + subscribers notified,
        # recorded at the end of _commit_view_change. Armed only once the
        # decision is validated as applicable: the missing-joiner recovery
        # above never commits, and a mark left by it would charge the whole
        # multi-second catch-up pull to "delivery" when the install lands.
        self.metrics.mark(_MARK_DELIVERY, now)
        self._cancel_failure_detectors()

        status_changes: List[NodeStatusChange] = []
        for node in proposal:
            if self.view.is_host_present(node):
                self.view.ring_delete(node)
                status_changes.append(
                    NodeStatusChange(node, EdgeStatus.DOWN, self.metadata_manager.get(node))
                )
                self.metadata_manager.remove_node(node)
            else:
                node_id = self._joiner_uuid.pop(node)
                self.view.ring_add(node, node_id)
                metadata = self._joiner_metadata.pop(node, ())
                if metadata:
                    self.metadata_manager.add_metadata({node: metadata})
                status_changes.append(NodeStatusChange(node, EdgeStatus.UP, metadata))

        change = ClusterStatusChange(
            configuration_id=self.view.configuration_id,
            membership=tuple(self.view.ring(0)),
            status_changes=tuple(status_changes),
        )
        self._commit_view_change(change, respond_to=proposal)

    def _commit_view_change(self, change: ClusterStatusChange, respond_to) -> None:
        """The apply/notify tail every view change shares — consensus
        decision and config catch-up alike: metrics, VIEW_CHANGE notify,
        per-configuration reset, failure-detector re-arm (or KICKED), and
        joiner responses."""
        self.metrics.inc("view_changes")
        if self._convergence_timing:
            self.metrics.record_ms(
                "view_change_convergence",
                self.metrics.elapsed_since_ms("view_change_convergence", self.clock.now_ms()),
            )
            self._convergence_timing = False
        # Recorded with the OLD configuration's trace id (the correlation key
        # of the change that produced this view) before the reset clears it.
        self.recorder.record(
            EventName.VIEW_CHANGE,
            config_id=change.configuration_id,
            trace_id=self._trace_id,
            membership_size=len(change.membership),
            changes=len(change.status_changes),
        )
        self._notify(ClusterEvents.VIEW_CHANGE, change)
        self._reset_for_new_configuration()

        if self.view.is_host_present(self.my_addr):
            self._create_failure_detectors()
        elif not self._kicked_signalled:
            LOG.info("%s was kicked out", self.my_addr)
            self._kicked_signalled = True
            self.metrics.inc("kicked")
            self.recorder.record(
                EventName.KICKED, config_id=change.configuration_id
            )
            self._notify(ClusterEvents.KICKED, change)

        self._respond_to_joiners(respond_to)
        if self.metrics.has_mark(_MARK_DELIVERY):
            # Consensus-decision commits only: a catch-up install never
            # armed the mark (its "decision" happened on another node).
            self.metrics.record_ms(
                _PHASE_TIMER,
                self.metrics.elapsed_since_ms(_MARK_DELIVERY, self.clock.now_ms()),
                phase="delivery",
            )
            self.metrics.clear_mark(_MARK_DELIVERY)

    def _reset_for_new_configuration(self) -> None:
        """Per-configuration protocol state reset, shared by the consensus
        decision path and the config catch-up path."""
        self.cut_detector.clear()
        self._announced_proposal = False
        self._alerts_sent.clear()
        self._redeliveries_this_config = 0
        # Joiner bookkeeping is per-configuration: a live joiner re-alerts in
        # the new configuration on its next attempt, and an identifier
        # recorded under an older configuration must never satisfy a later
        # decision's missing-identifier check — installing a stale identifier
        # would silently fork this node's configuration id from the cluster's.
        self._joiner_uuid.clear()
        self._joiner_metadata.clear()
        self._report_only_sync_pulls = 0
        self._undecided_suspicion_ticks = 0
        self._wedged_pulls = 0
        self._one_step_failed_notified = False
        self._decision_pending_catch_up = False
        # Trace context is per membership change: the next change mints or
        # adopts a fresh correlation key. Phase marks likewise — a detection
        # or agreement epoch left over from the superseded configuration
        # must not leak into the next change's phase timings.
        self.metrics.clear_mark(_MARK_DETECTION)
        self.metrics.clear_mark(_MARK_AGREEMENT)
        self._trace_id = None
        self._remember_config_id(self.view.configuration_id)
        self._fast_paxos.cancel_fallback()
        self._fast_paxos = self._new_fast_paxos()
        self.broadcaster.set_membership(self.view.ring(0))

    def _remember_config_id(self, config_id: int, inhabited: bool = True) -> None:
        """Bounded history of configuration ids this node has inhabited
        (value True) or merely verified via a futile pull as not ahead of it
        (value False): both suppress further evidence pulls, but only
        genuinely-inhabited ids qualify a sender for a config beacon — a
        futile-learned id belongs to a chain we never walked, and beaconing
        on it would let two diverged chains beacon each other forever. Ids
        are hash folds, not ordered; history is the only way to tell
        stragglers from configurations we genuinely missed."""
        if inhabited or not self._known_config_ids.get(config_id, False):
            self._known_config_ids[config_id] = inhabited
        self._known_config_ids.move_to_end(config_id)
        while len(self._known_config_ids) > 64:
            # Prefer evicting futile-learned (inhabited=False) entries:
            # straggler ids are unbounded in principle (any peer can stamp
            # any stale id), and letting them push out genuinely-inhabited
            # history would make OUR OWN old configurations look unknown
            # again — re-triggering spurious evidence pulls for traffic we
            # have already verified as behind us. Inhabited history is
            # bounded by real view changes, so it only rotates against
            # itself. The just-remembered id is exempt (evicting what we
            # came to learn would be a no-op cache).
            victim = next(
                (
                    cid
                    for cid, inh in self._known_config_ids.items()
                    if not inh and cid != config_id
                ),
                None,
            )
            if victim is not None:
                del self._known_config_ids[victim]
            else:
                self._known_config_ids.popitem(last=False)

    def _recover_from_unknown_joiners(self, missing: List[Endpoint]) -> None:
        """The cluster decided a view containing joiners whose identifiers we
        never received (their UP alerts were lost in transit). The decided
        configuration — identifiers included — exists in full at every peer
        that applied it, so the primary recovery is a config catch-up pull
        over the reliable path; the config-sync loop keeps retrying random
        peers until one has applied the decision. Only a service that cannot
        pull (no identity plumbed / sync disabled) falls back to the
        reference-style recovery: stop participating and signal ``KICKED`` so
        the application rejoins with a fresh identity."""
        self.metrics.inc("decision_missing_joiner_uuid")
        if self.node_id is not None and self.settings.config_sync_interval_ms > 0:
            LOG.warning(
                "%s cannot apply view change in config %d: no UUID recorded "
                "for joiner(s) %s; pulling the decided configuration",
                self.my_addr,
                self.view.configuration_id,
                [str(n) for n in missing],
            )
            self._decision_pending_catch_up = True
            peer = self._random_peer()
            if peer is not None:
                self._spawn_catch_up(peer)
            return
        LOG.error(
            "%s cannot apply view change in config %d: no UUID recorded for "
            "joiner(s) %s; signalling KICKED for rejoin",
            self.my_addr,
            self.view.configuration_id,
            [str(n) for n in missing],
        )
        self._cancel_failure_detectors()
        self._notify(
            ClusterEvents.KICKED,
            ClusterStatusChange(
                configuration_id=self.view.configuration_id,
                membership=tuple(self.view.ring(0)),
                status_changes=(),
            ),
        )

    def _new_fast_paxos(self) -> FastPaxos:
        vote_tally = (
            self._vote_tally_factory(self.view.membership_size)
            if self._vote_tally_factory is not None
            else None
        )
        return FastPaxos(
            my_addr=self.my_addr,
            configuration_id=self.view.configuration_id,
            membership_size=self.view.membership_size,
            broadcast_fn=self.broadcaster.broadcast,
            send_fn=self.client.send_nowait,
            on_decide=self._decide_view_change,
            clock=self.clock,
            consensus_fallback_base_delay_ms=self.settings.consensus_fallback_base_delay_ms,
            rng=self.rng,
            vote_tally=vote_tally,
            on_classic_round=self._on_fast_round_failed,
            recorder=self.recorder,
            trace_supplier=lambda: self._trace_id,
        )

    def _on_fast_round_failed(self) -> None:
        """The fallback fired before a fast-round quorum formed: classic
        Paxos is engaging. The metric counts every classic round started
        (rounds escalate while undecided); the VIEW_CHANGE_ONE_STEP_FAILED
        event — which the reference DECLARES but never fires
        (ClusterEvents.java:19-23) — fires once per configuration, the
        moment one-step consensus is first abandoned for the slow path."""
        self.metrics.inc("classic_rounds_started")
        if self._one_step_failed_notified:
            return
        self._one_step_failed_notified = True
        self._notify(
            ClusterEvents.VIEW_CHANGE_ONE_STEP_FAILED,
            ClusterStatusChange(
                configuration_id=self.view.configuration_id,
                membership=tuple(self.view.ring(0)),
                status_changes=(),
            ),
        )

    def _respond_to_joiners(self, proposal: Tuple[Endpoint, ...]) -> None:
        """Stream the new configuration to nodes joining through us
        (MembershipService.java:719-744)."""
        config = self.view.configuration
        metadata = self.metadata_manager.get_all_metadata()
        response = JoinResponse(
            sender=self.my_addr,
            status_code=JoinStatusCode.SAFE_TO_JOIN,
            configuration_id=config.configuration_id,
            endpoints=config.endpoints,
            identifiers=config.node_ids,
            metadata_keys=tuple(metadata.keys()),
            metadata_values=tuple(metadata.values()),
        )
        for node in proposal:
            for future in self._joiners_to_respond_to.pop(node, []):
                if not future.done():
                    future.set_result(response)

    # ------------------------------------------------------------------
    # failure detection (MembershipService.java:472-495, 697-714)
    # ------------------------------------------------------------------

    def _edge_failure_notification(self, subject: Endpoint, config_id: int) -> None:
        if config_id != self.view.configuration_id:
            LOG.info(
                "%s ignoring stale failure notification for %s (config %d != %d)",
                self.my_addr, subject, config_id, self.view.configuration_id,
            )
            return
        self._enqueue_alert(
            AlertMessage(
                edge_src=self.my_addr,
                edge_dst=subject,
                edge_status=EdgeStatus.DOWN,
                configuration_id=config_id,
                ring_numbers=tuple(
                    self._monitor_topology().ring_numbers(self.my_addr, subject)
                ),
            )
        )

    async def inject_byzantine_alert(
        self, subject: Endpoint, status: EdgeStatus, ring_numbers: Sequence[int]
    ) -> None:
        """Chaos seam: enqueue an edge report this node NEVER observed — a
        lying observer (rapid_tpu/sim's ``false_alert``/``alert_storm``
        events). The lie rides the real machinery end to end: the batcher
        broadcasts it, redelivery repeats it, and every receiver's H/L cut
        detector tallies the claimed rings exactly as it would honest
        evidence — which is the point: the paper's stability claim (sub-H
        report counts DELAY, never trigger, a view change) is only tested
        by reports that are actually false. Takes the protocol lock like
        any handler; no internal state is bypassed."""
        async with self._lock:
            self._enqueue_alert(
                AlertMessage(
                    edge_src=self.my_addr,
                    edge_dst=subject,
                    edge_status=status,
                    configuration_id=self.view.configuration_id,
                    ring_numbers=tuple(int(r) for r in ring_numbers),
                )
            )

    def _create_failure_detectors(self) -> None:
        if self._stopped:
            return
        self._fd_generation += 1
        generation = self._fd_generation
        config_id = self.view.configuration_id
        try:
            subjects = self._monitor_topology().subjects_of(self.my_addr)
        except NodeNotInRingError:
            # Evicted between the view change and this rearm: no ring
            # position means no subjects to watch — nothing to arm.
            return
        for subject in set(subjects):
            self._fd_tasks.append(
                asyncio.ensure_future(self._fd_loop(subject, generation, config_id))
            )

    async def _fd_loop(self, subject: Endpoint, generation: int, config_id: int) -> None:
        def notifier() -> None:
            task = asyncio.ensure_future(self._notify_edge_failure(subject, config_id))
            self._edge_notify_tasks.add(task)
            task.add_done_callback(self._edge_notify_tasks.discard)

        detector = self.fd_factory.create_instance(subject, notifier)
        while not self._stopped and generation == self._fd_generation:
            await detector.tick()
            await self.clock.sleep_ms(self.settings.failure_detector_interval_ms)

    async def _notify_edge_failure(self, subject: Endpoint, config_id: int) -> None:
        async with self._lock:
            self._edge_failure_notification(subject, config_id)

    def _cancel_failure_detectors(self) -> List[asyncio.Task]:
        self._fd_generation += 1
        cancelled = list(self._fd_tasks)
        for task in cancelled:
            task.cancel()
        self._fd_tasks.clear()
        return cancelled

    # ------------------------------------------------------------------
    # alert batching (MembershipService.java:572-581, 613-637)
    # ------------------------------------------------------------------

    def _stale_evidence_ms(self) -> float:
        """The window in which alerts related to the same membership change
        can plausibly still arrive; evidence marks (the convergence timer
        and the detection-phase mark) older than this belong to a change
        that never happened and are expired rather than trusted."""
        return 10 * (
            self.settings.failure_detector_interval_ms
            + self.settings.batching_window_ms
        )

    def _enqueue_alert(self, msg: AlertMessage) -> None:
        now = self.clock.now_ms()
        self._last_enqueue_ms = now
        self._send_queue.append(msg)
        self.metrics.inc("alerts_enqueued")
        if self._trace_id is None:
            # First local evidence of this membership change: mint the
            # trace id every node's recording of it will share.
            self._trace_id = mint_trace_id(
                str(self.my_addr), msg.configuration_id, now
            )
        self.recorder.record(
            EventName.ALERT_ENQUEUED,
            config_id=msg.configuration_id,
            trace_id=self._trace_id,
            subject=str(msg.edge_dst),
            status=msg.edge_status.name,
        )
        # North-star timer: first local evidence of a membership change until
        # the view change commits. A mark left by evidence that never led to
        # a proposal (e.g. one spurious FD firing, tally below L) would
        # inflate a much later convergence; expire it after the window in
        # which related alerts could plausibly still arrive.
        if (
            self._convergence_timing
            and not self._announced_proposal
            # Once a proposal is announced, convergence is genuinely in
            # flight (possibly slow via the classic fallback) — never expire.
            and self.metrics.elapsed_since_ms("view_change_convergence", now)
            > self._stale_evidence_ms()
        ):
            self._convergence_timing = False
        if not self._convergence_timing:
            self._convergence_timing = True
            self.metrics.mark("view_change_convergence", now)
            # Detection phase (re)opens with the convergence epoch: same
            # staleness policy, same first-evidence semantics.
            self.metrics.mark(_MARK_DETECTION, now)
        elif not self._announced_proposal and not self.metrics.has_mark(_MARK_DETECTION):
            self.metrics.mark(_MARK_DETECTION, now)

    async def _alert_batcher_loop(self) -> None:
        window = self.settings.batching_window_ms
        while not self._stopped:
            await self.clock.sleep_ms(window)
            # Under the protocol executor, like the redelivery and
            # config-sync loops: the queue swap, the redelivery-buffer
            # append, and the trace-id read must not interleave with a
            # handler mutating the same state while parked on an await
            # (surfaced by the unguarded-mutation analysis; previously this
            # loop touched _send_queue/_alerts_sent lock-free, safe only by
            # the accident of having no await inside the tick body).
            async with self._lock:
                if (
                    self._send_queue
                    and self._last_enqueue_ms > 0
                    and (self.clock.now_ms() - self._last_enqueue_ms) > window
                ):
                    messages, self._send_queue = self._send_queue, []
                    self.metrics.inc("alert_batches_sent")
                    self._alerts_sent.extend(messages)
                    self.recorder.record(
                        EventName.ALERT_BATCH_TX,
                        config_id=self.view.configuration_id,
                        trace_id=self._trace_id,
                        alerts=len(messages),
                    )
                    self.broadcaster.broadcast(
                        BatchedAlertMessage(
                            sender=self.my_addr,
                            messages=tuple(messages),
                            trace_id=self._trace_id,
                        )
                    )

    # ------------------------------------------------------------------
    # delivery liveness (droppable transports; settings.py rationale)
    #
    # The reference's protocol fires every broadcast exactly once and stays
    # live because its transport guarantees delivery (Retries.java:43-90,
    # GrpcClient.java:106-115). Here transports may drop (the UDP hybrid
    # ships one-way traffic as datagrams), so the delivery guarantee is
    # re-established at the protocol level: alert batches are re-broadcast
    # while their cut is unresolved, undecided consensus re-arms (fast_paxos
    # re-offers votes and escalates classic rounds), and a node with
    # evidence or suspicion of staleness pulls the current configuration
    # from a peer over the reliable request/response path.
    # ------------------------------------------------------------------

    async def _alert_redelivery_loop(self) -> None:
        """Re-broadcast this configuration's alert batches while the cut they
        announce is unresolved. Receivers are idempotent — the cut detector
        dedups per (subject, ring) and vote tallies dedup per sender — so
        redelivery is always safe. The first few rounds are unconditional
        (our own copy of the original broadcast may itself have been lost,
        leaving no local evidence of a pending cut); afterwards only while
        local state shows the cut in flight, capped at _MAX_REDELIVERIES."""
        interval = self.settings.alert_redelivery_interval_ms
        if interval <= 0:
            return
        while not self._stopped:
            await self.clock.sleep_ms(interval)
            try:
                async with self._lock:
                    if self._stopped:
                        return
                    config_id = self.view.configuration_id
                    # De-duplicate, order-preserving: join retries enqueue
                    # identical UP alerts; receivers dedup anyway (per
                    # subject+ring), so repeats only waste payload.
                    pending = tuple(dict.fromkeys(
                        m for m in self._alerts_sent if m.configuration_id == config_id
                    ))
                    if not pending or self._redeliveries_this_config >= _MAX_REDELIVERIES:
                        continue
                    unresolved = self._consensus_pending() or (
                        not self._announced_proposal
                        and self.cut_detector.has_pending_reports()
                    )
                    if (
                        not unresolved
                        and self._redeliveries_this_config >= _UNCONDITIONAL_REDELIVERIES
                    ):
                        continue
                    self._redeliveries_this_config += 1
                    self.metrics.inc("alert_batches_redelivered")
                    self.recorder.record(
                        EventName.ALERT_REDELIVERY,
                        config_id=config_id,
                        trace_id=self._trace_id,
                        alerts=len(pending),
                        redelivery=self._redeliveries_this_config,
                    )
                    self.broadcaster.broadcast(
                        BatchedAlertMessage(
                            sender=self.my_addr,
                            messages=pending,
                            trace_id=self._trace_id,
                        )
                    )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the liveness loop must survive
                LOG.exception("%s alert redelivery tick failed; continuing", self.my_addr)

    async def _config_sync_loop(self) -> None:
        """Anti-entropy for the configuration itself: while this node has
        reason to believe it is stuck — an undecided proposal, an unresolved
        cut, or a decision it could not apply — pull the current
        configuration from a random peer each interval. The pull rides the
        reliable path, so unlike every broadcast above it cannot be lost."""
        interval = self.settings.config_sync_interval_ms
        if interval <= 0 or self.node_id is None:
            return
        while not self._stopped:
            await self.clock.sleep_ms(interval)
            try:
                async with self._lock:
                    if self._stopped:
                        return
                    # An undecided proposal is normal for the first couple of
                    # intervals of any slow classic decision; only a
                    # PERSISTENTLY undecided one warrants pulling snapshots.
                    if self._consensus_pending():
                        self._undecided_suspicion_ticks += 1
                    else:
                        self._undecided_suspicion_ticks = 0
                    strong = self._decision_pending_catch_up or (
                        self._undecided_suspicion_ticks >= 2
                    )
                    report_only = (
                        not self._announced_proposal
                        and self.cut_detector.has_pending_reports()
                        and self._report_only_sync_pulls < _MAX_REPORT_ONLY_SYNC_PULLS
                    )
                    # Anti-entropy heartbeat (settings rationale): with no
                    # suspicion at all, still pull on the slow idle cadence —
                    # the only channel to a member that missed a decision
                    # with zero local evidence and zero inbound traffic.
                    idle_ms = self.settings.config_sync_idle_interval_ms
                    now = self.clock.now_ms()
                    idle_due = (
                        idle_ms > 0 and now - self._last_idle_sync_ms >= idle_ms
                    )
                    suspicious = (
                        not self._kicked_signalled
                        and not self._catch_up_inflight
                        and (strong or report_only or idle_due)
                    )
                    if suspicious:
                        self._last_idle_sync_ms = now
                    peer = self._random_peer() if suspicious else None
                    if peer is not None and not strong and not idle_due:
                        # Budget counts pulls actually ISSUED: a single-member
                        # view has no peer to pull from, and charging its
                        # no-op ticks would exhaust the report-only budget
                        # before a partner ever appears (advisor round 5).
                        self._report_only_sync_pulls += 1
                    if peer is not None and self._decision_pending_catch_up:
                        # A decision we could not apply keeps pulling
                        # uncapped — but if the peers that decided it all
                        # crashed before answering, this node is wedged on a
                        # configuration nobody can serve. Escalate once so
                        # the wedge is an observable incident, not an
                        # indefinite silent retry loop.
                        self._wedged_pulls += 1
                        if self._wedged_pulls == _WEDGED_PULLS_ERROR_THRESHOLD:
                            self.metrics.inc("catch_up_wedged")
                            self.recorder.record(
                                EventName.UNKNOWN_JOINER_WEDGE,
                                config_id=self.view.configuration_id,
                                trace_id=self._trace_id,
                                futile_pulls=self._wedged_pulls,
                            )
                            LOG.error(
                                "%s wedged: %d futile pulls for a decided "
                                "configuration we could not apply (config %d); "
                                "the deciding peers are likely gone — still "
                                "retrying, operator intervention (restart/"
                                "rejoin) may be required",
                                self.my_addr,
                                self._wedged_pulls,
                                self.view.configuration_id,
                            )
                if peer is not None:
                    await self._catch_up(peer)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — anti-entropy must survive, e.g.
                # a raising application subscriber inside a catch-up install.
                LOG.exception("%s config sync tick failed; continuing", self.my_addr)

    def _note_config_evidence(self, request: RapidRequest) -> None:
        """Traffic stamped with a configuration id this node has never
        inhabited is evidence that the cluster moved somewhere we missed:
        pull from the sender (who, having stamped it, holds that config).
        Ids are hash folds, not ordered, so the known-id history — not a
        comparison — tells stragglers from the future."""
        if self.node_id is None or self.settings.config_sync_interval_ms <= 0:
            return
        if self._stopped or self._kicked_signalled or self._catch_up_inflight:
            return
        if isinstance(request, BatchedAlertMessage):
            config_ids = {m.configuration_id for m in request.messages}
        elif isinstance(request, GlobalTierMessage):
            # The envelope itself is unstamped; the consensus payload inside
            # carries the configuration the sender inhabits. A payload
            # without a stamp (never sent by this implementation) is simply
            # not evidence.
            payload_cid = getattr(request.payload, "configuration_id", None)
            config_ids = set() if payload_cid is None else {payload_cid}
        else:
            config_ids = {request.configuration_id}
        unknown = frozenset(
            cid for cid in config_ids if cid not in self._known_config_ids
        )
        sender = request.sender
        if sender == self.my_addr:
            return
        now = self.clock.now_ms()
        if unknown:
            if now - self._last_catch_up_ms >= self.settings.config_sync_interval_ms:
                self._last_catch_up_ms = now
                self._last_idle_sync_ms = now  # a pull IS the heartbeat
                self._spawn_catch_up(sender, trigger_ids=unknown)
        elif (
            config_ids
            and self.view.configuration_id not in config_ids
            and all(self._known_config_ids.get(cid, False) for cid in config_ids)
        ):
            # Every id is one WE have inhabited (futile-learned ids do NOT
            # qualify — see _remember_config_id) but none is current: the
            # sender is demonstrably behind us (e.g. it missed a decision
            # and its liveness tick keeps re-offering old-config votes).
            # Answer with a config BEACON — a semantically inert alert
            # batch (a self-UP alert is filtered by every receiver) whose
            # config stamp is, to the stale sender, evidence of an unknown
            # configuration: its own evidence pull does the rest. Keeps
            # post-decision staleness recovery prompt without new wire
            # types; the idle-cadence pull remains the no-signal fallback.
            if now - self._last_beacon_ms >= self.settings.config_sync_interval_ms:
                self._last_beacon_ms = now
                self.metrics.inc("config_beacons_sent")
                self.recorder.record(
                    EventName.CONFIG_BEACON_TX,
                    config_id=self.view.configuration_id,
                    trace_id=self._trace_id,
                    peer=str(sender),
                )
                self.client.send_nowait(
                    sender,
                    BatchedAlertMessage(
                        sender=self.my_addr,
                        messages=(
                            AlertMessage(
                                edge_src=self.my_addr,
                                edge_dst=self.my_addr,
                                edge_status=EdgeStatus.UP,
                                configuration_id=self.view.configuration_id,
                                ring_numbers=(),
                            ),
                        ),
                    ),
                )

    def _random_peer(self) -> Optional[Endpoint]:
        members = [m for m in self.view.ring(0) if m != self.my_addr]
        if not members:
            return None
        return self.rng.choice(members)

    def _spawn_catch_up(self, peer: Endpoint, trigger_ids: frozenset = frozenset()) -> None:
        task = asyncio.ensure_future(self._catch_up(peer, trigger_ids))
        self._catch_up_tasks.add(task)
        task.add_done_callback(self._catch_up_task_done)

    def _catch_up_task_done(self, task: asyncio.Task) -> None:
        self._catch_up_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            LOG.error(
                "%s config catch-up task failed: %r", self.my_addr, task.exception()
            )

    async def _catch_up(self, peer: Endpoint, trigger_ids: frozenset = frozenset()) -> None:
        """Pull ``peer``'s current configuration via the join phase-2
        handler (a JoinMessage authenticated by our endpoint + identifier,
        stamped with our current config id — or the -1 sentinel on
        java-topology clusters, see CATCH_UP_CONFIG_ID) and adopt it if it
        is ahead of ours. ``trigger_ids`` are the unknown config ids whose
        traffic triggered this pull: on a futile outcome they are remembered
        as not-ahead (any id the sender stamped lies on its chain at or
        behind the not-ahead config it just answered with), so the same
        straggler traffic cannot re-trigger pulls forever."""
        if self._catch_up_inflight or self._stopped or self.node_id is None:
            return
        self._catch_up_inflight = True
        try:
            # Stamped with OUR current configuration id (not the joiner's -1
            # sentinel): a peer inhabiting the same configuration answers
            # with a compact "unchanged" response instead of streaming the
            # full O(N) configuration — which turns the 30 s idle heartbeat
            # into a true no-op when nothing changed. A peer on any other
            # configuration takes the mismatch branch and streams, exactly
            # as before. Java-topology clusters (which may contain reference
            # JVM peers without the unchanged branch) keep the sentinel —
            # see CATCH_UP_CONFIG_ID.
            self.recorder.record(
                EventName.CATCH_UP_PULL,
                config_id=self.view.configuration_id,
                trace_id=self._trace_id,
                peer=str(peer),
                triggers=len(trigger_ids),
            )
            pull_config_id = (
                CATCH_UP_CONFIG_ID
                if self.settings.topology == "java"
                else self.view.configuration_id
            )
            request = JoinMessage(
                sender=self.my_addr,
                node_id=self.node_id,
                ring_numbers=(),
                configuration_id=pull_config_id,
                metadata=(),
            )
            try:
                response = await self.client.send(peer, request)
            except Exception as exc:  # noqa: BLE001 — any transport failure: retry later
                LOG.debug("%s config pull from %s failed: %r", self.my_addr, peer, exc)
                return
            if not isinstance(response, JoinResponse):
                return
            async with self._lock:
                if not self._stopped:
                    self._apply_catch_up_response(peer, response, trigger_ids)
        finally:
            self._catch_up_inflight = False

    def _apply_catch_up_response(
        self,
        peer: Endpoint,
        response: JoinResponse,
        trigger_ids: frozenset = frozenset(),
    ) -> None:
        if self._kicked_signalled:
            return
        if response.status_code == JoinStatusCode.CONFIG_CHANGED:
            # The peer's view does not contain us. That alone is ambiguous —
            # the peer may be stuck in a configuration predating our join —
            # so eviction is concluded ONLY from verifiable proof: the peer's
            # identifier history contains everything ours does (it is at or
            # past every configuration we inhabited; histories are
            # append-only) yet its endpoints lack us. A stale pre-join peer
            # cannot fabricate this — it has never seen our identifier — so
            # no count of ambiguous answers is needed, and no count of
            # ambiguous answers can falsely convict.
            theirs_ids = frozenset(response.identifiers)
            proven = (
                bool(response.endpoints)
                and theirs_ids >= self.view.identifiers_seen()
                and self.my_addr not in set(response.endpoints)
            )
            if proven:
                LOG.warning(
                    "%s: peer %s proved a configuration past our eviction "
                    "(identifier superset, endpoints exclude us); signalling KICKED",
                    self.my_addr, peer,
                )
                # Latch: KICKED fires once; the application owns the rejoin.
                # Also silence our consensus liveness tick — an evicted node
                # must not keep broadcasting stale votes/rounds at the living.
                self._kicked_signalled = True
                self._fast_paxos.cancel_fallback()
                self.metrics.inc("kicked")
                self.recorder.record(
                    EventName.KICKED,
                    config_id=self.view.configuration_id,
                    peer=str(peer),
                )
                self._cancel_failure_detectors()
                self._notify(
                    ClusterEvents.KICKED,
                    ClusterStatusChange(
                        configuration_id=self.view.configuration_id,
                        membership=tuple(self.view.ring(0)),
                        status_changes=(),
                    ),
                )
            else:
                # Learned nothing actionable: remember the peer's config id
                # AND the trigger ids so this straggler traffic stops
                # re-triggering evidence pulls (ids are hash-unique; a config
                # verified not-ahead of us can never become ahead).
                self._remember_config_id(response.configuration_id, inhabited=False)
                for cid in trigger_ids:
                    self._remember_config_id(cid, inhabited=False)
                self.recorder.record(
                    EventName.CATCH_UP_RESULT,
                    config_id=self.view.configuration_id,
                    trace_id=self._trace_id,
                    peer=str(peer),
                    outcome="futile_config_changed",
                )
            return
        if response.status_code != JoinStatusCode.SAFE_TO_JOIN or not response.endpoints:
            if (
                response.status_code == JoinStatusCode.SAFE_TO_JOIN
                and response.configuration_id == self.view.configuration_id
            ):
                # Compact "unchanged" answer: the peer inhabits the same
                # configuration we do. The trigger ids (if any) are thereby
                # verified not-ahead — remember them so the same straggler
                # traffic cannot re-trigger pulls, exactly as a futile full
                # stream used to.
                self.metrics.inc("config_sync_unchanged")
                for cid in trigger_ids:
                    self._remember_config_id(cid, inhabited=False)
                self.recorder.record(
                    EventName.CATCH_UP_RESULT,
                    config_id=self.view.configuration_id,
                    trace_id=self._trace_id,
                    peer=str(peer),
                    outcome="unchanged",
                )
            return
        theirs_ids = frozenset(response.identifiers)
        mine_ids = self.view.identifiers_seen()
        theirs_eps = set(response.endpoints)
        mine_eps = set(self.view.ring(0))
        # Identifier history is append-only along the decided chain
        # (view.identifiers_seen docstring), which orders configurations
        # without a version counter.
        newer = theirs_ids > mine_ids or (
            theirs_ids == mine_ids and theirs_eps < mine_eps
        )
        if not newer:
            # Futile pull: mark the peer's config and the trigger ids as
            # known-not-ahead so this straggler traffic stops re-triggering
            # evidence pulls.
            self._remember_config_id(response.configuration_id, inhabited=False)
            for cid in trigger_ids:
                self._remember_config_id(cid, inhabited=False)
            self.recorder.record(
                EventName.CATCH_UP_RESULT,
                config_id=self.view.configuration_id,
                trace_id=self._trace_id,
                peer=str(peer),
                outcome="futile_not_newer",
            )
            return
        self.metrics.inc("config_catch_ups")
        self.recorder.record(
            EventName.CATCH_UP_RESULT,
            config_id=self.view.configuration_id,
            trace_id=self._trace_id,
            peer=str(peer),
            outcome="installed",
            new_config_id=response.configuration_id,
        )
        self._install_fetched_configuration(response)

    def _install_fetched_configuration(self, response: JoinResponse) -> None:
        """Adopt a configuration pulled from a peer: the catch-up twin of
        ``_decide_view_change``'s apply path, with status changes computed as
        the membership diff."""
        old_members = set(self.view.ring(0))
        old_metadata = self.metadata_manager.get_all_metadata()
        self._cancel_failure_detectors()
        self.view = MembershipView(
            self.settings.k,
            node_ids=response.identifiers,
            endpoints=response.endpoints,
            topology=self.settings.topology,
        )
        self.metadata_manager = MetadataManager()
        if response.metadata_keys:
            self.metadata_manager.add_metadata(
                dict(zip(response.metadata_keys, response.metadata_values))
            )
        new_members = set(self.view.ring(0))
        status_changes = tuple(
            NodeStatusChange(node, EdgeStatus.UP, self.metadata_manager.get(node))
            for node in self.view.ring_zero_sorted(new_members - old_members)
        ) + tuple(
            NodeStatusChange(node, EdgeStatus.DOWN, old_metadata.get(node, ()))
            for node in sorted(old_members - new_members)
        )
        change = ClusterStatusChange(
            configuration_id=self.view.configuration_id,
            membership=tuple(self.view.ring(0)),
            status_changes=status_changes,
        )
        LOG.info(
            "%s caught up to config %d (%d nodes) via peer pull",
            self.my_addr, self.view.configuration_id, self.view.membership_size,
        )
        # Joiners pending through us that the fetched configuration admitted
        # get it streamed; the rest keep waiting (decide-path contract).
        pending_members = tuple(
            joiner for joiner in self._joiners_to_respond_to if joiner in new_members
        )
        self._commit_view_change(change, respond_to=pending_members)

    # ------------------------------------------------------------------
    # leave (MembershipService.java:545-565)
    # ------------------------------------------------------------------

    async def leave(self) -> None:
        try:
            observers = self._monitor_topology().observers_of(self.my_addr)
        except NodeNotInRingError:
            return  # already removed — nothing to announce
        leave_msg = LeaveMessage(sender=self.my_addr)
        sends = [self.client.send_best_effort(observer, leave_msg) for observer in observers]
        try:
            await asyncio.wait_for(
                asyncio.gather(*sends, return_exceptions=True),
                timeout=self.settings.leave_message_timeout_ms / 1000.0,
            )
        except asyncio.TimeoutError:
            pass

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------

    def _notify(self, event: ClusterEvents, change: ClusterStatusChange) -> None:
        # Subscriber isolation: callbacks are application code, and several
        # call sites sit mid-transition (view replaced, per-config state not
        # yet reset). A raising subscriber must not abort the transition —
        # that would strand the service half-migrated (new view, old
        # consensus/broadcaster state) with no repair path.
        for callback in self.subscriptions[event]:
            try:
                callback(change)
            except Exception:  # noqa: BLE001 — app callback, not protocol state
                LOG.exception(
                    "%s subscriber for %s raised; continuing", self.my_addr, event
                )

    def _status_changes_for(self, proposal) -> List[NodeStatusChange]:
        return [
            NodeStatusChange(
                node,
                EdgeStatus.DOWN if self.view.is_host_present(node) else EdgeStatus.UP,
                self.metadata_manager.get(node),
            )
            for node in proposal
        ]

    def _initial_view_change(self) -> ClusterStatusChange:
        return ClusterStatusChange(
            configuration_id=self.view.configuration_id,
            membership=tuple(self.view.ring(0)),
            status_changes=tuple(
                NodeStatusChange(node, EdgeStatus.UP, self.metadata_manager.get(node))
                for node in self.view.ring(0)
            ),
        )
