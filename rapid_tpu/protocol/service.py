"""The membership service: single protocol engine per endpoint.

Orchestration semantics follow ``MembershipService.java``: one serialized
protocol context handles every message (the reference serializes via a
single-thread executor, ``SharedResources.java:53``; here an asyncio lock),
owns alert batching (100 ms quiescence window), join bookkeeping, failure-
detector scheduling, and view-change application.

Message flow (MembershipService.java:174-196): every RapidRequest enters
``handle_message``; alerts feed the cut detector; a released cut becomes a
Fast Paxos proposal; the decision mutates the K-ring view, notifies
subscribers, re-arms failure detectors, and unblocks joiners.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, List, Optional, Tuple

from rapid_tpu.messaging.base import Broadcaster, MessagingClient, UnicastToAllBroadcaster
from rapid_tpu.monitoring.base import EdgeFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.events import ClusterEvents, ClusterStatusChange, NodeStatusChange
from rapid_tpu.protocol.fast_paxos import FastPaxos
from rapid_tpu.protocol.metadata import FrozenMetadata, MetadataManager
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidRequest,
    RapidResponse,
    Response,
)
from rapid_tpu.utils.clock import AsyncioClock, Clock
from rapid_tpu.utils.metrics import Metrics

LOG = logging.getLogger(__name__)

CONSENSUS_TYPES = (
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)


class MembershipService:
    def __init__(
        self,
        my_addr: Endpoint,
        cut_detector: MultiNodeCutDetector,
        view: MembershipView,
        settings: Settings,
        client: MessagingClient,
        fd_factory: EdgeFailureDetectorFactory,
        metadata_map: Optional[Dict[Endpoint, FrozenMetadata]] = None,
        subscriptions: Optional[Dict[ClusterEvents, List]] = None,
        clock: Optional[Clock] = None,
        broadcaster: Optional[Broadcaster] = None,
        rng: Optional[random.Random] = None,
        vote_tally_factory=None,
    ) -> None:
        self.my_addr = my_addr
        self.settings = settings
        self.view = view
        self.cut_detector = cut_detector
        self.client = client
        self.fd_factory = fd_factory
        self.clock = clock if clock is not None else AsyncioClock()
        self.rng = rng if rng is not None else random.Random()
        self.metadata_manager = MetadataManager()
        if metadata_map:
            self.metadata_manager.add_metadata(metadata_map)
        self.broadcaster = (
            broadcaster if broadcaster is not None else UnicastToAllBroadcaster(client, self.rng)
        )
        # vote_tally_factory(membership_size) -> tally object, re-created per
        # configuration (e.g. rapid_tpu.protocol.device_vote_tally.DeviceVoteTally).
        self._vote_tally_factory = vote_tally_factory
        self.subscriptions: Dict[ClusterEvents, List] = {event: [] for event in ClusterEvents}
        if subscriptions:
            for event, callbacks in subscriptions.items():
                self.subscriptions[event].extend(callbacks)

        self.metrics = Metrics()
        self._convergence_timing = False
        self._lock = asyncio.Lock()  # the "protocol executor"
        self._joiners_to_respond_to: Dict[Endpoint, List[asyncio.Future]] = {}
        self._joiner_uuid: Dict[Endpoint, NodeId] = {}
        self._joiner_metadata: Dict[Endpoint, FrozenMetadata] = {}
        self._announced_proposal = False
        self._send_queue: List[AlertMessage] = []
        self._last_enqueue_ms: float = -1.0
        self._background_tasks: List[asyncio.Task] = []
        self._fd_tasks: List[asyncio.Task] = []
        self._fd_generation = 0
        self._stopped = False

        self.broadcaster.set_membership(self.view.ring(0))
        self._fast_paxos = self._new_fast_paxos()

        # Inform the application that the start/join completed
        # (MembershipService.java:162-168).
        self._notify(ClusterEvents.VIEW_CHANGE, self._initial_view_change())

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        """Arm the alert batcher and failure detectors."""
        self._background_tasks.append(asyncio.ensure_future(self._alert_batcher_loop()))
        self._create_failure_detectors()

    async def shutdown(self) -> None:
        self._stopped = True
        fd_tasks = self._cancel_failure_detectors()
        for task in self._background_tasks:
            task.cancel()
        # Await detectors too: a mid-tick probe must finish (or unwind) before
        # the client underneath it is shut down.
        await asyncio.gather(*self._background_tasks, *fd_tasks, return_exceptions=True)
        self._background_tasks.clear()
        await self.client.shutdown()

    # ------------------------------------------------------------------
    # accessors (Cluster API surface)
    # ------------------------------------------------------------------

    @property
    def membership(self) -> List[Endpoint]:
        return self.view.ring(0)

    @property
    def membership_size(self) -> int:
        return self.view.membership_size

    def get_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return self.metadata_manager.get_all_metadata()

    def register_subscription(self, event: ClusterEvents, callback) -> None:
        self.subscriptions[event].append(callback)

    # ------------------------------------------------------------------
    # message entry point (MembershipService.java:174-196)
    # ------------------------------------------------------------------

    async def handle_message(self, request: RapidRequest) -> RapidResponse:
        if isinstance(request, ProbeMessage):
            # Probes bypass the protocol context (MembershipService.java:449-452).
            return ProbeResponse()
        if isinstance(request, PreJoinMessage):
            async with self._lock:
                return self._handle_pre_join(request)
        if isinstance(request, JoinMessage):
            async with self._lock:
                future = self._handle_join_phase2(request)
            if isinstance(future, asyncio.Future):
                return await future
            return future
        if isinstance(request, BatchedAlertMessage):
            async with self._lock:
                return self._handle_batched_alerts(request)
        if isinstance(request, CONSENSUS_TYPES):
            async with self._lock:
                return self._fast_paxos.handle_message(request)
        if isinstance(request, LeaveMessage):
            async with self._lock:
                self._edge_failure_notification(
                    request.sender, self.view.configuration_id
                )
            return Response()
        raise TypeError(f"unidentified request type {type(request)!r}")

    # ------------------------------------------------------------------
    # join protocol, server side
    # ------------------------------------------------------------------

    def _handle_pre_join(self, msg: PreJoinMessage) -> JoinResponse:
        """Phase 1 at the seed (MembershipService.java:203-224)."""
        status = self.view.is_safe_to_join(msg.sender, msg.node_id)
        endpoints: Tuple[Endpoint, ...] = ()
        if status in (JoinStatusCode.SAFE_TO_JOIN, JoinStatusCode.HOSTNAME_ALREADY_IN_RING):
            endpoints = tuple(self.view.expected_observers_of(msg.sender))
        LOG.info(
            "join at seed %s for %s: %s (config %d, size %d)",
            self.my_addr, msg.sender, status.name,
            self.view.configuration_id, self.view.membership_size,
        )
        return JoinResponse(
            sender=self.my_addr,
            status_code=status,
            configuration_id=self.view.configuration_id,
            endpoints=endpoints,
        )

    def _handle_join_phase2(self, msg: JoinMessage):
        """Phase 2 at an observer (MembershipService.java:232-289). Returns
        either an immediate JoinResponse or a future resolved after consensus."""
        current_config = self.view.configuration_id
        if current_config == msg.configuration_id:
            future: asyncio.Future = asyncio.get_event_loop().create_future()
            self._joiners_to_respond_to.setdefault(msg.sender, []).append(future)
            alert = AlertMessage(
                edge_src=self.my_addr,
                edge_dst=msg.sender,
                edge_status=EdgeStatus.UP,
                configuration_id=current_config,
                ring_numbers=msg.ring_numbers,
                node_id=msg.node_id,
                metadata=msg.metadata,
            )
            self._enqueue_alert(alert)
            return future

        # Configuration changed between phase 1 and 2
        # (MembershipService.java:255-286).
        config = self.view.configuration
        if self.view.is_host_present(msg.sender) and self.view.is_identifier_present(msg.node_id):
            # The cluster already admitted this joiner; stream it the config.
            metadata = self.metadata_manager.get_all_metadata()
            return JoinResponse(
                sender=self.my_addr,
                status_code=JoinStatusCode.SAFE_TO_JOIN,
                configuration_id=config.configuration_id,
                endpoints=config.endpoints,
                identifiers=config.node_ids,
                metadata_keys=tuple(metadata.keys()),
                metadata_values=tuple(metadata.values()),
            )
        return JoinResponse(
            sender=self.my_addr,
            status_code=JoinStatusCode.CONFIG_CHANGED,
            configuration_id=config.configuration_id,
        )

    # ------------------------------------------------------------------
    # alert pipeline (MembershipService.java:300-354)
    # ------------------------------------------------------------------

    def _handle_batched_alerts(self, batch: BatchedAlertMessage) -> Response:
        self.metrics.inc("alerts_received", len(batch.messages))
        config_id = self.view.configuration_id
        valid = [
            self._extract_joiner_details(msg)
            for msg in batch.messages
            if self._filter_alert(msg, config_id)
        ]
        if self._announced_proposal:
            # We already initiated consensus and cannot revise our proposal.
            return Response()

        # One batched detector pass (host hash-map or device kernel —
        # DeviceCutDetector overrides aggregate_batch with a fused kernel).
        proposal = self.cut_detector.aggregate_batch(valid, self.view)

        if proposal:
            LOG.info("%s proposing membership change of size %d", self.my_addr, len(proposal))
            self.metrics.inc("proposals_announced")
            self._announced_proposal = True
            if not self._convergence_timing:
                self._convergence_timing = True
                self.metrics.mark("view_change_convergence", self.clock.now_ms())
            self._notify(
                ClusterEvents.VIEW_CHANGE_PROPOSAL,
                ClusterStatusChange(
                    configuration_id=config_id,
                    membership=tuple(self.view.ring(0)),
                    status_changes=tuple(self._status_changes_for(proposal)),
                ),
            )
            self._fast_paxos.propose(tuple(self.view.ring_zero_sorted(proposal)))
        return Response()

    def _filter_alert(self, msg: AlertMessage, config_id: int) -> bool:
        """Config-id check + the once-in/once-out invariant
        (MembershipService.java:644-675)."""
        if msg.configuration_id != config_id:
            return False
        if msg.edge_status == EdgeStatus.UP and self.view.is_host_present(msg.edge_dst):
            return False
        if msg.edge_status == EdgeStatus.DOWN and not self.view.is_host_present(msg.edge_dst):
            return False
        return True

    def _extract_joiner_details(self, msg: AlertMessage) -> AlertMessage:
        """Save joiner UUID/metadata for the eventual ring add
        (MembershipService.java:677-685)."""
        if msg.edge_status == EdgeStatus.UP:
            if msg.node_id is not None:
                self._joiner_uuid[msg.edge_dst] = msg.node_id
            self._joiner_metadata[msg.edge_dst] = msg.metadata
        return msg

    # ------------------------------------------------------------------
    # consensus decision (MembershipService.java:385-444)
    # ------------------------------------------------------------------

    def _decide_view_change(self, proposal: Tuple[Endpoint, ...]) -> None:
        LOG.info(
            "%s decide view change in config %d (%d nodes): %s",
            self.my_addr, self.view.configuration_id, self.view.membership_size,
            [str(p) for p in proposal],
        )
        # Validate BEFORE mutating anything: alert broadcasts are best-effort
        # (single-attempt, and the UDP hybrid transport ships them as
        # droppable datagrams), so a decision can name a joiner whose UP alert
        # we never saw — leaving us without its UUID. Applying a partial view
        # would fork this node from the cluster; applying half a view and
        # raising mid-loop (the reference NPEs here,
        # MembershipService.java:401-404) would strand it with no failure
        # detectors. Apply nothing and recover instead.
        missing = [
            node
            for node in proposal
            if not self.view.is_host_present(node) and node not in self._joiner_uuid
        ]
        if missing:
            self._recover_from_unknown_joiners(missing)
            return
        self._cancel_failure_detectors()

        status_changes: List[NodeStatusChange] = []
        for node in proposal:
            if self.view.is_host_present(node):
                self.view.ring_delete(node)
                status_changes.append(
                    NodeStatusChange(node, EdgeStatus.DOWN, self.metadata_manager.get(node))
                )
                self.metadata_manager.remove_node(node)
            else:
                node_id = self._joiner_uuid.pop(node)
                self.view.ring_add(node, node_id)
                metadata = self._joiner_metadata.pop(node, ())
                if metadata:
                    self.metadata_manager.add_metadata({node: metadata})
                status_changes.append(NodeStatusChange(node, EdgeStatus.UP, metadata))

        config_id = self.view.configuration_id
        change = ClusterStatusChange(
            configuration_id=config_id,
            membership=tuple(self.view.ring(0)),
            status_changes=tuple(status_changes),
        )
        self.metrics.inc("view_changes")
        if self._convergence_timing:
            self.metrics.record_ms(
                "view_change_convergence",
                self.metrics.elapsed_since_ms("view_change_convergence", self.clock.now_ms()),
            )
            self._convergence_timing = False
        self._notify(ClusterEvents.VIEW_CHANGE, change)

        # Reset for the next configuration.
        self.cut_detector.clear()
        self._announced_proposal = False
        self._fast_paxos = self._new_fast_paxos()
        self.broadcaster.set_membership(self.view.ring(0))

        if self.view.is_host_present(self.my_addr):
            self._create_failure_detectors()
        else:
            LOG.info("%s was kicked out", self.my_addr)
            self.metrics.inc("kicked")
            self._notify(ClusterEvents.KICKED, change)

        self._respond_to_joiners(proposal)

    def _recover_from_unknown_joiners(self, missing: List[Endpoint]) -> None:
        """The cluster decided a view containing joiners we know nothing
        about; the rest of the cluster will apply it, so our configuration is
        now permanently stale. Stop participating and signal ``KICKED`` so the
        application layer performs the standard stale-node recovery: rejoin
        with a fresh identity (same path as an eviction)."""
        LOG.error(
            "%s cannot apply view change in config %d: no UUID recorded for "
            "joiner(s) %s; signalling KICKED for rejoin",
            self.my_addr,
            self.view.configuration_id,
            [str(n) for n in missing],
        )
        self.metrics.inc("decision_missing_joiner_uuid")
        self._cancel_failure_detectors()
        self._notify(
            ClusterEvents.KICKED,
            ClusterStatusChange(
                configuration_id=self.view.configuration_id,
                membership=tuple(self.view.ring(0)),
                status_changes=(),
            ),
        )

    def _new_fast_paxos(self) -> FastPaxos:
        vote_tally = (
            self._vote_tally_factory(self.view.membership_size)
            if self._vote_tally_factory is not None
            else None
        )
        return FastPaxos(
            my_addr=self.my_addr,
            configuration_id=self.view.configuration_id,
            membership_size=self.view.membership_size,
            broadcast_fn=self.broadcaster.broadcast,
            send_fn=self.client.send_nowait,
            on_decide=self._decide_view_change,
            clock=self.clock,
            consensus_fallback_base_delay_ms=self.settings.consensus_fallback_base_delay_ms,
            rng=self.rng,
            vote_tally=vote_tally,
            on_classic_round=self._on_fast_round_failed,
        )

    def _on_fast_round_failed(self) -> None:
        """The jittered fallback fired before a fast-round quorum formed:
        classic Paxos is engaging. The reference DECLARES this event but
        never fires it (ClusterEvents.java:19-23); here the declared API is
        completed — subscribers learn exactly when one-step consensus failed
        and the metrics record how often the slow path runs."""
        self.metrics.inc("classic_rounds_started")
        self._notify(
            ClusterEvents.VIEW_CHANGE_ONE_STEP_FAILED,
            ClusterStatusChange(
                configuration_id=self.view.configuration_id,
                membership=tuple(self.view.ring(0)),
                status_changes=(),
            ),
        )

    def _respond_to_joiners(self, proposal: Tuple[Endpoint, ...]) -> None:
        """Stream the new configuration to nodes joining through us
        (MembershipService.java:719-744)."""
        config = self.view.configuration
        metadata = self.metadata_manager.get_all_metadata()
        response = JoinResponse(
            sender=self.my_addr,
            status_code=JoinStatusCode.SAFE_TO_JOIN,
            configuration_id=config.configuration_id,
            endpoints=config.endpoints,
            identifiers=config.node_ids,
            metadata_keys=tuple(metadata.keys()),
            metadata_values=tuple(metadata.values()),
        )
        for node in proposal:
            for future in self._joiners_to_respond_to.pop(node, []):
                if not future.done():
                    future.set_result(response)

    # ------------------------------------------------------------------
    # failure detection (MembershipService.java:472-495, 697-714)
    # ------------------------------------------------------------------

    def _edge_failure_notification(self, subject: Endpoint, config_id: int) -> None:
        if config_id != self.view.configuration_id:
            LOG.info(
                "%s ignoring stale failure notification for %s (config %d != %d)",
                self.my_addr, subject, config_id, self.view.configuration_id,
            )
            return
        self._enqueue_alert(
            AlertMessage(
                edge_src=self.my_addr,
                edge_dst=subject,
                edge_status=EdgeStatus.DOWN,
                configuration_id=config_id,
                ring_numbers=tuple(self.view.ring_numbers(self.my_addr, subject)),
            )
        )

    def _create_failure_detectors(self) -> None:
        if self._stopped:
            return
        self._fd_generation += 1
        generation = self._fd_generation
        config_id = self.view.configuration_id
        try:
            subjects = self.view.subjects_of(self.my_addr)
        except Exception:
            return
        for subject in set(subjects):
            self._fd_tasks.append(
                asyncio.ensure_future(self._fd_loop(subject, generation, config_id))
            )

    async def _fd_loop(self, subject: Endpoint, generation: int, config_id: int) -> None:
        def notifier() -> None:
            asyncio.ensure_future(self._notify_edge_failure(subject, config_id))

        detector = self.fd_factory.create_instance(subject, notifier)
        while not self._stopped and generation == self._fd_generation:
            await detector.tick()
            await self.clock.sleep_ms(self.settings.failure_detector_interval_ms)

    async def _notify_edge_failure(self, subject: Endpoint, config_id: int) -> None:
        async with self._lock:
            self._edge_failure_notification(subject, config_id)

    def _cancel_failure_detectors(self) -> List[asyncio.Task]:
        self._fd_generation += 1
        cancelled = list(self._fd_tasks)
        for task in cancelled:
            task.cancel()
        self._fd_tasks.clear()
        return cancelled

    # ------------------------------------------------------------------
    # alert batching (MembershipService.java:572-581, 613-637)
    # ------------------------------------------------------------------

    def _enqueue_alert(self, msg: AlertMessage) -> None:
        now = self.clock.now_ms()
        self._last_enqueue_ms = now
        self._send_queue.append(msg)
        self.metrics.inc("alerts_enqueued")
        # North-star timer: first local evidence of a membership change until
        # the view change commits. A mark left by evidence that never led to
        # a proposal (e.g. one spurious FD firing, tally below L) would
        # inflate a much later convergence; expire it after the window in
        # which related alerts could plausibly still arrive.
        stale_ms = 10 * (
            self.settings.failure_detector_interval_ms + self.settings.batching_window_ms
        )
        if (
            self._convergence_timing
            and not self._announced_proposal
            # Once a proposal is announced, convergence is genuinely in
            # flight (possibly slow via the classic fallback) — never expire.
            and self.metrics.elapsed_since_ms("view_change_convergence", now) > stale_ms
        ):
            self._convergence_timing = False
        if not self._convergence_timing:
            self._convergence_timing = True
            self.metrics.mark("view_change_convergence", now)

    async def _alert_batcher_loop(self) -> None:
        window = self.settings.batching_window_ms
        while not self._stopped:
            await self.clock.sleep_ms(window)
            if (
                self._send_queue
                and self._last_enqueue_ms > 0
                and (self.clock.now_ms() - self._last_enqueue_ms) > window
            ):
                messages, self._send_queue = self._send_queue, []
                self.metrics.inc("alert_batches_sent")
                self.broadcaster.broadcast(
                    BatchedAlertMessage(sender=self.my_addr, messages=tuple(messages))
                )

    # ------------------------------------------------------------------
    # leave (MembershipService.java:545-565)
    # ------------------------------------------------------------------

    async def leave(self) -> None:
        try:
            observers = self.view.observers_of(self.my_addr)
        except Exception:
            return  # already removed — nothing to announce
        leave_msg = LeaveMessage(sender=self.my_addr)
        sends = [self.client.send_best_effort(observer, leave_msg) for observer in observers]
        try:
            await asyncio.wait_for(
                asyncio.gather(*sends, return_exceptions=True),
                timeout=self.settings.leave_message_timeout_ms / 1000.0,
            )
        except asyncio.TimeoutError:
            pass

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------

    def _notify(self, event: ClusterEvents, change: ClusterStatusChange) -> None:
        for callback in self.subscriptions[event]:
            callback(change)

    def _status_changes_for(self, proposal) -> List[NodeStatusChange]:
        return [
            NodeStatusChange(
                node,
                EdgeStatus.DOWN if self.view.is_host_present(node) else EdgeStatus.UP,
                self.metadata_manager.get(node),
            )
            for node in proposal
        ]

    def _initial_view_change(self) -> ClusterStatusChange:
        return ClusterStatusChange(
            configuration_id=self.view.configuration_id,
            membership=tuple(self.view.ring(0)),
            status_changes=tuple(
                NodeStatusChange(node, EdgeStatus.UP, self.metadata_manager.get(node))
                for node in self.view.ring(0)
            ),
        )
