from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.device_cut_detector import DeviceCutDetector
from rapid_tpu.protocol.events import ClusterEvents, ClusterStatusChange, NodeStatusChange
from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.protocol.metadata import MetadataManager
from rapid_tpu.protocol.paxos import Paxos, select_proposal_using_coordinator_rule
from rapid_tpu.protocol.view import (
    TOPOLOGY_JAVA,
    TOPOLOGY_NATIVE,
    Configuration,
    MembershipView,
    configuration_id_of,
    ring_key,
    ring_key_java,
)

__all__ = [
    "Cluster",
    "MultiNodeCutDetector",
    "DeviceCutDetector",
    "ClusterEvents",
    "ClusterStatusChange",
    "NodeStatusChange",
    "FastPaxos",
    "fast_paxos_quorum",
    "MetadataManager",
    "Paxos",
    "select_proposal_using_coordinator_rule",
    "Configuration",
    "MembershipView",
    "configuration_id_of",
    "ring_key",
    "ring_key_java",
    "TOPOLOGY_JAVA",
    "TOPOLOGY_NATIVE",
]
