"""Device-backed Fast Paxos vote tallying for host membership nodes: the vote
half of the north-star bridge (BASELINE.json — "alerts/votes become
device-array writes").

A host node coordinating a large configuration replaces ``FastPaxos``'s
per-vote hash-map counting (FastPaxos.java:53-54, whose own comment says the
sender set "should be a bitset") with device arrays: each vote is one slot
write (sender slot -> proposal hash lanes), and the quorum check is the
``rapid_tpu.ops.consensus.tally_candidates`` kernel over all N slots —
exactly the tally the virtual-cluster engine runs, now serving the real
distributed protocol.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from rapid_tpu.ops.consensus import tally_candidates
from rapid_tpu.types import Endpoint
from rapid_tpu.utils.xxhash import xxh64

LOG = logging.getLogger(__name__)

Proposal = Tuple[Endpoint, ...]


def _proposal_hash_lanes(proposal: Proposal) -> Tuple[int, int]:
    """64-bit identity of a canonical (ring-0-sorted) endpoint list, split
    into uint32 lanes (the host analog of the engine's set hashes). The full
    64-bit running hash seeds every chaining step — truncating it would
    bottleneck distinct-proposal collisions at 2^-32."""
    h = 0x243F6A8885A308D3
    for ep in proposal:
        h = xxh64(ep.hostname.encode("utf-8"), h)
        h = xxh64(ep.port.to_bytes(4, "little"), h)
    return (h >> 32) & 0xFFFFFFFF, h & 0xFFFFFFFF


class DeviceVoteTally:
    """Drop-in vote tally for FastPaxos (see its ``vote_tally`` parameter).

    ``add_vote(sender, proposal)`` records one fast-round vote and returns the
    decided proposal once the reference's rule holds: total votes >= N - F and
    votes for one identical proposal >= N - F, F = floor((N-1)/4)
    (FastPaxos.java:145-150).
    """

    def __init__(self, membership_size: int, max_slots: int = 4096, max_proposals: int = 32):
        self.n = membership_size
        self.max_slots = max(max_slots, membership_size)
        self.max_proposals = max_proposals
        self._sender_slot: Dict[Endpoint, int] = {}
        self._voted: set = set()
        self._proposal_index: Dict[Tuple[int, int], int] = {}
        self._proposals: List[Proposal] = []
        # Persistent device arrays: each vote is one scatter write, never a
        # full re-upload.
        self._vote_hi = jnp.zeros(self.max_slots, dtype=jnp.uint32)
        self._vote_lo = jnp.zeros(self.max_slots, dtype=jnp.uint32)
        self._vote_valid = jnp.zeros(self.max_slots, dtype=bool)
        self._cand_hi = jnp.zeros(max_proposals, dtype=jnp.uint32)
        self._cand_lo = jnp.zeros(max_proposals, dtype=jnp.uint32)
        self._cand_valid = jnp.zeros(max_proposals, dtype=bool)

    def add_vote(self, sender: Endpoint, proposal: Proposal) -> Optional[Proposal]:
        from rapid_tpu.protocol.fast_paxos import fast_paxos_quorum

        if sender in self._voted:
            return None  # duplicate sender (FastPaxos.java:134-136)
        slot = self._sender_slot.get(sender)
        if slot is None:
            slot = len(self._sender_slot)
            if slot >= self.max_slots:
                LOG.warning(
                    "DeviceVoteTally slot capacity %d exhausted; dropping vote", self.max_slots
                )
                return None
            self._sender_slot[sender] = slot

        lanes = _proposal_hash_lanes(proposal)
        cand = self._proposal_index.get(lanes)
        if cand is None:
            cand = len(self._proposals)
            if cand >= self.max_proposals:
                LOG.warning(
                    "DeviceVoteTally proposal capacity %d exhausted; dropping vote",
                    self.max_proposals,
                )
                return None
            self._proposal_index[lanes] = cand
            self._proposals.append(tuple(proposal))
            self._cand_hi = self._cand_hi.at[cand].set(lanes[0])
            self._cand_lo = self._cand_lo.at[cand].set(lanes[1])
            self._cand_valid = self._cand_valid.at[cand].set(True)

        # The device-array write: one slot per sender.
        self._voted.add(sender)
        self._vote_hi = self._vote_hi.at[slot].set(lanes[0])
        self._vote_lo = self._vote_lo.at[slot].set(lanes[1])
        self._vote_valid = self._vote_valid.at[slot].set(True)

        # No decision is possible before quorum-many votes exist; skip the
        # tally kernel (and its device->host readback) until then.
        if len(self._voted) < fast_paxos_quorum(self.n):
            return None

        result = tally_candidates(
            self._vote_hi,
            self._vote_lo,
            self._vote_valid,
            self._cand_hi,
            self._cand_lo,
            self._cand_valid,
            jnp.int32(self.n),
        )
        if not bool(result.decided):
            return None
        winner = (int(result.winner_hi), int(result.winner_lo))
        return self._proposals[self._proposal_index[winner]]
