"""Classic single-decree Paxos with Fast Paxos's coordinator value-pick rule.

Semantics follow ``Paxos.java``: ranks are (round, node_index) tuples; round 1
is reserved for the single fast round; any node may start a classic round >= 2
as coordinator; the coordinator picks values per Figure 2 of the Fast Paxos
paper (``Paxos.java:271-328``). This is the rare recovery path, so it stays
host-side Python; the fast-round tally is what runs on TPU.

Transport-agnostic: the engine injects ``broadcast_fn(request)`` and
``send_fn(destination, request)`` (both fire-and-forget), matching the
reference's IBroadcaster / IMessagingClient seam.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from rapid_tpu.types import (
    Endpoint,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    Rank,
    RapidRequest,
)
from rapid_tpu.utils.flight_recorder import EventName, FlightRecorder
from rapid_tpu.utils.xxhash import xxh64

BroadcastFn = Callable[[RapidRequest], None]
SendFn = Callable[[Endpoint, RapidRequest], None]
OnDecideFn = Callable[[Tuple[Endpoint, ...]], None]


def node_index_of(endpoint: Endpoint) -> int:
    """Stable per-node rank index for classic rounds (the reference uses
    Java's Object.hashCode, Paxos.java:102)."""
    return xxh64(str(endpoint).encode("utf-8"), 0xC0FFEE) & 0x7FFFFFFF


class Paxos:
    def __init__(
        self,
        my_addr: Endpoint,
        configuration_id: int,
        membership_size: int,
        broadcast_fn: BroadcastFn,
        send_fn: SendFn,
        on_decide: OnDecideFn,
        recorder: Optional[FlightRecorder] = None,
        trace_supplier: Optional[Callable[[], Optional[int]]] = None,
    ) -> None:
        self.my_addr = my_addr
        self.configuration_id = configuration_id
        self.n = membership_size
        self._broadcast = broadcast_fn
        self._send = send_fn
        self._on_decide = on_decide
        # Observability: the owning FastPaxos threads the service's flight
        # recorder and trace-context supplier through, so every classic
        # message this engine emits carries the view change's trace id.
        self._recorder = recorder
        self._trace = trace_supplier if trace_supplier is not None else (lambda: None)

        self.rnd = Rank(0, 0)
        self.vrnd = Rank(0, 0)
        self.vval: Tuple[Endpoint, ...] = ()
        self.crnd = Rank(0, 0)
        self.cval: Tuple[Endpoint, ...] = ()
        self._phase1b_messages: Dict[Endpoint, Phase1bMessage] = {}
        self._accept_responses: Dict[Rank, Dict[Endpoint, Phase2bMessage]] = {}
        self.decided = False

    # -- coordinator ------------------------------------------------------

    def start_phase1a(self, round_number: int) -> None:
        """Become coordinator for ``round_number`` (Paxos.java:98-111).

        Re-entrant: the fallback re-arms with escalating rounds until a
        decision lands (lossy transports can eat an entire round's messages).
        Advancing to a higher rank discards the previous round's coordinator
        state — promises collected at an older crnd must never satisfy the
        new round's majority, and ``cval`` must be re-picked from the new
        round's phase1b quorum (the reference never re-enters,
        FastPaxos.java:189-195, because its transport never drops)."""
        if self.crnd.round > round_number:
            return
        rank = Rank(round_number, node_index_of(self.my_addr))
        if rank.as_tuple() > self.crnd.as_tuple():
            self.crnd = rank
            self._phase1b_messages = {}
            self.cval = ()
        self._broadcast(
            Phase1aMessage(
                sender=self.my_addr,
                configuration_id=self.configuration_id,
                rank=self.crnd,
                trace_id=self._trace(),
            )
        )

    def handle_phase1a(self, msg: Phase1aMessage) -> None:
        """Acceptor: promise to the highest rank seen (Paxos.java:118-148)."""
        if msg.configuration_id != self.configuration_id:
            return
        if self.rnd.as_tuple() < msg.rank.as_tuple():
            self.rnd = msg.rank
        else:
            return
        self._send(
            msg.sender,
            Phase1bMessage(
                sender=self.my_addr,
                configuration_id=self.configuration_id,
                rnd=self.rnd,
                vrnd=self.vrnd,
                vval=self.vval,
                trace_id=msg.trace_id if msg.trace_id is not None else self._trace(),
            ),
        )

    def handle_phase1b(self, msg: Phase1bMessage) -> None:
        """Coordinator: on a majority of promises, pick a value and send
        phase2a (Paxos.java:156-188)."""
        if msg.configuration_id != self.configuration_id:
            return
        if msg.rnd != self.crnd:
            return
        # Keyed by sender: redelivered promises must not inflate the majority
        # count (the reference appends to a list, Paxos.java:168, which is
        # unsafe under at-least-once transports).
        self._phase1b_messages[msg.sender] = msg
        if len(self._phase1b_messages) > self.n // 2:
            chosen = select_proposal_using_coordinator_rule(
                list(self._phase1b_messages.values()), self.n
            )
            if msg.rnd == self.crnd and not self.cval and chosen:
                self.cval = chosen
                if self._recorder is not None:
                    self._recorder.record(
                        EventName.CLASSIC_PHASE2A_TX,
                        config_id=self.configuration_id,
                        trace_id=self._trace(),
                        round=self.crnd.round,
                        proposal=[str(node) for node in chosen],
                    )
                self._broadcast(
                    Phase2aMessage(
                        sender=self.my_addr,
                        configuration_id=self.configuration_id,
                        rnd=self.crnd,
                        vval=chosen,
                        trace_id=self._trace(),
                    )
                )

    # -- acceptor ---------------------------------------------------------

    def handle_phase2a(self, msg: Phase2aMessage) -> None:
        """Acceptor: accept and echo phase2b (Paxos.java:195-216)."""
        if msg.configuration_id != self.configuration_id:
            return
        if self.rnd.as_tuple() <= msg.rnd.as_tuple() and self.vrnd != msg.rnd:
            self.rnd = msg.rnd
            self.vrnd = msg.rnd
            self.vval = msg.vval
            self._broadcast(
                Phase2bMessage(
                    sender=self.my_addr,
                    configuration_id=self.configuration_id,
                    rnd=msg.rnd,
                    endpoints=msg.vval,
                    trace_id=msg.trace_id if msg.trace_id is not None else self._trace(),
                )
            )

    # Classic rounds escalate while undecided (fast_paxos liveness tick), so
    # a long-lived partition would otherwise accumulate one accept-tally per
    # rank forever. Only the highest few ranks can still plausibly complete a
    # majority; pruning below them affects memory, never safety (a pruned
    # rank merely loses the ability to decide at that stale rank).
    _MAX_TRACKED_ACCEPT_RANKS = 8

    def handle_phase2b(self, msg: Phase2bMessage) -> None:
        """Learner: decide on a majority of identical-rank accepts
        (Paxos.java:223-238)."""
        if msg.configuration_id != self.configuration_id:
            return
        in_rnd = self._accept_responses.setdefault(msg.rnd, {})
        in_rnd[msg.sender] = msg
        if len(self._accept_responses) > self._MAX_TRACKED_ACCEPT_RANKS:
            oldest = min(self._accept_responses, key=lambda r: r.as_tuple())
            del self._accept_responses[oldest]
        if len(in_rnd) > self.n // 2 and not self.decided:
            self.decided = True
            self._on_decide(msg.endpoints)

    # -- fast-round bridge ------------------------------------------------

    def register_fast_round_vote(self, vote: Tuple[Endpoint, ...]) -> None:
        """Record our own implicit accept in the (only) fast round, round 1
        (Paxos.java:246-260)."""
        if self.rnd.round > 1:
            return
        self.rnd = Rank(1, 1)
        self.vrnd = self.rnd
        self.vval = tuple(vote)


def select_proposal_using_coordinator_rule(
    phase1b_messages: List[Phase1bMessage], n: int
) -> Tuple[Endpoint, ...]:
    """Figure 2 of the Fast Paxos paper (Paxos.java:271-328):

    - among the quorum's highest-vrnd non-empty vvals, a unique value wins;
    - else any value with more than N/4 occurrences wins;
    - else any non-empty vval may be proposed (empty if none voted).
    """
    if not phase1b_messages:
        raise ValueError("phase1b_messages must not be empty")
    max_vrnd = max(m.vrnd.as_tuple() for m in phase1b_messages)
    collected = [
        tuple(m.vval)
        for m in phase1b_messages
        if m.vrnd.as_tuple() == max_vrnd and len(m.vval) > 0
    ]
    unique = set(collected)
    if len(unique) == 1:
        return collected[0]
    if len(collected) > 1:
        counters: Dict[Tuple[Endpoint, ...], int] = {}
        for value in collected:
            count = counters.get(value, 0)
            if count + 1 > n // 4:
                return value
            counters[value] = count + 1
    for m in phase1b_messages:
        if len(m.vval) > 0:
            return tuple(m.vval)
    return ()
