"""K-ring expander membership view.

Semantics follow the reference's ``MembershipView``
(``rapid/src/main/java/com/vrg/rapid/MembershipView.java``): K pseudo-random
permutations of the member list, each ordered by a seeded 64-bit hash of the
endpoint; a node's *observers* are its K successors, its *subjects* its K
predecessors (``MembershipView.java:234-322``); a 64-bit configuration id is
folded from the identifiers and ring-0 member order
(``MembershipView.java:544-556``).

Representation is TPU-minded rather than object-per-ring: each ring is a flat
sorted array of ``(key, endpoint)`` maintained by bisection. ``ring_keys()``
exposes the raw per-ring hash keys so the device kernels in
``rapid_tpu.ops.rings`` can operate on exactly the same ordering.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rapid_tpu.errors import (
    NodeAlreadyInRingError,
    NodeNotInRingError,
    UUIDAlreadySeenError,
)
from rapid_tpu.types import Endpoint, JoinStatusCode, NodeId
from rapid_tpu.utils.xxhash import to_signed64, xxh64, xxh64_int, xxh64_int4

_MASK64 = (1 << 64) - 1

#: Topology modes. NATIVE is the tpu-first default: ports hashed as 8 bytes,
#: keys and identifiers ordered unsigned — one uniform u64 keyspace shared
#: with the device kernels (rapid_tpu.ops.rings ships u32 hi/lo words).
#: JAVA_COMPAT reproduces the reference's exact semantics — ports hashed as
#: 4-byte Java ints (``LongHashFunction.hashInt``), ring keys compared as
#: SIGNED longs (``Long.compare``, MembershipView.java:573-577), identifiers
#: ordered by the signed (high, low) NodeIdComparator
#: (MembershipView.java:474-499) — so a compat-mode cluster computes the same
#: ring orders, observer/subject sets, and configuration ids a Java cluster
#: would, making mixed clusters over the interop transport possible in
#: principle.
TOPOLOGY_NATIVE = "native"
TOPOLOGY_JAVA = "java"
TOPOLOGIES = (TOPOLOGY_NATIVE, TOPOLOGY_JAVA)


def ring_key(endpoint: Endpoint, seed: int) -> int:
    """The seeded ordering key for one ring, native mode (semantics of
    ``MembershipView.AddressComparator``, MembershipView.java:562-587, with
    the port hashed as 8 bytes and the key compared unsigned)."""
    h = xxh64(endpoint.hostname.encode("utf-8"), seed)
    return (h * 31 + xxh64_int(endpoint.port, seed)) & _MASK64


def ring_key_java(endpoint: Endpoint, seed: int) -> int:
    """Reference-exact ring key, returned SIGNED so Python's natural int
    ordering reproduces Java's ``Long.compare``: ``xx(seed).hashBytes(
    hostname_utf8) * 31 + xx(seed).hashInt(port)`` in wrapping 64-bit
    arithmetic (MembershipView.java:579-587)."""
    h = xxh64(endpoint.hostname.encode("utf-8"), seed)
    return to_signed64((h * 31 + xxh64_int4(endpoint.port, seed)) & _MASK64)


def _ring_key_for(topology: str):
    return ring_key_java if topology == TOPOLOGY_JAVA else ring_key


def node_id_sort_key(node_id: NodeId, topology: str = TOPOLOGY_NATIVE):
    """Identifier ordering for the configuration fold: unsigned (high, low)
    natively; Java's signed NodeIdComparator (MembershipView.java:474-499)
    in compat mode."""
    if topology == TOPOLOGY_JAVA:
        return (to_signed64(node_id.high), to_signed64(node_id.low))
    return (node_id.high, node_id.low)


def configuration_id_of(
    node_ids: Sequence[NodeId],
    endpoints: Sequence[Endpoint],
    topology: str = TOPOLOGY_NATIVE,
) -> int:
    """Deterministic 64-bit fold over identifiers-seen and membership
    (semantics of ``MembershipView.Configuration.getConfigurationId``,
    MembershipView.java:544-556). ``node_ids`` must be in the topology's
    sorted order and ``endpoints`` in the topology's ring-0 order for all
    members to agree. In JAVA mode the fold is reference-exact (seed-0 xxHash,
    ports as 4-byte ints), so a compat cluster computes the ids a Java
    cluster would.

    Returned as *signed* 64-bit (Java-long convention, and the wire codec's
    i64): every host-path config-id comparison uses this signed canonical
    form. (The device engine's config identity is a separate unsigned
    set-hash space, never compared against this fold.)"""
    if topology != TOPOLOGY_JAVA:
        from rapid_tpu.utils._native import native_configuration_id

        native = native_configuration_id(
            [nid.high for nid in node_ids],
            [nid.low for nid in node_ids],
            [ep.hostname.encode("utf-8") for ep in endpoints],
            [ep.port for ep in endpoints],
        )
        if native is not None:
            return to_signed64(native)
    hash_port = xxh64_int4 if topology == TOPOLOGY_JAVA else xxh64_int
    h = 1
    for nid in node_ids:
        h = (h * 37 + xxh64_int(nid.high)) & _MASK64
        h = (h * 37 + xxh64_int(nid.low)) & _MASK64
    for ep in endpoints:
        h = (h * 37 + xxh64(ep.hostname.encode("utf-8"))) & _MASK64
        h = (h * 37 + hash_port(ep.port)) & _MASK64
    return to_signed64(h)


class Configuration:
    """The serializable membership snapshot: (identifiers-seen, ring-0 member
    list). Sufficient to reconstruct an identical view — this is also the
    checkpoint format (MembershipView.java:521-533)."""

    __slots__ = ("node_ids", "endpoints", "topology", "_config_id")

    def __init__(
        self,
        node_ids: Sequence[NodeId],
        endpoints: Sequence[Endpoint],
        topology: str = TOPOLOGY_NATIVE,
    ):
        self.node_ids: Tuple[NodeId, ...] = tuple(node_ids)
        self.endpoints: Tuple[Endpoint, ...] = tuple(endpoints)
        self.topology = topology
        self._config_id: Optional[int] = None

    @property
    def configuration_id(self) -> int:
        if self._config_id is None:
            self._config_id = configuration_id_of(
                self.node_ids, self.endpoints, self.topology
            )
        return self._config_id


class MembershipView:
    """K sorted rings + identifier history. Single-owner (the protocol engine
    serializes all access, like the reference's single protocol executor)."""

    def __init__(
        self,
        k: int,
        node_ids: Sequence[NodeId] = (),
        endpoints: Sequence[Endpoint] = (),
        topology: str = TOPOLOGY_NATIVE,
    ) -> None:
        if k <= 0:
            raise ValueError("K must be > 0")
        if topology not in TOPOLOGIES:
            raise ValueError(f"topology must be one of {TOPOLOGIES}, got {topology!r}")
        self.k = k
        self.topology = topology
        self._ring_key = _ring_key_for(topology)
        # Per ring: parallel sorted lists of keys and endpoints.
        self._ring_keys: List[List[int]] = [[] for _ in range(k)]
        self._rings: List[List[Endpoint]] = [[] for _ in range(k)]
        self._key_cache: Dict[Endpoint, Tuple[int, ...]] = {}
        self._all_nodes: Set[Endpoint] = set()
        self._identifiers_seen: Set[NodeId] = set()
        self._config_dirty = True
        self._cached_configuration: Optional[Configuration] = None

        if endpoints:
            self._bulk_insert(list(endpoints))
        self._identifiers_seen.update(node_ids)

    # -- internal ---------------------------------------------------------

    def _bulk_insert(self, endpoints: List[Endpoint]) -> None:
        """Construct all K rings in one pass: batch-hash every key (native
        xxh64 when the C library is built — bit-identical to the Python
        path — else the per-endpoint fallback) and sort each ring once.
        O(K·N log N) against the incremental path's O(K·N²) list churn,
        with ~100× less hashing overhead via the native batch. Matters
        wherever a whole view is (re)built: join responses, checkpoint
        resume, and config catch-up installs, which run inside the protocol
        lock. Tie-break matches ``_insert`` exactly: equal keys order by
        endpoint."""
        if self._all_nodes:
            # Bulk construction is a from-empty operation: overwriting rings
            # on a populated view would strand existing members in
            # _all_nodes but absent from every ring.
            raise ValueError("_bulk_insert requires an empty view")
        keys_kn = None
        if self.topology == TOPOLOGY_NATIVE:
            from rapid_tpu.utils._native import native_ring_keys_batch

            keys_kn = native_ring_keys_batch(
                [ep.hostname.encode("utf-8") for ep in endpoints],
                [ep.port for ep in endpoints],
                self.k,
            )
        if keys_kn is not None:
            # One vectorized conversion, not K·N numpy scalar extractions.
            key_rows = keys_kn.T.tolist()  # [n][k] python ints
            for ep, row in zip(endpoints, key_rows):
                self._key_cache[ep] = tuple(row)
        else:
            for ep in endpoints:
                self._key_cache[ep] = tuple(
                    self._ring_key(ep, seed) for seed in range(self.k)
                )
        for ring_idx in range(self.k):
            order = sorted(
                endpoints, key=lambda e: (self._key_cache[e][ring_idx], e)
            )
            self._rings[ring_idx] = order
            self._ring_keys[ring_idx] = [self._key_cache[e][ring_idx] for e in order]
        self._all_nodes.update(endpoints)

    def _keys_of(self, endpoint: Endpoint) -> Tuple[int, ...]:
        keys = self._key_cache.get(endpoint)
        if keys is None:
            keys = tuple(self._ring_key(endpoint, seed) for seed in range(self.k))
            self._key_cache[endpoint] = keys
        return keys

    def _insert(self, endpoint: Endpoint) -> None:
        keys = self._keys_of(endpoint)
        for ring_idx in range(self.k):
            pos = bisect.bisect_left(self._ring_keys[ring_idx], keys[ring_idx])
            # Break 64-bit key ties deterministically by endpoint ordering.
            while (
                pos < len(self._ring_keys[ring_idx])
                and self._ring_keys[ring_idx][pos] == keys[ring_idx]
                and self._rings[ring_idx][pos] < endpoint
            ):
                pos += 1
            self._ring_keys[ring_idx].insert(pos, keys[ring_idx])
            self._rings[ring_idx].insert(pos, endpoint)
        self._all_nodes.add(endpoint)

    def _position(self, ring_idx: int, endpoint: Endpoint) -> int:
        key = self._keys_of(endpoint)[ring_idx]
        pos = bisect.bisect_left(self._ring_keys[ring_idx], key)
        while pos < len(self._rings[ring_idx]) and self._rings[ring_idx][pos] != endpoint:
            pos += 1
        if pos >= len(self._rings[ring_idx]):
            raise NodeNotInRingError(str(endpoint))
        return pos

    # -- queries ----------------------------------------------------------

    def is_safe_to_join(self, node: Endpoint, node_id: NodeId) -> JoinStatusCode:
        """MembershipView.java:100-115."""
        if node in self._all_nodes:
            return JoinStatusCode.HOSTNAME_ALREADY_IN_RING
        if node_id in self._identifiers_seen:
            return JoinStatusCode.UUID_ALREADY_IN_RING
        return JoinStatusCode.SAFE_TO_JOIN

    def is_host_present(self, node: Endpoint) -> bool:
        return node in self._all_nodes

    def is_identifier_present(self, node_id: NodeId) -> bool:
        return node_id in self._identifiers_seen

    def identifiers_seen(self) -> frozenset:
        """The append-only identifier history. ``ring_delete`` never removes
        identifiers (MembershipView.java:167-201 semantics), so along the
        decided configuration chain this set only grows — which makes two
        configurations comparable without a version counter: the newer one
        has a strict superset of identifiers, or an equal identifier set and
        a strict subset of endpoints (equal-identifier chains are
        remove-only). The config catch-up path relies on this ordering."""
        return frozenset(self._identifiers_seen)

    @property
    def membership_size(self) -> int:
        return len(self._all_nodes)

    def ring(self, ring_idx: int) -> List[Endpoint]:
        return list(self._rings[ring_idx])

    def ring_keys(self, ring_idx: int) -> List[int]:
        """Raw sorted hash keys of one ring. In ``TOPOLOGY_NATIVE`` these are
        u64 values interchangeable with the device kernels
        (``ops.rings.endpoint_ring_keys`` computes the identical function).
        In ``TOPOLOGY_JAVA`` they are SIGNED 64-bit values in signed ring
        order — reference-compatible, but NOT device interchange: the engine
        path is native-topology only (``endpoint_ring_keys`` enforces it)."""
        return list(self._ring_keys[ring_idx])

    def observers_of(self, node: Endpoint) -> List[Endpoint]:
        """K ring-successors (MembershipView.java:234-257)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        if len(self._all_nodes) <= 1:
            return []
        out = []
        for ring_idx in range(self.k):
            pos = self._position(ring_idx, node)
            out.append(self._rings[ring_idx][(pos + 1) % len(self._rings[ring_idx])])
        return out

    def subjects_of(self, node: Endpoint) -> List[Endpoint]:
        """K ring-predecessors (MembershipView.java:267-282)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        if len(self._all_nodes) <= 1:
            return []
        return self._predecessors_of(node)

    def expected_observers_of(self, node: Endpoint) -> List[Endpoint]:
        """Gatekeepers of a joiner not yet in the ring: the nodes that would
        precede it on each ring (MembershipView.java:292-303)."""
        if not self._all_nodes:
            return []
        return self._predecessors_of(node)

    def _predecessors_of(self, node: Endpoint) -> List[Endpoint]:
        out = []
        keys = self._keys_of(node)
        for ring_idx in range(self.k):
            ring = self._rings[ring_idx]
            if node in self._all_nodes:
                pos = self._position(ring_idx, node)
            else:
                pos = bisect.bisect_left(self._ring_keys[ring_idx], keys[ring_idx])
            out.append(ring[(pos - 1) % len(ring)])
        return out

    def ring_numbers(self, observer: Endpoint, subject: Endpoint) -> List[int]:
        """All k such that ``observer`` monitors ``subject`` on ring k
        (MembershipView.java:397-418)."""
        subjects = self.subjects_of(observer)
        return [idx for idx, node in enumerate(subjects) if node == subject]

    # -- mutation ---------------------------------------------------------

    def ring_add(self, node: Endpoint, node_id: NodeId) -> None:
        """MembershipView.java:123-160."""
        if node_id in self._identifiers_seen:
            raise UUIDAlreadySeenError(f"{node} with identifier {node_id}")
        if node in self._all_nodes:
            raise NodeAlreadyInRingError(str(node))
        self._insert(node)
        self._identifiers_seen.add(node_id)
        self._config_dirty = True

    def ring_delete(self, node: Endpoint) -> None:
        """MembershipView.java:167-201."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        for ring_idx in range(self.k):
            pos = self._position(ring_idx, node)
            del self._ring_keys[ring_idx][pos]
            del self._rings[ring_idx][pos]
        self._all_nodes.remove(node)
        self._key_cache.pop(node, None)
        self._config_dirty = True

    # -- configuration ----------------------------------------------------

    @property
    def configuration(self) -> Configuration:
        if self._config_dirty or self._cached_configuration is None:
            self._cached_configuration = Configuration(
                sorted(
                    self._identifiers_seen,
                    key=lambda nid: node_id_sort_key(nid, self.topology),
                ),
                self._rings[0],
                topology=self.topology,
            )
            self._config_dirty = False
        return self._cached_configuration

    @property
    def configuration_id(self) -> int:
        return self.configuration.configuration_id

    def ring_zero_sorted(self, endpoints) -> List[Endpoint]:
        """Canonical proposal order: ring-0 comparator
        (MembershipService.java:346-348)."""
        return sorted(endpoints, key=lambda ep: (self._ring_key(ep, 0), ep))
