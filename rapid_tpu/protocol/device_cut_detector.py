"""Device-backed cut detection for host membership nodes: the north-star
bridge (BASELINE.json) — the unchanged membership service front-end, with the
multi-node cut detector's tallies executing as batched device kernels.

A host node coordinating many members replaces its per-alert hash-map
detector with this class: each BatchedAlertMessage becomes one
``process_alert_batch`` kernel invocation over padded slot arrays
(``rapid_tpu.ops.cut_detection``), with endpoint<->slot mapping and the
invalidation-observer table maintained incrementally host-side. Semantics
are equivalence-tested against ``MultiNodeCutDetector``.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, TYPE_CHECKING

import numpy as np

from rapid_tpu.ops.cut_detection import CutState, alerts_to_report_matrix, process_alert_batch
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint

if TYPE_CHECKING:
    from rapid_tpu.protocol.view import MembershipView

LOG = logging.getLogger(__name__)

_K_MIN = 3


class DeviceCutDetector:
    """Drop-in for MultiNodeCutDetector (same constructor contract and
    aggregate_batch SPI), tallying on the attached accelerator."""

    def __init__(self, k: int, h: int, l: int, max_slots: int = 4096) -> None:
        if h > k or l > h or k < _K_MIN or l <= 0 or h <= 0:
            raise ValueError(f"arguments must satisfy K >= H >= L >= 1, K >= 3: K={k} H={h} L={l}")
        self.k = k
        self.h = h
        self.l = l
        self.max_slots = max_slots
        self._proposal_count = 0
        self._reset_state()

    def _reset_state(self) -> None:
        self._slot_of: Dict[Endpoint, int] = {}
        self._endpoint_of: List[Optional[Endpoint]] = [None] * self.max_slots
        self._state = CutState.create(self.max_slots, self.k)
        # Invalidation-observer table, filled lazily per touched subject.
        self._inval_obs = np.full((self.k, self.max_slots), -1, dtype=np.int32)
        self._subject_mask = np.zeros(self.max_slots, dtype=bool)
        self._observers_filled: set = set()
        self._overflow_warned = False

    @property
    def num_proposals(self) -> int:
        return self._proposal_count

    def has_pending_reports(self) -> bool:
        """True while any subject occupies a report slot this configuration —
        same suspicion signal as MultiNodeCutDetector.has_pending_reports."""
        return bool(self._slot_of)

    def _slot(self, endpoint: Endpoint) -> Optional[int]:
        """Slot for an endpoint, or None when capacity is exhausted. Alerts
        for unslottable endpoints are dropped — always protocol-safe (alert
        delivery is best-effort) and strictly better than wedging the node's
        alert handler for the rest of the configuration."""
        slot = self._slot_of.get(endpoint)
        if slot is None:
            slot = len(self._slot_of)
            if slot >= self.max_slots:
                if not self._overflow_warned:
                    self._overflow_warned = True
                    LOG.warning(
                        "DeviceCutDetector slot capacity %d exhausted; dropping "
                        "alerts for new endpoints until the next view change",
                        self.max_slots,
                    )
                return None
            self._slot_of[endpoint] = slot
            self._endpoint_of[slot] = endpoint
            self._subject_mask[slot] = True
        return slot

    def _fill_observers(self, subject: Endpoint, view: "MembershipView") -> None:
        """Populate the invalidation-observer column for a touched subject:
        ring observers for members, expected observers for joiners
        (MultiNodeCutDetector.java:147-149). Once per subject per
        configuration."""
        if subject in self._observers_filled:
            return
        slot = self._slot(subject)
        if slot is None:
            return
        self._observers_filled.add(subject)
        observers = (
            view.observers_of(subject)
            if view.is_host_present(subject)
            else view.expected_observers_of(subject)
        )
        for ring_number, observer in enumerate(observers[: self.k]):
            observer_slot = self._slot(observer)
            if observer_slot is not None:
                self._inval_obs[ring_number, slot] = observer_slot

    def aggregate_batch(self, msgs, view: "MembershipView") -> Set[Endpoint]:
        """One kernel pass for the whole alert batch."""
        dst_idx: List[int] = []
        rings: List[int] = []
        has_down = False
        for msg in msgs:
            slot = self._slot(msg.edge_dst)
            if slot is None:
                continue  # capacity exhausted: drop (best-effort delivery)
            self._fill_observers(msg.edge_dst, view)
            for ring_number in msg.ring_numbers:
                dst_idx.append(slot)
                rings.append(ring_number)
            has_down = has_down or msg.edge_status == EdgeStatus.DOWN
        if not dst_idx and not bool(self._state.seen_down):
            return set()

        new_reports = alerts_to_report_matrix(
            self.max_slots,
            self.k,
            np.asarray(dst_idx, dtype=np.int32),
            np.asarray(rings, dtype=np.int32),
        )
        result = process_alert_batch(
            self._state,
            new_reports,
            np.asarray(has_down),
            self._inval_obs,
            self._subject_mask,
            self.h,
            self.l,
        )
        self._state = result.state
        if not bool(result.propose):
            return set()
        self._proposal_count += 1
        mask = np.asarray(result.proposal_mask)
        return {self._endpoint_of[i] for i in np.nonzero(mask)[0]}

    # -- single-alert API parity (tests, tooling) -----------------------

    def aggregate(self, msg: AlertMessage) -> List[Endpoint]:
        return sorted(self.aggregate_batch([msg], _EmptyView()), key=str)

    def invalidate_failing_edges(self, view: "MembershipView") -> List[Endpoint]:
        return sorted(self.aggregate_batch([], view), key=str)

    def clear(self) -> None:
        self._proposal_count = 0
        self._reset_state()


class _EmptyView:
    """View stand-in for single-alert aggregation without invalidation."""

    def is_host_present(self, node) -> bool:
        return False

    def observers_of(self, node):
        return []

    def expected_observers_of(self, node):
        return []
