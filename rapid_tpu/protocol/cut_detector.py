"""Multi-node cut detection: the H/L watermark filter.

Semantics follow ``MultiNodeCutDetector.java``: a subject enters the
*pre-proposal* once L distinct rings report it and graduates to the *proposal*
at H reports; the accumulated proposal is released only when no subject sits
between the watermarks (``MultiNodeCutDetector.java:84-128``). Implicit edge
invalidation co-reports edges whose observers are themselves failing
(``MultiNodeCutDetector.java:137-164``).

This class is the sequential oracle and the per-node engine for the host
protocol path; ``rapid_tpu.ops.cut_detection`` is the batched device kernel
with the same per-batch semantics.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, TYPE_CHECKING

from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint
from rapid_tpu.utils.flight_recorder import EventName, FlightRecorder

if TYPE_CHECKING:
    from rapid_tpu.protocol.view import MembershipView

_K_MIN = 3


class MultiNodeCutDetector:
    def __init__(self, k: int, h: int, l: int) -> None:
        if h > k or l > h or k < _K_MIN or l <= 0 or h <= 0:
            raise ValueError(f"arguments must satisfy K >= H >= L >= 1, K >= 3: K={k} H={h} L={l}")
        self.k = k
        self.h = h
        self.l = l
        self._proposal_count = 0
        self._updates_in_progress = 0
        self._reports_per_host: Dict[Endpoint, Dict[int, Endpoint]] = {}
        self._proposal: Set[Endpoint] = set()
        self._pre_proposal: Set[Endpoint] = set()
        self._seen_down_events = False
        # Observability seam (bind_recorder): the owning service threads its
        # flight recorder + trace-context supplier in so watermark crossings
        # land in the same correlated event stream as the alert/consensus
        # events around them. None (standalone detector) = no recording.
        self._recorder: Optional[FlightRecorder] = None
        self._trace: Callable[[], Optional[int]] = lambda: None

    def bind_recorder(
        self, recorder: FlightRecorder, trace_supplier: Callable[[], Optional[int]]
    ) -> None:
        self._recorder = recorder
        self._trace = trace_supplier

    def _record(self, name: EventName, **fields) -> None:
        if self._recorder is not None:
            self._recorder.record(name, trace_id=self._trace(), **fields)

    @property
    def num_proposals(self) -> int:
        return self._proposal_count

    def has_pending_reports(self) -> bool:
        """True while any edge report is held for the current configuration —
        the service's alert-redelivery and config-sync loops use this as the
        'a cut may be stuck below H somewhere' suspicion signal."""
        return bool(self._reports_per_host)

    def aggregate(self, msg: AlertMessage) -> List[Endpoint]:
        """Apply one alert (all its ring numbers); returns the released
        proposal if this alert completed one, else [] (MultiNodeCutDetector.java:76-82)."""
        out: List[Endpoint] = []
        for ring_number in msg.ring_numbers:
            out.extend(
                self._aggregate_edge(msg.edge_src, msg.edge_dst, msg.edge_status, ring_number)
            )
        return out

    def _aggregate_edge(
        self, link_src: Endpoint, link_dst: Endpoint, status: EdgeStatus, ring_number: int
    ) -> List[Endpoint]:
        if status == EdgeStatus.DOWN:
            self._seen_down_events = True

        reports_for_host = self._reports_per_host.setdefault(link_dst, {})
        if ring_number in reports_for_host:
            return []  # duplicate announcement for this ring, ignore
        reports_for_host[ring_number] = link_src
        num_reports = len(reports_for_host)

        if num_reports == self.l:
            self._updates_in_progress += 1
            self._pre_proposal.add(link_dst)
            self._record(
                EventName.CUT_L_CROSSED, subject=str(link_dst), reports=num_reports
            )

        if num_reports == self.h:
            self._pre_proposal.discard(link_dst)
            self._proposal.add(link_dst)
            self._updates_in_progress -= 1
            self._record(
                EventName.CUT_H_CROSSED, subject=str(link_dst), reports=num_reports
            )
            if self._updates_in_progress == 0:
                # Every subject past H and none in (L, H): release the cut.
                self._proposal_count += 1
                ret = list(self._proposal)
                self._proposal.clear()
                self._record(
                    EventName.CUT_RELEASED,
                    subjects=[str(node) for node in ret],
                )
                return ret
        return []

    def invalidate_failing_edges(self, view: "MembershipView") -> List[Endpoint]:
        """Implicit detection of edges whose observers are themselves failing
        (MultiNodeCutDetector.java:137-164). Safe no-op without DOWN events."""
        if not self._seen_down_events:
            return []
        proposals: List[Endpoint] = []
        for node_in_flux in list(self._pre_proposal):
            observers = (
                view.observers_of(node_in_flux)
                if view.is_host_present(node_in_flux)
                else view.expected_observers_of(node_in_flux)
            )
            for ring_number, observer in enumerate(observers):
                if observer in self._proposal or observer in self._pre_proposal:
                    status = (
                        EdgeStatus.DOWN if view.is_host_present(node_in_flux) else EdgeStatus.UP
                    )
                    proposals.extend(
                        self._aggregate_edge(observer, node_in_flux, status, ring_number)
                    )
        return proposals

    def aggregate_batch(self, msgs, view: "MembershipView") -> Set[Endpoint]:
        """Apply one alert batch plus implicit invalidation; returns the union
        of released proposals — the exact quantity the membership service
        consumes per BatchedAlertMessage (MembershipService.java:300-354).

        This is the detector SPI the service calls; device-backed detectors
        override it with a single batched kernel invocation."""
        proposal: Set[Endpoint] = set()
        for msg in msgs:
            proposal.update(self.aggregate(msg))
        proposal.update(self.invalidate_failing_edges(view))
        return proposal

    def clear(self) -> None:
        """Reset after a view change (MultiNodeCutDetector.java:169-178)."""
        self._reports_per_host.clear()
        self._proposal.clear()
        self._pre_proposal.clear()
        self._updates_in_progress = 0
        self._proposal_count = 0
        self._seen_down_events = False
