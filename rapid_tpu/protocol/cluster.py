"""Public cluster API: bootstrap a seed or join through one.

API surface mirrors the reference ``Cluster`` builder
(``Cluster.java:53-160``): ``start()`` boots a single-node cluster,
``join(seed)`` runs the two-phase bootstrap with retries
(``Cluster.java:303-437``), plus ``membership``/``metadata`` accessors,
subscriptions, ``leave_gracefully`` and ``shutdown``. Everything is
async-first; transports plug in through the messaging SPI.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, List, Optional

from rapid_tpu.errors import JoinError, JoinPhaseOneError, JoinPhaseTwoError
from rapid_tpu.messaging.base import MessagingClient, MessagingServer
from rapid_tpu.messaging.inprocess import InProcessClient, InProcessNetwork, InProcessServer
from rapid_tpu.monitoring.base import EdgeFailureDetectorFactory
from rapid_tpu.monitoring.ping_pong import PingPongFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.protocol.metadata import FrozenMetadata
from rapid_tpu.protocol.service import MembershipService
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    PreJoinMessage,
)
from rapid_tpu.utils.clock import Clock

LOG = logging.getLogger(__name__)


class Cluster:
    def __init__(
        self,
        listen_address: Endpoint,
        service: MembershipService,
        server: MessagingServer,
        client: MessagingClient,
    ) -> None:
        self.listen_address = listen_address
        self.service = service
        self._server = server
        self._client = client
        # Set by _from_join_response when the service start is scheduled
        # rather than awaited; shutdown() settles it first.
        self._start_task: Optional[asyncio.Task] = None

    # -- accessors (Cluster.java:98-129) -------------------------------

    @property
    def membership(self) -> List[Endpoint]:
        return self.service.membership

    @property
    def membership_size(self) -> int:
        return self.service.membership_size

    @property
    def metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return self.service.get_metadata()

    def register_subscription(self, event: ClusterEvents, callback) -> None:
        self.service.register_subscription(event, callback)

    @property
    def metrics(self):
        # Counters + timings for this node; view_change_convergence_ms is the
        # north-star metric (SURVEY §5.1).
        return self.service.metrics.summary()

    def telemetry_snapshot(self, recorder_tail=None):
        """The node's unified telemetry (utils/exposition.py schema): the
        service snapshot plus the server side of the transport accounting,
        which only this layer holds."""
        snapshot = self.service.telemetry_snapshot(recorder_tail=recorder_tail)
        server_stats = getattr(self._server, "stats", None)
        snapshot["transport"]["server"] = (
            server_stats.snapshot() if server_stats is not None else None
        )
        return snapshot

    def prometheus_text(self) -> str:
        """Prometheus text exposition for this node (stable names pinned by
        tests/test_observability.py) — the string to serve on /metrics."""
        from rapid_tpu.utils import exposition

        return exposition.prometheus_text(self.telemetry_snapshot(recorder_tail=0))

    # -- lifecycle ------------------------------------------------------

    async def leave_gracefully(self) -> None:
        """Tell observers to proactively report us DOWN, then shut down
        (Cluster.java:145-149)."""
        await self.service.leave()
        await self.shutdown()

    async def shutdown(self) -> None:
        if self._start_task is not None:
            # A join-built cluster scheduled service.start() instead of
            # awaiting it; settle it so the background loops exist before
            # service.shutdown() cancels them (start() is await-free, so
            # this completes in one scheduling step). A failed start must
            # not abort the teardown below — report it and keep going.
            (result,) = await asyncio.gather(self._start_task, return_exceptions=True)
            if isinstance(result, BaseException) and not isinstance(
                result, asyncio.CancelledError
            ):
                LOG.warning(
                    "%s service start failed before shutdown: %r", self, result
                )
            self._start_task = None
        await self._server.shutdown()
        await self.service.shutdown()

    def __str__(self) -> str:
        return f"Cluster:{self.listen_address}"

    # -- builders -------------------------------------------------------

    @classmethod
    async def start(
        cls,
        listen_address: Endpoint,
        settings: Optional[Settings] = None,
        network: Optional[InProcessNetwork] = None,
        client: Optional[MessagingClient] = None,
        server: Optional[MessagingServer] = None,
        fd_factory: Optional[EdgeFailureDetectorFactory] = None,
        metadata: FrozenMetadata = (),
        subscriptions: Optional[Dict[ClusterEvents, List]] = None,
        clock: Optional[Clock] = None,
        rng: Optional[random.Random] = None,
        cut_detector_factory=None,
        vote_tally_factory=None,
        broadcaster_factory=None,
        node_id: Optional[NodeId] = None,
    ) -> "Cluster":
        """Bootstrap a one-node cluster (Cluster.java:255-280).
        ``cut_detector_factory(k, h, l)`` swaps the detector implementation
        (e.g. DeviceCutDetector); ``vote_tally_factory(membership_size)``
        swaps the consensus vote tally (e.g. DeviceVoteTally) — together they
        put both halves of the protocol hot path on the accelerator.
        ``broadcaster_factory(client, listen_address, rng)`` swaps the
        broadcast strategy (e.g. ``GossipBroadcaster.factory()``); a factory
        whose product has a ``router`` method gets it wrapped around the
        service at the server seam (gossip unwrap/relay)."""
        settings = settings if settings is not None else Settings()
        settings.validate()
        client, server = cls._make_transport(listen_address, settings, network, client, server)
        fd_factory = fd_factory or PingPongFailureDetectorFactory(listen_address, client)
        # An injected identity makes a simulated run a pure function of its
        # seed (rapid_tpu/sim); production callers omit it and get a UUID.
        node_id = node_id if node_id is not None else NodeId.from_uuid()
        view = MembershipView(
            settings.k,
            node_ids=[node_id],
            endpoints=[listen_address],
            topology=settings.topology,
        )
        detector_factory = cut_detector_factory or MultiNodeCutDetector
        cut_detector = detector_factory(settings.k, settings.h, settings.l)
        metadata_map = {listen_address: metadata} if metadata else {}
        broadcaster = (
            broadcaster_factory(client, listen_address, rng) if broadcaster_factory else None
        )
        service = cls._service_class(settings)(
            my_addr=listen_address,
            cut_detector=cut_detector,
            view=view,
            settings=settings,
            client=client,
            fd_factory=fd_factory,
            metadata_map=metadata_map,
            subscriptions=subscriptions,
            clock=clock,
            rng=rng,
            vote_tally_factory=vote_tally_factory,
            broadcaster=broadcaster,
            node_id=node_id,
        )
        server.set_membership_service(cls._server_handler(broadcaster, service))
        await server.start()
        await service.start()
        return cls(listen_address, service, server, client)

    @staticmethod
    def _service_class(settings: Settings):
        """Flat or two-level service, by configuration. Imported lazily:
        the hier package depends on protocol/, not the other way around."""
        if settings.hier_target_cohort_size > 0:
            from rapid_tpu.hier.service import HierMembershipService

            return HierMembershipService
        return MembershipService

    @staticmethod
    def _server_handler(broadcaster, service):
        """The object the server dispatches to: the service itself, or the
        broadcaster's router facade when the broadcast strategy needs to see
        inbound envelopes (gossip relay)."""
        if broadcaster is not None and hasattr(broadcaster, "router"):
            return broadcaster.router(service)
        return service

    @classmethod
    async def join(
        cls,
        seed_address: Endpoint,
        listen_address: Endpoint,
        settings: Optional[Settings] = None,
        network: Optional[InProcessNetwork] = None,
        client: Optional[MessagingClient] = None,
        server: Optional[MessagingServer] = None,
        fd_factory: Optional[EdgeFailureDetectorFactory] = None,
        metadata: FrozenMetadata = (),
        subscriptions: Optional[Dict[ClusterEvents, List]] = None,
        clock: Optional[Clock] = None,
        rng: Optional[random.Random] = None,
        cut_detector_factory=None,
        vote_tally_factory=None,
        broadcaster_factory=None,
        node_id: Optional[NodeId] = None,
    ) -> "Cluster":
        """Two-phase join through ``seed_address`` with retries
        (Cluster.java:303-344)."""
        settings = settings if settings is not None else Settings()
        settings.validate()
        client, server = cls._make_transport(listen_address, settings, network, client, server)
        fd_factory = fd_factory or PingPongFailureDetectorFactory(listen_address, client)
        # Injected identity: see start(). The UUID_ALREADY_IN_RING retry
        # below still re-mints — identity reuse is rejected by the protocol
        # whatever the caller supplied.
        node_id = node_id if node_id is not None else NodeId.from_uuid()
        # The server starts before the service exists; probes are answered
        # with BOOTSTRAPPING in the meantime (Cluster.java:312).
        await server.start()

        try:
            for attempt in range(settings.join_attempts):
                try:
                    return await cls._join_attempt(
                        seed_address, listen_address, node_id, settings, client, server,
                        fd_factory, metadata, subscriptions, clock, rng,
                        cut_detector_factory, vote_tally_factory, broadcaster_factory,
                    )
                except JoinPhaseOneError as exc:
                    status = exc.join_response.status_code
                    LOG.warning("%s join phase 1 rejected: %s (attempt %d)",
                                listen_address, status.name, attempt)
                    if status == JoinStatusCode.UUID_ALREADY_IN_RING:
                        node_id = NodeId.from_uuid()
                    elif status not in (
                        JoinStatusCode.CONFIG_CHANGED,
                        JoinStatusCode.MEMBERSHIP_REJECTED,
                    ):
                        break
                except (
                    JoinPhaseTwoError,
                    ConnectionError,
                    OSError,
                    asyncio.TimeoutError,
                ) as exc:
                    LOG.warning("%s join attempt %d failed: %r", listen_address, attempt, exc)
        except BaseException:
            # Unexpected failure (codec error, cancellation, ...): never leak
            # the already-started server/client.
            await server.shutdown()
            await client.shutdown()
            raise

        await server.shutdown()
        await client.shutdown()
        raise JoinError(f"join attempt unsuccessful for {listen_address}")

    # ------------------------------------------------------------------

    @staticmethod
    def _make_transport(listen_address, settings, network, client, server):
        if client is not None and server is not None:
            return client, server
        if network is None:
            raise ValueError(
                "provide either (client, server) or an InProcessNetwork to attach to"
            )
        return (
            client or InProcessClient(network, listen_address, settings),
            server or InProcessServer(network, listen_address),
        )

    @classmethod
    async def _join_attempt(
        cls, seed_address, listen_address, node_id, settings, client, server,
        fd_factory, metadata, subscriptions, clock, rng, cut_detector_factory=None,
        vote_tally_factory=None, broadcaster_factory=None,
    ) -> "Cluster":
        """One join attempt: phase 1 at the seed, phase 2 at the observers
        (Cluster.java:352-401)."""
        phase1 = await client.send(
            seed_address, PreJoinMessage(sender=listen_address, node_id=node_id)
        )
        assert isinstance(phase1, JoinResponse)
        if phase1.status_code not in (
            JoinStatusCode.SAFE_TO_JOIN,
            JoinStatusCode.HOSTNAME_ALREADY_IN_RING,
        ):
            raise JoinPhaseOneError(phase1)

        # HOSTNAME_ALREADY_IN_RING: a previous attempt's consensus admitted us
        # while our phase 2 timed out; join with config -1 so any observer
        # streams the configuration back (Cluster.java:374-381).
        config_to_join = (
            -1
            if phase1.status_code == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
            else phase1.configuration_id
        )

        # Group ring numbers per observer so each observer gets one message
        # for all rings it gatekeeps (Cluster.java:406-419).
        ring_numbers_per_observer: Dict[Endpoint, List[int]] = {}
        for ring_number, observer in enumerate(phase1.endpoints):
            ring_numbers_per_observer.setdefault(observer, []).append(ring_number)

        sends = [
            client.send(
                observer,
                JoinMessage(
                    sender=listen_address,
                    node_id=node_id,
                    ring_numbers=tuple(ring_numbers),
                    configuration_id=config_to_join,
                    metadata=metadata,
                ),
            )
            for observer, ring_numbers in ring_numbers_per_observer.items()
        ]
        responses = await asyncio.gather(*sends, return_exceptions=True)
        for response in responses:
            if (
                isinstance(response, JoinResponse)
                and response.status_code == JoinStatusCode.SAFE_TO_JOIN
                and response.configuration_id != config_to_join
            ):
                return cls._from_join_response(
                    response, listen_address, settings, client, server,
                    fd_factory, subscriptions, clock, rng, cut_detector_factory,
                    vote_tally_factory, broadcaster_factory, node_id=node_id,
                )
        raise JoinPhaseTwoError()

    @classmethod
    def _from_join_response(
        cls, response: JoinResponse, listen_address, settings, client, server,
        fd_factory, subscriptions, clock, rng, cut_detector_factory=None,
        vote_tally_factory=None, broadcaster_factory=None, node_id=None,
    ) -> "Cluster":
        """Build the node from a streamed configuration (Cluster.java:442-474)."""
        assert response.endpoints and response.identifiers
        view = MembershipView(
            settings.k,
            node_ids=response.identifiers,
            endpoints=response.endpoints,
            topology=settings.topology,
        )
        metadata_map = dict(zip(response.metadata_keys, response.metadata_values))
        detector_factory = cut_detector_factory or MultiNodeCutDetector
        cut_detector = detector_factory(settings.k, settings.h, settings.l)
        broadcaster = (
            broadcaster_factory(client, listen_address, rng) if broadcaster_factory else None
        )
        service = cls._service_class(settings)(
            my_addr=listen_address,
            cut_detector=cut_detector,
            view=view,
            settings=settings,
            client=client,
            fd_factory=fd_factory,
            metadata_map=metadata_map,
            subscriptions=subscriptions,
            clock=clock,
            rng=rng,
            vote_tally_factory=vote_tally_factory,
            broadcaster=broadcaster,
            node_id=node_id,
        )
        server.set_membership_service(cls._server_handler(broadcaster, service))
        cluster = cls(listen_address, service, server, client)
        # This builder is sync (called from the join response loop), so the
        # service start is scheduled rather than awaited — but retained on
        # the cluster: an untracked task could be garbage-collected by the
        # loop before running, and shutdown() awaits it so the background
        # loops it spawns are fully armed before being torn down.
        cluster._start_task = asyncio.ensure_future(service.start())
        return cluster
