"""Subscription event model (reference: ClusterEvents.java, ClusterStatusChange.java,
NodeStatusChange.java)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Tuple

from rapid_tpu.protocol.metadata import FrozenMetadata
from rapid_tpu.types import EdgeStatus, Endpoint


class ClusterEvents(enum.Enum):
    """ClusterEvents.java:19-23. The reference declares
    VIEW_CHANGE_ONE_STEP_FAILED but never fires it; here the declared API is
    completed: it fires when the jittered classic-Paxos fallback engages
    because the fast round could not clear (service._on_fast_round_failed)."""

    VIEW_CHANGE_PROPOSAL = "VIEW_CHANGE_PROPOSAL"
    VIEW_CHANGE = "VIEW_CHANGE"
    #: Payload contract: the accompanying ClusterStatusChange carries the
    #: configuration id and membership of the view the fallback is deciding
    #: IN, with EMPTY status_changes — at fallback engagement no view delta
    #: has been decided yet (the fast round failed to pick one). Subscribers
    #: must not assume every notification carries changes; deltas arrive with
    #: the eventual VIEW_CHANGE. Deviation from the reference (which declares
    #: this event but never fires it) documented in PARITY.md.
    VIEW_CHANGE_ONE_STEP_FAILED = "VIEW_CHANGE_ONE_STEP_FAILED"
    KICKED = "KICKED"


@dataclass(frozen=True)
class NodeStatusChange:
    """NodeStatusChange.java:24-40."""

    endpoint: Endpoint
    status: EdgeStatus
    metadata: FrozenMetadata = ()


@dataclass(frozen=True)
class ClusterStatusChange:
    """ClusterStatusChange.java:20-34: (configuration id, full membership,
    delta of status changes)."""

    configuration_id: int
    membership: Tuple[Endpoint, ...]
    status_changes: Tuple[NodeStatusChange, ...]
