"""Per-node metadata registry (reference: MetadataManager.java)."""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

from rapid_tpu.types import Endpoint

FrozenMetadata = Tuple[Tuple[str, bytes], ...]


class MetadataManager:
    def __init__(self) -> None:
        self._table: Dict[Endpoint, FrozenMetadata] = {}

    def get(self, node: Endpoint) -> FrozenMetadata:
        return self._table.get(node, ())

    def add_metadata(self, roles: Mapping[Endpoint, FrozenMetadata]) -> None:
        """put-if-absent, like MetadataManager.java:49."""
        for node, metadata in roles.items():
            self._table.setdefault(node, metadata)

    def remove_node(self, node: Endpoint) -> None:
        self._table.pop(node, None)

    def get_all_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return dict(self._table)
