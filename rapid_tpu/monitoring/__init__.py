from rapid_tpu.monitoring.base import (
    EdgeFailureDetector,
    EdgeFailureDetectorFactory,
    EdgeFailureNotifier,
)
from rapid_tpu.monitoring.ping_pong import (
    PingPongFailureDetector,
    PingPongFailureDetectorFactory,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetector, StaticFailureDetectorFactory

__all__ = [
    "EdgeFailureDetector",
    "EdgeFailureDetectorFactory",
    "EdgeFailureNotifier",
    "PingPongFailureDetector",
    "PingPongFailureDetectorFactory",
    "StaticFailureDetector",
    "StaticFailureDetectorFactory",
]
