from rapid_tpu.monitoring.base import (
    EdgeFailureDetector,
    EdgeFailureDetectorFactory,
    EdgeFailureNotifier,
)
from rapid_tpu.monitoring.ping_pong import (
    PingPongFailureDetector,
    PingPongFailureDetectorFactory,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetector, StaticFailureDetectorFactory
from rapid_tpu.monitoring.windowed import (
    WindowedFailureDetector,
    WindowedFailureDetectorFactory,
)

__all__ = [
    "EdgeFailureDetector",
    "EdgeFailureDetectorFactory",
    "EdgeFailureNotifier",
    "PingPongFailureDetector",
    "PingPongFailureDetectorFactory",
    "StaticFailureDetector",
    "StaticFailureDetectorFactory",
    "WindowedFailureDetector",
    "WindowedFailureDetectorFactory",
]
