"""Default ping-pong edge failure detector
(reference: monitoring/impl/PingPongFailureDetector.java).

Each tick probes the subject; FAILURE_THRESHOLD consecutive failed windows
mark the edge faulty (notifier fired once). A bootstrapping subject (server up
but service not yet set) is tolerated for BOOTSTRAP_COUNT_THRESHOLD responses
before counting as failure (PingPongFailureDetector.java:41-45).
"""

from __future__ import annotations

from rapid_tpu.messaging.base import MessagingClient
from rapid_tpu.monitoring.base import (
    EdgeFailureDetector,
    EdgeFailureDetectorFactory,
    EdgeFailureNotifier,
)
from rapid_tpu.types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse

FAILURE_THRESHOLD = 10
BOOTSTRAP_COUNT_THRESHOLD = 30


class PingPongFailureDetector(EdgeFailureDetector):
    def __init__(
        self,
        my_addr: Endpoint,
        subject: Endpoint,
        client: MessagingClient,
        notifier: EdgeFailureNotifier,
        failure_threshold: int = FAILURE_THRESHOLD,
    ) -> None:
        self._my_addr = my_addr
        self._subject = subject
        self._client = client
        self._notifier = notifier
        self._failure_threshold = failure_threshold
        self._failure_count = 0
        self._bootstrap_responses = 0
        self._notified = False

    async def tick(self) -> None:
        if self._notified:
            return
        if self._failure_count >= self._failure_threshold:
            self._notified = True
            self._notifier()
            return
        response = await self._client.send_best_effort(
            self._subject, ProbeMessage(sender=self._my_addr)
        )
        if response is None:
            self._failure_count += 1
            return
        if isinstance(response, ProbeResponse) and response.status == NodeStatus.BOOTSTRAPPING:
            self._bootstrap_responses += 1
            if self._bootstrap_responses > BOOTSTRAP_COUNT_THRESHOLD:
                self._failure_count += 1
        # An OK probe does not reset the counter: the reference counts
        # consecutive windows without a successful reset either
        # (PingPongFailureDetector.java:74-85 increments only).


class PingPongFailureDetectorFactory(EdgeFailureDetectorFactory):
    def __init__(
        self, my_addr: Endpoint, client: MessagingClient, failure_threshold: int = FAILURE_THRESHOLD
    ) -> None:
        self._my_addr = my_addr
        self._client = client
        self._failure_threshold = failure_threshold

    def create_instance(
        self, subject: Endpoint, notifier: EdgeFailureNotifier
    ) -> EdgeFailureDetector:
        return PingPongFailureDetector(
            self._my_addr, subject, self._client, notifier, self._failure_threshold
        )
