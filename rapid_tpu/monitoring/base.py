"""Failure-detector plugin SPI (reference: monitoring/IEdgeFailureDetectorFactory.java).

One detector instance per monitoring edge (observer -> subject), re-created on
every configuration change; the membership service schedules each instance at
the failure-detector interval and the instance signals an edge failure by
invoking its notifier exactly once.
"""

from __future__ import annotations

import abc
from typing import Callable

from rapid_tpu.types import Endpoint

EdgeFailureNotifier = Callable[[], None]


class EdgeFailureDetector(abc.ABC):
    """Per-edge detector; ``tick`` runs once per failure-detector interval."""

    @abc.abstractmethod
    async def tick(self) -> None:
        ...


class EdgeFailureDetectorFactory(abc.ABC):
    @abc.abstractmethod
    def create_instance(
        self, subject: Endpoint, notifier: EdgeFailureNotifier
    ) -> EdgeFailureDetector:
        ...
