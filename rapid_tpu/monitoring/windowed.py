"""Windowed-fraction edge failure detector — the PAPER's stated policy.

The reference paper (§7) describes marking an edge faulty when "40% of the
last 10 probes" failed, but the shipped code uses a consecutive-failure
counter instead (PingPongFailureDetector.java:41, 74-85; SURVEY §2.3 flags
the divergence as behavior to standardize). This rebuild ships BOTH
policies as first-class detectors: ``PingPongFailureDetector`` matches the
shipped code; this detector matches the paper — a sliding window of the
last ``window`` probe outcomes, edge faulty once the window is full and the
failed fraction reaches ``fail_fraction``.

The windowed policy recovers from transient blips (old failures age out of
the window) where the counter policy latches them — the paper's rationale
for fractional measurement over multiple probes.
"""

from __future__ import annotations

import math
from collections import deque

from rapid_tpu.messaging.base import MessagingClient
from rapid_tpu.monitoring.base import (
    EdgeFailureDetector,
    EdgeFailureDetectorFactory,
    EdgeFailureNotifier,
)
from rapid_tpu.monitoring.ping_pong import BOOTSTRAP_COUNT_THRESHOLD
from rapid_tpu.types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse

WINDOW = 10
FAIL_FRACTION = 0.4


class WindowedFailureDetector(EdgeFailureDetector):
    def __init__(
        self,
        my_addr: Endpoint,
        subject: Endpoint,
        client: MessagingClient,
        notifier: EdgeFailureNotifier,
        window: int = WINDOW,
        fail_fraction: float = FAIL_FRACTION,
    ) -> None:
        if not 0 < fail_fraction <= 1:
            raise ValueError(f"fail_fraction must be in (0, 1], got {fail_fraction}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._my_addr = my_addr
        self._subject = subject
        self._client = client
        self._notifier = notifier
        self._window = window
        # ceil honors ">= fail_fraction": round-half would fire below the
        # configured fraction (e.g. 0.44 of 10 firing at 4/10 = 40%).
        self._fail_threshold = max(1, math.ceil(window * fail_fraction))
        self._outcomes: deque = deque(maxlen=window)  # True = probe failed
        self._bootstrap_responses = 0
        self._notified = False

    async def tick(self) -> None:
        if self._notified:
            return
        response = await self._client.send_best_effort(
            self._subject, ProbeMessage(sender=self._my_addr)
        )
        failed = response is None
        if (
            isinstance(response, ProbeResponse)
            and response.status == NodeStatus.BOOTSTRAPPING
        ):
            # Same bootstrap grace as the ping-pong detector: a starting
            # server is not a faulty one, up to a point.
            self._bootstrap_responses += 1
            failed = self._bootstrap_responses > BOOTSTRAP_COUNT_THRESHOLD
        self._outcomes.append(failed)
        if (
            len(self._outcomes) == self._window
            and sum(self._outcomes) >= self._fail_threshold
        ):
            self._notified = True
            self._notifier()


class WindowedFailureDetectorFactory(EdgeFailureDetectorFactory):
    def __init__(
        self,
        my_addr: Endpoint,
        client: MessagingClient,
        window: int = WINDOW,
        fail_fraction: float = FAIL_FRACTION,
    ) -> None:
        self._my_addr = my_addr
        self._client = client
        self._window = window
        self._fail_fraction = fail_fraction

    def create_instance(
        self, subject: Endpoint, notifier: EdgeFailureNotifier
    ) -> EdgeFailureDetector:
        return WindowedFailureDetector(
            self._my_addr,
            subject,
            self._client,
            notifier,
            self._window,
            self._fail_fraction,
        )
