"""Deterministic failure detector for tests and simulation
(reference test fixture: StaticFailureDetector.java:24-62).

A shared mutable blacklist decides which subjects are "down"; adding a node to
the blacklist makes every edge pointing at it fail on the next tick. This is
the host-side analog of the TPU engine's fault-mask arrays.
"""

from __future__ import annotations

from typing import Iterable, Set

from rapid_tpu.monitoring.base import (
    EdgeFailureDetector,
    EdgeFailureDetectorFactory,
    EdgeFailureNotifier,
)
from rapid_tpu.types import Endpoint


class StaticFailureDetector(EdgeFailureDetector):
    def __init__(self, subject: Endpoint, blacklist: Set[Endpoint], notifier: EdgeFailureNotifier):
        self._subject = subject
        self._blacklist = blacklist
        self._notifier = notifier
        self._notified = False

    async def tick(self) -> None:
        if not self._notified and self._subject in self._blacklist:
            self._notified = True
            self._notifier()


class StaticFailureDetectorFactory(EdgeFailureDetectorFactory):
    def __init__(self, blacklist: Iterable[Endpoint] = ()) -> None:
        self.blacklist: Set[Endpoint] = set(blacklist)

    def add_failed_nodes(self, nodes: Iterable[Endpoint]) -> None:
        self.blacklist.update(nodes)

    def create_instance(
        self, subject: Endpoint, notifier: EdgeFailureNotifier
    ) -> EdgeFailureDetector:
        return StaticFailureDetector(subject, self.blacklist, notifier)
