"""Deterministic chaos-simulation subsystem.

The reference validates Rapid's headline claim — stable, consistent
membership under adverse networks — with fault-injection test fixtures
(``MessageDropInterceptor.java``) driven by hand-written scenarios. This
package turns that into a subsystem in the FoundationDB/Jepsen mold:

- :mod:`rapid_tpu.sim.faults` — a declarative, serializable fault-schedule
  model (link loss/delay/duplication, symmetric and asymmetric partitions,
  crash/restart, clock skew/pause) compiled onto the in-process transport's
  fault seams and the injected clock, so a whole run is a pure function of
  one seed;
- :mod:`rapid_tpu.sim.scenario` — the scenario runner: builds a cluster,
  steps simulated time, applies the schedule, and captures a replayable
  repro artifact (schedule + per-node flight recordings + outcome);
- :mod:`rapid_tpu.sim.oracles` — invariant checkers executed after every
  run: configuration-chain consistency (no split-brain), per-node
  monotonicity, final agreement, eviction discipline, bounded convergence,
  and the differential host<->device oracle that replays the same schedule
  through the jitted engine;
- :mod:`rapid_tpu.sim.fuzz` — seeded random-schedule generation plus a
  greedy shrinker that minimizes any oracle-violating schedule into the
  smallest repro that still fails.

``tools/chaosrun.py`` is the CLI over all four.
"""

from rapid_tpu.sim.faults import FaultEvent, FaultSchedule, LinkShaper
from rapid_tpu.sim.scenario import RunResult, ScenarioRunner, SimHarness

__all__ = [
    "FaultEvent",
    "FaultSchedule",
    "LinkShaper",
    "RunResult",
    "ScenarioRunner",
    "SimHarness",
]
