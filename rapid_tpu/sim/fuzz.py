"""Schedule-space search: seeded generation, named families, shrinking.

Three pieces:

- **Named scenario families** — the four canonical adverse-network shapes
  (partition-heal, asymmetric link, crash-during-join, churn-under-loss),
  the ADVERSARIAL shapes (false-alert stability, watermark probe — Byzantine
  observers lying against the H/L watermarks, scenarios the paper's
  honest-but-flaky evaluation never reached), and the WAN-shaped
  hierarchical-membership shapes (inter-cohort loss/latency asymmetry,
  delegate gray failure, cohort-boundary flapping, committee crash during
  reconfiguration — ``profile="hier"``, run over the two-level protocol of
  :mod:`rapid_tpu.hier`), each a seeded generator over a fixed slot
  geometry so every (family, seed) pair is one pinned, replayable scenario.
  The tier-1 chaos smoke runs a pinned grid of these; ``tools/chaosrun.py``
  runs them by name.
- **Random schedules** — :func:`random_schedule` draws arbitrary mixes of
  membership phases and environment faults, sized to keep the cluster
  decidable (slot 0 never faulted, enough reachable voters for a classic
  majority, partitions always healed) so a violation means the PROTOCOL
  broke, not the scenario.
- **The shrinker** — :func:`shrink` greedily minimizes an oracle-violating
  schedule: drop events, shrink fault sets, zero dwell times — accepting a
  reduction only if the original violation (same oracle set) still fires.
  The result is the smallest repro the greedy pass can reach, which is what
  gets written to disk and attached to the bug.

All geometry is shared (``N0``/``N_SLOTS``) so the differential oracle's
engine executable compiles once per process, not once per seed.
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from rapid_tpu.hier.cohorts import CohortMap
from rapid_tpu.sim.faults import (
    WATERMARK_H,
    WATERMARK_L,
    FaultEvent,
    FaultSchedule,
    ScheduleError,
)
from rapid_tpu.sim.oracles import Violation, check_all
from rapid_tpu.sim.scenario import (
    RunResult,
    ScenarioRunner,
    endpoints_for,
    hier_sim_settings,
)

#: One slot geometry for every generated scenario: 8 initial members, a
#: 4-slot joiner pool. Small enough that a full run is cheap, large enough
#: that H=9-of-K=10 cut detection, fast-quorum arithmetic (7 of 8), and the
#: classic fallback (majority 5) are all exercised.
N0 = 8
N_SLOTS = 12


def _initial_live(rng: random.Random) -> List[int]:
    """Non-seed initial members, shuffled — faultable in draw order."""
    live = list(range(1, N0))
    rng.shuffle(live)
    return live


# ---------------------------------------------------------------------------
# the flat named families
# ---------------------------------------------------------------------------


def partition_heal(seed: int) -> FaultSchedule:
    """One-way partition across a crash decision, then heal: the blocked
    members' ingress is dead through the view change — they miss the
    decision (and hold the fast round below quorum, so the CLASSIC path
    decides) and must re-join the configuration through config pulls, first
    through the partition, then after the heal."""
    rng = random.Random(f"partition-heal:{seed}")
    pool = _initial_live(rng)
    blocked, victim = sorted(pool[:2]), pool[2]
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"partition_heal/{seed}",
        events=[
            FaultEvent("ingress_block", tuple(blocked), dwell_ms=500),
            FaultEvent("crash", (victim,), dwell_ms=1_000),
            FaultEvent("heal_partitions", dwell_ms=500),
        ],
    )


def asymmetric_link(seed: int) -> FaultSchedule:
    """A one-way ingress partition (the victim still sends — the asymmetric
    failure the paper's §1 motivates): observers evict it; a fresh joiner
    then arrives through the healed network."""
    rng = random.Random(f"asymmetric-link:{seed}")
    pool = _initial_live(rng)
    victim, skewed = pool[0], pool[1]
    joiner = N0 + (seed % (N_SLOTS - N0))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"asymmetric_link/{seed}",
        events=[
            FaultEvent("clock_skew", (skewed,), args={"offset_ms": 350.0}),
            FaultEvent("partition_oneway", (victim,), dwell_ms=1_000),
            FaultEvent("heal_partitions", dwell_ms=500),
            FaultEvent("join", (joiner,), dwell_ms=500),
        ],
    )


def crash_during_join(seed: int) -> FaultSchedule:
    """A join wave overlapped with a crash (settle=False): the join's UP
    alerts and the crash's DOWN alerts race into the same cut detectors —
    the straddling-configuration shape of the fixed-scenario oracle."""
    rng = random.Random(f"crash-during-join:{seed}")
    pool = _initial_live(rng)
    victim = pool[0]
    joiners = tuple(range(N0, N0 + 2))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"crash_during_join/{seed}",
        events=[
            FaultEvent("join", joiners, settle=False),
            FaultEvent("crash", (victim,), dwell_ms=1_000),
        ],
    )


def churn_under_loss(seed: int) -> FaultSchedule:
    """Sustained 5% symmetric message loss (plus duplication) while the
    membership churns — joins, a crash, a graceful leave. The delivery-
    liveness machinery (alert redelivery, config-sync pulls) must absorb
    the loss; the decided cuts must be exactly the clean-network ones."""
    rng = random.Random(f"churn-under-loss:{seed}")
    pool = _initial_live(rng)
    victim, leaver = pool[0], pool[1]
    joiners = tuple(range(N0, N0 + 2))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"churn_under_loss/{seed}",
        events=[
            FaultEvent("loss", args={"permille": 50}),
            FaultEvent("duplicate", args={"permille": 20}),
            FaultEvent("join", joiners, dwell_ms=500),
            FaultEvent("crash", (victim,), dwell_ms=500),
            FaultEvent("leave", (leaver,), dwell_ms=500),
            FaultEvent("loss", args={"permille": 0}),
        ],
    )


# ---------------------------------------------------------------------------
# adversarial families: Byzantine observers against the H/L watermarks
# ---------------------------------------------------------------------------


def false_alert_stability(seed: int) -> FaultSchedule:
    """The paper's stability claim, tested against an observer that LIES:
    a Byzantine liar claims a seeded number of distinct rings in [L, H)
    about a healthy subject, then three more colluders re-claim the SAME
    rings (an idempotent storm — per-ring dedup must keep the count where
    it is). The cumulative tally sits in the stable band for the whole run:
    no view change may fire, the subject stays in every view, and once the
    lies cease the cluster is simply converged (it never moved)."""
    rng = random.Random(f"false-alert-stability:{seed}")
    pool = _initial_live(rng)
    liar, subject = pool[0], pool[1]
    colluders = tuple(sorted(pool[2:5]))
    reports = rng.randint(WATERMARK_L, WATERMARK_H - 1)
    rings = list(range(reports))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"false_alert_stability/{seed}",
        events=[
            FaultEvent("false_alert", (liar,),
                       args={"subject": subject, "rings": rings},
                       dwell_ms=2_000),
            FaultEvent("alert_storm", colluders,
                       args={"subject": subject, "rings": rings},
                       dwell_ms=2_000),
        ],
    )


def watermark_probe(seed: int) -> FaultSchedule:
    """Adversarially timed against the exact watermark boundary: one liar
    holds the subject's count at a seeded value in [L, H) for a dwell (the
    stable band — no view change), then a storm of colluders tops the
    cumulative count up to EXACTLY H. The healthy subject is evicted — the
    adversary wins that much — but the eviction must be one agreed,
    chain-consistent cut: every node delivers the same (wrong) view."""
    rng = random.Random(f"watermark-probe:{seed}")
    pool = _initial_live(rng)
    liar, subject = pool[0], pool[1]
    colluders = tuple(sorted(pool[2:4]))
    hold = rng.randint(WATERMARK_L, WATERMARK_H - 1)
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"watermark_probe/{seed}",
        events=[
            FaultEvent("false_alert", (liar,),
                       args={"subject": subject, "rings": list(range(hold))},
                       dwell_ms=1_500),
            FaultEvent("alert_storm", colluders,
                       args={"subject": subject,
                             "rings": list(range(hold, WATERMARK_H))},
                       dwell_ms=1_000),
        ],
    )


# ---------------------------------------------------------------------------
# WAN-shaped hierarchical families (rapid_tpu/hier; profile="hier")
# ---------------------------------------------------------------------------


def hier_geometry(seed: int):
    """The cohort structure of the INITIAL 8-member hierarchical cluster for
    a family seed: (cohort map, slot-of-endpoint). Deterministic — the
    generator reasons about the exact cohorts the runner will boot, so a
    family can aim a fault at a real delegate or a real cohort boundary."""
    settings = hier_sim_settings()
    endpoints = endpoints_for(seed, N_SLOTS)
    cmap = CohortMap(
        endpoints[:N0], settings.hier_seed, settings.hier_target_cohort_size
    )
    slot_of = {ep: i for i, ep in enumerate(endpoints)}
    return cmap, endpoints, slot_of


def wan_cohort_asym(seed: int) -> FaultSchedule:
    """Inter-cohort latency/loss asymmetry: the cohort on the far side of a
    lossy, slow WAN boundary (25% cross-boundary loss, +20..120 ms
    cross-boundary delay) loses a member and admits a joiner. The cohort-
    local fast path never crosses the boundary — detection and cohort
    agreement run at LAN speed — and only the thin global tier pays the WAN;
    its redelivery/classic machinery must absorb the loss."""
    cmap, endpoints, slot_of = hier_geometry(seed)
    rng = random.Random(f"wan-cohort-asym:{seed}")
    seed_cohort = cmap.cohort_of(endpoints[0])
    far = next(c for c in range(cmap.n_cohorts) if c != seed_cohort)
    group = sorted(slot_of[ep] for ep in cmap.members_of(far))
    victim = rng.choice(group)
    joiner = N0 + (seed % (N_SLOTS - N0))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, profile="hier",
        name=f"wan_cohort_asym/{seed}",
        events=[
            FaultEvent("wan_asym", tuple(group),
                       args={"loss_permille": 250, "delay_min_ms": 20.0,
                             "delay_max_ms": 120.0}),
            FaultEvent("crash", (victim,), dwell_ms=1_000),
            FaultEvent("join", (joiner,), dwell_ms=500),
            FaultEvent("wan_asym", args={"loss_permille": 0}),
        ],
    )


def delegate_gray_failure(seed: int) -> FaultSchedule:
    """Gray failure of a delegate: a global-committee member keeps SENDING
    (its egress is open) but hears nothing (ingress partitioned) — the
    asymmetric half-death that wedges naive leader-based designs. Its
    cohort must detect it, decide the cut without it, fail over the
    forwarding chain, and the committee must decide classically around the
    unresponsive member; a joiner then lands through the healed network."""
    cmap, endpoints, slot_of = hier_geometry(seed)
    rng = random.Random(f"delegate-gray:{seed}")
    committee = [ep for ep in cmap.committee() if ep != endpoints[0]]
    victim = slot_of[rng.choice(committee)]
    skew_pool = [s for s in range(1, N0) if s != victim]
    skewed = rng.choice(skew_pool)
    joiner = N0 + (seed % (N_SLOTS - N0))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, profile="hier",
        name=f"delegate_gray_failure/{seed}",
        events=[
            FaultEvent("clock_skew", (skewed,), args={"offset_ms": 250.0}),
            FaultEvent("partition_oneway", (victim,), dwell_ms=1_000),
            FaultEvent("heal_partitions", dwell_ms=500),
            FaultEvent("join", (joiner,), dwell_ms=500),
        ],
    )


def cohort_boundary_flap(seed: int) -> FaultSchedule:
    """Flapping across the cohort boundary: one inter-cohort link blocks
    and heals repeatedly — in both directions — while a join overlaps a
    crash. The flap touches only cross-cohort traffic (the global tier and
    config pulls); cohort-local detection must stay quiet about it (no
    false evictions of the flapping link's endpoints) and the overlapped
    churn must still serialize into one consistent chain."""
    cmap, endpoints, slot_of = hier_geometry(seed)
    rng = random.Random(f"boundary-flap:{seed}")
    seed_cohort = cmap.cohort_of(endpoints[0])
    far = next(c for c in range(cmap.n_cohorts) if c != seed_cohort)
    near_pool = [
        slot_of[ep] for ep in cmap.members_of(seed_cohort) if ep != endpoints[0]
    ]
    far_pool = sorted(slot_of[ep] for ep in cmap.members_of(far))
    a = rng.choice(near_pool)
    b, victim = rng.sample(far_pool, 2)
    joiner = N0 + (seed % (N_SLOTS - N0))
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, profile="hier",
        name=f"cohort_boundary_flap/{seed}",
        events=[
            FaultEvent("link_block", args={"src": a, "dst": b}, dwell_ms=400),
            FaultEvent("link_heal", args={"src": a, "dst": b}, dwell_ms=200),
            FaultEvent("link_block", args={"src": b, "dst": a}, dwell_ms=400),
            FaultEvent("join", (joiner,), settle=False),
            FaultEvent("crash", (victim,), dwell_ms=800),
            FaultEvent("link_heal", args={"src": b, "dst": a}, dwell_ms=300),
        ],
    )


def committee_crash_during_reconfig(seed: int) -> FaultSchedule:
    """Crash a global-committee member INSIDE the hier reconfiguration
    window (the committee-crash shape of "Reconfigurable Atomic Transaction
    Commit", arXiv:1906.01365): the armed tripwire fires the instant the
    triggering crash's cohort cut is forwarded to the committee — after
    forwarding, before the global decision. The committee must still decide
    (classic fallback around the dead member), the cohort forwarding chain
    must fail over, and the dead committee member is detected and evicted
    in a follow-up cut — two removals, one consistent chain."""
    cmap, endpoints, slot_of = hier_geometry(seed)
    rng = random.Random(f"committee-crash:{seed}")
    committee = [ep for ep in cmap.committee() if ep != endpoints[0]]
    victim = slot_of[rng.choice(committee)]
    # The trigger must be a NON-committee member: the committee is static
    # for the configuration and sized 2 per cohort, so losing the armed
    # victim AND a committee-member trigger would drop the global tier
    # below its classic majority — a legitimate wedge, but a different
    # scenario (quorum loss) than the reconfiguration-window crash this
    # family pins.
    committee_slots = {slot_of[ep] for ep in cmap.committee()}
    trigger_pool = [
        s for s in range(1, N0) if s != victim and s not in committee_slots
    ]
    trigger = rng.choice(trigger_pool)
    return FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, profile="hier",
        name=f"committee_crash_during_reconfig/{seed}",
        events=[
            FaultEvent("committee_crash", (victim,), settle=False),
            FaultEvent("crash", (trigger,), dwell_ms=1_000),
        ],
    )


FAMILIES: Dict[str, Callable[[int], FaultSchedule]] = {
    "partition_heal": partition_heal,
    "asymmetric_link": asymmetric_link,
    "crash_during_join": crash_during_join,
    "churn_under_loss": churn_under_loss,
    "false_alert_stability": false_alert_stability,
    "watermark_probe": watermark_probe,
    "wan_cohort_asym": wan_cohort_asym,
    "delegate_gray_failure": delegate_gray_failure,
    "cohort_boundary_flap": cohort_boundary_flap,
    "committee_crash_during_reconfig": committee_crash_during_reconfig,
}


def scenario_family(name: str, seed: int) -> FaultSchedule:
    try:
        return FAMILIES[name](seed)
    except KeyError:
        raise ScheduleError(
            f"unknown scenario family {name!r}; known: {sorted(FAMILIES)}"
        ) from None


# ---------------------------------------------------------------------------
# random schedules
# ---------------------------------------------------------------------------


def random_schedule(seed: int, phases: Optional[int] = None) -> FaultSchedule:
    """A seeded random mix of membership phases and environment faults over
    the shared geometry. Sizing rules keep every schedule decidable — a
    violation means the protocol broke, not the scenario: slot 0 is never
    faulted, at most 2 slots are ingress-blocked at once, full symmetric
    partitions only appear as (partition, heal) brackets with no membership
    phase in between (spanning one can legitimately wedge detection below
    H — the shape reserved for the shrinker's violating schedules), every
    block heals, loss stays <= 8%, and membership never drops below 2/3 of
    its peak."""
    rng = random.Random(f"rapid-fuzz:{seed}")
    live = set(range(N0))
    peak = N0
    fresh = list(range(N0, N_SLOTS))
    removed: List[int] = []
    events: List[FaultEvent] = []
    partitioned = False
    blocked_now: set = set()

    lossy = rng.random() < 0.5
    if lossy:
        events.append(FaultEvent("loss", args={"permille": rng.choice([20, 50, 80])}))
    if rng.random() < 0.3:
        events.append(FaultEvent("duplicate", args={"permille": 20}))
    if rng.random() < 0.3:
        events.append(FaultEvent(
            "delay", args={"min_ms": 0.0, "max_ms": float(rng.choice([50, 150]))}
        ))

    for _ in range(phases if phases is not None else rng.randint(2, 4)):
        floor = (peak * 2) // 3
        removable = len(live) - floor
        choices = ["join", "crash", "leave", "partition_oneway"]
        if removed:
            choices.append("restart")
        if not partitioned and removable >= 2 and rng.random() < 0.4:
            blocked = rng.sample(sorted(live - {0}), rng.randint(1, 2))
            events.append(
                FaultEvent("ingress_block", tuple(sorted(blocked)), dwell_ms=500)
            )
            partitioned = True
            blocked_now = set(blocked)
        elif not partitioned and rng.random() < 0.2:
            # A full symmetric partition, healed before the next membership
            # phase: a sub-detection-threshold network blip the cluster must
            # ride out without any membership effect.
            blipped = rng.sample(sorted(live - {0}), 1)
            events.append(FaultEvent("partition", tuple(blipped), dwell_ms=1_000))
            events.append(FaultEvent("heal_partitions", dwell_ms=500))
        kind = rng.choice(choices)
        if kind in ("join", "restart") and blocked_now:
            # An admission while members are ingress-blocked can wedge
            # legitimately: if >= K-H+1 of the joiner's gatekeepers cannot
            # RECEIVE its phase-2 join messages, the admission cut sits
            # below H until the heal — a real protocol property, but not a
            # schedule that must converge. Generated schedules admit only
            # on an unblocked network; the pinned chaos soak covers the
            # join-under-partition shapes that do work.
            kind = "crash"
        if kind == "join" and fresh:
            size = rng.randint(1, min(2, len(fresh)))
            slots = [fresh.pop(0) for _ in range(size)]
            events.append(FaultEvent("join", tuple(slots), dwell_ms=500))
            live |= set(slots)
            peak = max(peak, len(live))
        elif kind == "restart" and removed:
            slot = removed.pop(0)
            events.append(FaultEvent("restart", (slot,), dwell_ms=500))
            live.add(slot)
            peak = max(peak, len(live))
        # Quorum headroom: the decision evicting this phase's victims runs
        # inside the PRE-phase configuration (majority of len(live)), and
        # neither the victims nor the ingress-blocked members can vote (the
        # blocked cannot hear the proposal). Reachable voters must keep a
        # classic majority or the phase wedges until the heal — a real
        # protocol property, but not a schedule that must converge.
        # Under sustained loss, a margin-less quorum (exactly majority
        # reachable) can stall for many simulated seconds — every consensus
        # message of some round must land. Keep one voter of slack.
        max_victims = (
            len(live) - len(blocked_now) - (len(live) // 2 + 1) - (1 if lossy else 0)
        )
        if kind == "crash" and removable >= 1 and max_victims >= 1:
            candidates = sorted(live - {0} - blocked_now)
            if not candidates:
                continue
            size = rng.randint(1, min(2, removable, max_victims, len(candidates)))
            slots = rng.sample(candidates, size)
            events.append(FaultEvent("crash", tuple(sorted(slots)), dwell_ms=500))
            live -= set(slots)
            removed.extend(slots)
        elif kind in ("leave", "partition_oneway") and removable >= 1 and max_victims >= 1:
            candidates = sorted(live - {0} - blocked_now)
            if not candidates:
                continue
            slot = rng.choice(candidates)
            events.append(FaultEvent(kind, (slot,), dwell_ms=500))
            live -= {slot}
            removed.append(slot)
        if partitioned and rng.random() < 0.6:
            events.append(FaultEvent("heal_partitions", dwell_ms=500))
            partitioned = False
            blocked_now = set()

    if partitioned:
        events.append(FaultEvent("heal_partitions", dwell_ms=500))
    events.append(FaultEvent("loss", args={"permille": 0}))
    schedule = FaultSchedule(
        n0=N0, n_slots=N_SLOTS, seed=seed, name=f"fuzz/{seed}", events=events
    )
    schedule.validate()
    return schedule


# ---------------------------------------------------------------------------
# running, shrinking, replaying
# ---------------------------------------------------------------------------


def run_schedule(schedule: FaultSchedule) -> RunResult:
    return ScenarioRunner(schedule).run()


def _violation_names(violations: Iterable[Violation]) -> frozenset:
    return frozenset(v.oracle for v in violations)


def _shrink_candidates(schedule: FaultSchedule) -> Iterable[FaultSchedule]:
    """Reductions in decreasing aggressiveness: drop an event, drop one slot
    from a multi-slot fault, zero a dwell. Each candidate revalidates, so a
    reduction that orphans a later event (e.g. removing a join whose slot is
    later crashed) is skipped, not crashed on."""
    events = schedule.events

    def rebuilt(new_events: List[FaultEvent]) -> FaultSchedule:
        return FaultSchedule(
            n0=schedule.n0, n_slots=schedule.n_slots, seed=schedule.seed,
            events=new_events, converge_budget_ms=schedule.converge_budget_ms,
            phase_budget_ms=schedule.phase_budget_ms, name=schedule.name,
            profile=schedule.profile,
        )

    for i in range(len(events)):
        yield rebuilt(events[:i] + events[i + 1:])
    for i, event in enumerate(events):
        if len(event.slots) > 1:
            for j in range(len(event.slots)):
                slots = event.slots[:j] + event.slots[j + 1:]
                reduced = FaultEvent(
                    event.kind, slots, dict(event.args), event.dwell_ms, event.settle
                )
                yield rebuilt(events[:i] + [reduced] + events[i + 1:])
    for i, event in enumerate(events):
        if event.dwell_ms > 0:
            reduced = FaultEvent(
                event.kind, event.slots, dict(event.args), 0.0, event.settle
            )
            yield rebuilt(events[:i] + [reduced] + events[i + 1:])


def shrink(
    schedule: FaultSchedule,
    violations: List[Violation],
    max_runs: int = 80,
) -> Tuple[FaultSchedule, List[Violation], int]:
    """Greedily minimize an oracle-violating schedule: accept any reduction
    under which every oracle of the ORIGINAL violation set still fires.
    The differential oracle is excluded from the preserved set — the loop
    re-runs candidates without the (expensive) engine replay, so it could
    never observe a differential violation and would otherwise reject every
    reduction; callers re-verify the final repro with the full battery.
    Returns (minimal schedule, its violations, runs spent)."""
    target = _violation_names(violations) - {"differential"}
    if not target:
        if _violation_names(violations):
            raise ValueError(
                "cannot shrink a differential-only violation: the shrink "
                "loop runs without the engine replay"
            )
        raise ValueError("nothing to shrink: the schedule passed its oracles")
    current, current_violations = schedule, violations
    runs = 0
    improved = True
    while improved and runs < max_runs:
        improved = False
        for candidate in _shrink_candidates(current):
            if runs >= max_runs:
                break
            try:
                candidate.validate()
            except ScheduleError:
                continue
            result = run_schedule(candidate)
            runs += 1
            got = check_all(result, differential=False)
            if target <= _violation_names(got):
                current, current_violations = candidate, got
                improved = True
                break
    return current, current_violations, runs


def write_repro(
    result: RunResult,
    violations: List[Violation],
    directory,
) -> Path:
    """The full repro artifact: the run's schedule and captures, plus the
    violations it proves."""
    directory = Path(result.write_repro(directory))
    (directory / "violations.txt").write_text(
        "".join(f"{v}\n" for v in violations) or "(none)\n"
    )
    return directory


def replay(directory) -> Tuple[RunResult, List[Violation]]:
    """Re-run a written repro: loads ``schedule.json`` and replays it (same
    seed, same draws, same simulated clock) through the full oracle
    battery. Deterministic: the violations reproduce exactly."""
    schedule = FaultSchedule.from_json(
        (Path(directory) / "schedule.json").read_text()
    )
    result = run_schedule(schedule)
    return result, check_all(result)


def fuzz(
    seeds: Iterable[int],
    out_dir=None,
    shrink_failures: bool = True,
) -> List[dict]:
    """Run random schedules over ``seeds``; on any oracle violation, shrink
    to a minimal repro and (when ``out_dir`` is given) write it to
    ``<out_dir>/seed<N>/``. Returns one summary dict per seed."""
    summaries = []
    for seed in seeds:
        schedule = random_schedule(seed)
        result = run_schedule(schedule)
        violations = check_all(result)
        summary: dict = {
            "seed": seed,
            "events": len(schedule.events),
            "violations": [str(v) for v in violations],
        }
        if violations and shrink_failures:
            minimal, _, runs = shrink(schedule, violations)
            summary["shrunk_events"] = len(minimal.events)
            summary["shrink_runs"] = runs
            if out_dir is not None:
                repro_dir = Path(out_dir) / f"seed{seed}"
                # Re-verify the minimal schedule with the FULL battery
                # (shrink ran without the differential replay): the repro's
                # recorded violations must be exactly what a replay sees,
                # or `chaosrun replay` would flag its own artifact.
                min_result = run_schedule(minimal)
                write_repro(min_result, check_all(min_result), repro_dir)
                summary["repro"] = str(repro_dir)
        summaries.append(summary)
    return summaries
