"""Scenario runner: apply a fault schedule to a simulated cluster.

Two layers:

- :class:`SimHarness` — the reusable asyncio-stack scaffolding (bootstrap
  through the seed, per-node :class:`~rapid_tpu.utils.clock.NodeClock` over
  one shared ``ManualClock``, cut/configuration capture on every node, and
  the fault primitives compiled onto the in-process transport seams). The
  cross-stack oracle tests drive it directly with bespoke scenarios; the
  runner below drives it from a declarative schedule.
- :class:`ScenarioRunner` — interprets a :class:`FaultSchedule` over a
  harness: applies events in order, convergence-waits after each settling
  membership phase, advances simulated time by each event's dwell, and
  captures everything a replay needs (the schedule, a fault log stamped in
  simulated time, per-node flight recordings, the outcome) into a repro
  directory ``tools/traceview.py`` can render end-to-end.

A run is deterministic: one seed fixes the statistical link faults, node
rngs are derived from slot numbers, and all time is the schedule's.
"""

from __future__ import annotations

import asyncio
import json
import random
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from rapid_tpu.errors import JoinError
from rapid_tpu.messaging.inprocess import (
    InProcessNetwork,
    RequestTripwire,
    ServerDropFirstN,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.settings import Settings
from rapid_tpu.sim.faults import (
    DROPPABLE_MESSAGES,
    MEMBERSHIP_KINDS,
    FaultEvent,
    FaultSchedule,
    LinkShaper,
    schedule_rng,
)
from rapid_tpu.types import CohortCutMessage, EdgeStatus, Endpoint, NodeId
from rapid_tpu.utils.clock import ManualClock, NodeClock


async def _drain(loop_yields: int = 60) -> None:
    for _ in range(loop_yields):
        await asyncio.sleep(0)


class SimHarness:
    """Simulated-cluster scaffolding: lifecycle, fault primitives, capture.

    ``endpoints[slot]`` is the address of slot ``slot``; slot 0 is the seed.
    Every node runs on its own :class:`NodeClock` over the one shared
    ``ManualClock`` (so clock faults are per-node), with ``random.Random(slot)``
    as its protocol rng (so jitter is a function of the slot number).
    """

    def __init__(
        self,
        endpoints: Sequence[Endpoint],
        settings: Optional[Settings] = None,
        id_seed: Optional[int] = None,
    ) -> None:
        self.endpoints = list(endpoints)
        self.settings = settings if settings is not None else Settings()
        #: Seed for deterministic node identities: configuration ids (which
        #: fold the member identifiers) then replay bit-identically run to
        #: run. None = UUID identities, as production mints them.
        self.id_seed = id_seed
        self._incarnation: Dict[int, int] = {}
        self.network = InProcessNetwork()
        self.clock = ManualClock()
        self.node_clocks: Dict[int, NodeClock] = {}
        self.fd = StaticFailureDetectorFactory()
        self.clusters: Dict[int, Cluster] = {}
        self.live_ids: set = set()
        #: Slots currently symmetrically partitioned away (cannot even pull):
        #: phase convergence excludes them; the post-heal final convergence
        #: does not.
        self.blocked_slots: set = set()
        #: node 0's view-change deltas after bootstrap, each a frozenset of
        #: (Endpoint, EdgeStatus) — the cut sequence the oracles compare.
        self.cuts: List[frozenset] = []
        #: Per-slot delivered configuration history, from birth:
        #: (configuration_id, membership tuple) per VIEW_CHANGE.
        self.configs: Dict[int, List[Tuple[int, Tuple[Endpoint, ...]]]] = {}
        #: Slots that observed their own eviction (KICKED).
        self.kicked: List[int] = []
        self.shaper: Optional[LinkShaper] = None

    # -- construction ---------------------------------------------------

    def attach_shaper(self, rng: random.Random) -> LinkShaper:
        self.shaper = LinkShaper(rng, self.clock)
        self.network.shaper = self.shaper
        return self.shaper

    def node_clock(self, slot: int) -> NodeClock:
        if slot not in self.node_clocks:
            self.node_clocks[slot] = NodeClock(self.clock)
        return self.node_clocks[slot]

    def _node_id(self, slot: int) -> Optional[NodeId]:
        """Deterministic per-(slot, incarnation) identity — a restarted slot
        is a NEW identity (the protocol rejects reuse), still derived purely
        from the seed."""
        if self.id_seed is None:
            return None
        incarnation = self._incarnation.get(slot, 0)
        rng = random.Random(f"node-id:{self.id_seed}:{slot}:{incarnation}")
        return NodeId(high=rng.getrandbits(64), low=rng.getrandbits(64))

    def _subscriptions(self, slot: int) -> Dict[ClusterEvents, List]:
        self.configs.setdefault(slot, [])

        def on_view(change) -> None:
            self.configs[slot].append(
                (change.configuration_id, tuple(change.membership))
            )

        def on_kicked(_change) -> None:
            self.kicked.append(slot)

        return {
            ClusterEvents.VIEW_CHANGE: [on_view],
            ClusterEvents.KICKED: [on_kicked],
        }

    async def _drive(self, *tasks: asyncio.Future) -> None:
        """Pump the shared clock until every task completes."""
        while not all(t.done() for t in tasks):
            await self.advance(200)
        for t in tasks:
            t.result()  # surface failures here, not as pending warnings

    async def advance(self, total_ms: float, step_ms: float = 50) -> None:
        advanced = 0.0
        while advanced < total_ms:
            self.clock.advance_ms(step_ms)
            advanced += step_ms
            await _drain()

    async def bootstrap(self, n0: int) -> None:
        self.clusters[0] = await Cluster.start(
            self.endpoints[0], settings=self.settings, network=self.network,
            fd_factory=self.fd, clock=self.node_clock(0),
            rng=random.Random(0), subscriptions=self._subscriptions(0),
            node_id=self._node_id(0),
        )
        self.live_ids = {0}
        for i in range(1, n0):
            await self.join_one(i)
        assert all(c.membership_size == n0 for c in self.clusters.values())
        self.clusters[0].register_subscription(
            ClusterEvents.VIEW_CHANGE,
            lambda change: self.cuts.append(
                frozenset(
                    (sc.endpoint, sc.status) for sc in change.status_changes
                )
            ),
        )

    async def join_one(self, slot: int) -> None:
        task = asyncio.ensure_future(
            Cluster.join(self.endpoints[0], self.endpoints[slot],
                         settings=self.settings, network=self.network,
                         fd_factory=self.fd, clock=self.node_clock(slot),
                         rng=random.Random(slot),
                         subscriptions=self._subscriptions(slot),
                         node_id=self._node_id(slot))
        )
        await self._drive(task)
        self.clusters[slot] = task.result()
        self.live_ids.add(slot)

    async def join_wave(self, slots: Sequence[int]) -> None:
        """Concurrent joins through the seed — one thundering batch."""
        tasks = [
            asyncio.ensure_future(
                Cluster.join(self.endpoints[0], self.endpoints[s],
                             settings=self.settings, network=self.network,
                             fd_factory=self.fd, clock=self.node_clock(s),
                             rng=random.Random(s),
                             subscriptions=self._subscriptions(s),
                             node_id=self._node_id(s))
            )
            for s in slots
        ]
        await self._drive(*tasks)
        for s, t in zip(slots, tasks):
            self.clusters[s] = t.result()
        self.live_ids |= set(slots)

    # -- fault primitives (the InProcessNetwork / clock seams) ----------

    def crash(self, slots: Sequence[int]) -> None:
        for s in slots:
            self.network.blackholed.add(self.endpoints[s])
        self.fd.add_failed_nodes([self.endpoints[s] for s in slots])
        self.live_ids -= set(slots)

    async def restart(self, slot: int) -> None:
        """A removed slot rejoins at the same endpoint as a fresh incarnation
        (new identity — the protocol rejects UUID reuse, so this is how a
        real restarted process returns)."""
        old = self.clusters.pop(slot, None)
        if old is not None:
            await old.shutdown()
        endpoint = self.endpoints[slot]
        self.network.blackholed.discard(endpoint)
        self.network.blackholed_links = {
            link for link in self.network.blackholed_links if endpoint not in link
        }
        self.fd.blacklist.discard(endpoint)
        self._incarnation[slot] = self._incarnation.get(slot, 0) + 1
        await self.join_one(slot)

    async def leave(self, slot: int) -> None:
        task = asyncio.ensure_future(self.clusters[slot].leave_gracefully())
        await self._drive(task)
        self.live_ids -= {slot}

    def partition_one_way(self, victim: int) -> None:
        """Everything INTO the victim drops (it can still send); its
        observers lose probe responses, so detection fires."""
        for i in self.clusters:
            if i != victim:
                self.network.blackholed_links.add(
                    (self.endpoints[i], self.endpoints[victim])
                )
        self.fd.add_failed_nodes([self.endpoints[victim]])
        self.live_ids -= {victim}

    def partition(self, slots: Sequence[int]) -> None:
        """Symmetric isolation of ``slots`` from the rest — a pure network
        fault: detection does NOT fire (the members remain in every view)
        and the set can still talk among itself."""
        inside = set(slots)
        for s in inside:
            for o in range(len(self.endpoints)):
                if o not in inside:
                    self.network.blackholed_links.add(
                        (self.endpoints[o], self.endpoints[s])
                    )
                    self.network.blackholed_links.add(
                        (self.endpoints[s], self.endpoints[o])
                    )
        self.blocked_slots |= inside

    def ingress_block(self, slots: Sequence[int]) -> None:
        """One-way isolation: all links INTO each slot drop; its egress
        stays open, so its alerts still reach the cluster and its config
        pulls work through the partition (requests out, responses back on
        the same call). Detection does not fire — the member stays in every
        view and re-joins each configuration by pulling."""
        for s in slots:
            for o in range(len(self.endpoints)):
                if o != s:
                    self.network.blackholed_links.add(
                        (self.endpoints[o], self.endpoints[s])
                    )

    def heal_partitions(self) -> None:
        self.network.blackholed_links.clear()
        self.blocked_slots.clear()

    def block_link(self, src: int, dst: int) -> None:
        self.network.blackholed_links.add((self.endpoints[src], self.endpoints[dst]))

    def heal_link(self, src: int, dst: int) -> None:
        self.network.blackholed_links.discard(
            (self.endpoints[src], self.endpoints[dst])
        )

    def drop_first_n(self, slot: int, message: str, count: int) -> None:
        server = self.network.servers[self.endpoints[slot]]
        server.drop_interceptors.append(
            ServerDropFirstN(DROPPABLE_MESSAGES[message], count)
        )

    # -- adversarial primitives (Byzantine observers, committee crash) --

    async def false_alert(
        self, liar: int, subject: int, rings: Sequence[int], status: str = "DOWN"
    ) -> None:
        """Slot ``liar`` broadcasts edge reports it never observed about
        ``subject``, claiming the given ring numbers — the hostile half of
        the paper's flaky-observer stability story (sim/faults.py
        ``false_alert``). The lie rides the real alert machinery (batching,
        broadcast, redelivery) via the service's Byzantine seam."""
        await self.clusters[liar].service.inject_byzantine_alert(
            self.endpoints[subject],
            EdgeStatus.DOWN if status == "DOWN" else EdgeStatus.UP,
            rings,
        )

    async def alert_storm(
        self, liars: Sequence[int], subject: int, rings: Sequence[int],
        status: str = "DOWN",
    ) -> None:
        """Simultaneous collusion: the claimed rings are distributed
        round-robin across the liars, so the RECEIVER-side cumulative tally
        is identical to one liar claiming them all — but the reports arrive
        from distinct senders in distinct batches (exercising per-ring
        dedup across senders)."""
        liars = list(liars)
        for j, liar in enumerate(liars):
            share = [r for i, r in enumerate(rings) if i % len(liars) == j]
            if share:
                await self.false_alert(liar, subject, share, status)

    def arm_committee_crash(self, victim: int) -> None:
        """Crash ``victim`` the instant the first CohortCutMessage hits any
        server: the window between cohort-cut forwarding and the global
        decision — the hier reconfiguration gap of arXiv:1906.01365. The
        tripwire fires synchronously before the triggering message is
        handled, so a victim that was the recipient loses the message with
        the process."""

        def fire() -> None:
            if victim in self.live_ids:
                self.crash([victim])

        self.network.tripwires.append(RequestTripwire(CohortCutMessage, fire))

    # -- convergence ----------------------------------------------------

    def _agreeing(self, expected: int, include_blocked: bool) -> bool:
        ids = self.live_ids if include_blocked else self.live_ids - self.blocked_slots
        live = [self.clusters[i] for i in ids]
        if not all(c.membership_size == expected for c in live):
            return False
        return len({tuple(c.membership) for c in live}) == 1

    async def try_converge(
        self, expected: int, budget_ms: float, include_blocked: bool = True
    ) -> Optional[float]:
        """Advance simulated time until every (reachable) live node holds
        the identical ``expected``-member view; returns the simulated ms it
        took, or None if the budget ran out."""
        start = self.clock.now_ms()
        while self.clock.now_ms() - start < budget_ms:
            if self._agreeing(expected, include_blocked):
                return self.clock.now_ms() - start
            await self.advance(400)
        return self.clock.now_ms() - start if self._agreeing(expected, include_blocked) else None

    async def converge_members(self, expected: int, budget_ms: float = 12_000) -> None:
        """Raise-on-timeout convergence (the bespoke-scenario tests' form)."""
        elapsed = await self.try_converge(
            expected, budget_ms, include_blocked=False
        )
        if elapsed is None:
            raise AssertionError(
                f"did not converge to {expected}: "
                f"{[self.clusters[i].membership_size for i in sorted(self.live_ids)]}"
            )

    # -- teardown -------------------------------------------------------

    async def shutdown(self) -> set:
        for nc in self.node_clocks.values():
            nc.resume()  # a paused node must not hang its own teardown
        final = set(self.clusters[0].membership) if 0 in self.clusters else set()
        await asyncio.gather(
            *(c.shutdown() for c in self.clusters.values()),
            return_exceptions=True,
        )
        return final


@dataclass
class RunResult:
    """Everything a repro or an oracle needs from one simulated run."""

    schedule: FaultSchedule
    endpoints: List[Endpoint]
    cuts: List[frozenset]
    configs: Dict[int, List[Tuple[int, Tuple[Endpoint, ...]]]]
    kicked: List[int]
    final_membership: set
    live_slots: List[int]
    expected_members: int
    phase_converged: List[bool]
    final_converged: bool
    final_converge_sim_ms: Optional[float]
    aborted_at_event: Optional[int]
    faultlog: List[dict]
    shaper_stats: Dict[str, int]
    snapshots: Dict[int, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "schedule": self.schedule.to_dict(),
            "endpoints": [str(e) for e in self.endpoints],
            "cuts": [
                sorted([str(ep), status.name] for ep, status in cut)
                for cut in self.cuts
            ],
            "configs": {
                str(slot): [
                    {"config_id": cid, "membership": [str(m) for m in members]}
                    for cid, members in history
                ]
                for slot, history in self.configs.items()
            },
            "kicked": sorted(self.kicked),
            "final_membership": sorted(str(e) for e in self.final_membership),
            "live_slots": sorted(self.live_slots),
            "expected_members": self.expected_members,
            "phase_converged": self.phase_converged,
            "final_converged": self.final_converged,
            "final_converge_sim_ms": self.final_converge_sim_ms,
            "aborted_at_event": self.aborted_at_event,
            "shaper_stats": self.shaper_stats,
        }

    def write_repro(self, directory) -> Path:
        """Write the replayable artifact set: the schedule (the repro
        itself), the outcome, the fault log, and one telemetry snapshot per
        node (flight recordings included) for ``tools/traceview.py``."""
        directory = Path(directory)
        (directory / "nodes").mkdir(parents=True, exist_ok=True)
        (directory / "schedule.json").write_text(self.schedule.to_json())
        (directory / "result.json").write_text(
            json.dumps(self.to_dict(), indent=1) + "\n"
        )
        (directory / "faultlog.json").write_text(
            json.dumps(self.faultlog, indent=1) + "\n"
        )
        for slot, snapshot in self.snapshots.items():
            (directory / "nodes" / f"slot{slot:03d}.json").write_text(
                json.dumps(snapshot, indent=1) + "\n"
            )
        return directory


def endpoints_for(seed: int, n_slots: int) -> List[Endpoint]:
    """THE slot->endpoint derivation for generated scenarios — one
    definition, shared by the runner and by family generators that need to
    reason about endpoint-dependent structure (the hierarchical families
    compute the cohort map of the initial cluster to pick delegates and
    cross-cohort links deterministically)."""
    return [
        Endpoint(f"10.83.{seed % 250}.{i % 250}", 7800 + i)
        for i in range(n_slots)
    ]


def sim_settings() -> Settings:
    """The chaos-simulation settings profile: reference protocol defaults,
    with the anti-entropy idle pull fast enough that members healed out of a
    symmetric partition re-join the configuration within a few simulated
    seconds (the 30 s production default would dominate every scenario's
    convergence tail; see settings.py on why the pull is the ONLY channel
    that reaches an evidence-free partition survivor)."""
    settings = Settings()
    settings.config_sync_idle_interval_ms = 2_000
    return settings


#: Cohort size for hierarchical simulation profiles: 4 over the shared
#: 8-member geometry gives exactly two cohorts — the smallest topology where
#: the global reconfiguration tier does real work (cross-cohort stitching,
#: delegate failover) while every cohort stays big enough to self-detect.
HIER_SIM_COHORT_SIZE = 4


def hier_sim_settings() -> Settings:
    """The chaos settings profile for two-level hierarchical membership
    (rapid_tpu/hier): the flat sim profile plus cohort mode."""
    settings = sim_settings()
    settings.hier_target_cohort_size = HIER_SIM_COHORT_SIZE
    return settings


class ScenarioRunner:
    """Interpret a :class:`FaultSchedule` over a fresh simulated cluster."""

    def __init__(
        self,
        schedule: FaultSchedule,
        settings: Optional[Settings] = None,
        wall_timeout_s: float = 300.0,
    ) -> None:
        schedule.validate()
        self.schedule = schedule
        if settings is not None:
            self.settings = settings
        elif schedule.profile == "hier":
            self.settings = hier_sim_settings()
        else:
            self.settings = sim_settings()
        self.wall_timeout_s = wall_timeout_s

    def endpoints(self) -> List[Endpoint]:
        return endpoints_for(self.schedule.seed, self.schedule.n_slots)

    def run(self) -> RunResult:
        async def with_timeout() -> RunResult:
            return await asyncio.wait_for(self._run(), timeout=self.wall_timeout_s)

        return asyncio.run(with_timeout())

    async def _run(self) -> RunResult:
        s = self.schedule
        harness = SimHarness(
            self.endpoints(), settings=self.settings, id_seed=s.seed
        )
        harness.attach_shaper(schedule_rng(s))
        await harness.bootstrap(s.n0)

        expected = s.n0
        phase_converged: List[bool] = []
        faultlog: List[dict] = []
        aborted_at: Optional[int] = None
        overlap_pending = 0  # unsettled membership events awaiting a settle
        # Which false_alert/alert_storm events cross H (and therefore evict
        # their subject) — precomputed once so the runner, the schedule's
        # expected-membership accounting, and the oracles share the single
        # cumulative-ring definition in faults.py.
        crossings = s.adversarial_crossings()

        for i, event in enumerate(s.events):
            faultlog.append(
                {"t_ms": harness.clock.now_ms(), **event.to_dict()}
            )
            try:
                expected += await self._apply(harness, event)
            except (JoinError, AssertionError):
                # A join that cannot complete under the injected faults (or
                # a lifecycle assertion) ends the run: the oracles judge
                # what the cluster reached, not what it never attempted.
                aborted_at = i
                break
            if i in crossings or event.kind == "committee_crash":
                # A past-H lie evicts its healthy subject; an armed
                # committee crash removes its victim once tripped. Both
                # change the expected membership like any schedule fault.
                expected -= 1
            if (
                event.kind in MEMBERSHIP_KINDS
                or i in crossings
                or event.kind == "committee_crash"
            ):
                if not event.settle:
                    overlap_pending += 1
                    # The dwell is the overlap window: how much simulated
                    # time passes before the NEXT event lands on top.
                    if event.dwell_ms:
                        await harness.advance(event.dwell_ms)
                    continue
                overlap_pending = 0
                elapsed = await harness.try_converge(
                    expected, s.phase_budget_ms, include_blocked=False
                )
                phase_converged.append(elapsed is not None)
                if elapsed is None:
                    aborted_at = i
                    break
            if event.dwell_ms:
                await harness.advance(event.dwell_ms)

        if overlap_pending and aborted_at is None:
            # Defensive: validate() rejects trailing non-settled events, so
            # an overlapped group is always closed by a settling event.
            phase_converged.append(
                await harness.try_converge(
                    expected, s.phase_budget_ms, include_blocked=False
                )
                is not None
            )

        # Final convergence: EVERY live node — including partition survivors
        # that must catch up — inside the schedule's bound. This is what the
        # bounded-convergence oracle asserts.
        final_ms = await harness.try_converge(
            expected, s.converge_budget_ms, include_blocked=True
        )

        snapshots = {
            slot: cluster.telemetry_snapshot()
            for slot, cluster in harness.clusters.items()
        }
        live_slots = sorted(harness.live_ids)
        shaper = harness.shaper
        cuts = list(harness.cuts)
        configs = {k: list(v) for k, v in harness.configs.items()}
        kicked = list(harness.kicked)
        final = await harness.shutdown()
        return RunResult(
            schedule=s,
            endpoints=harness.endpoints,
            cuts=cuts,
            configs=configs,
            kicked=kicked,
            final_membership=final,
            live_slots=live_slots,
            expected_members=expected,
            phase_converged=phase_converged,
            final_converged=final_ms is not None,
            final_converge_sim_ms=final_ms,
            aborted_at_event=aborted_at,
            faultlog=faultlog,
            shaper_stats={
                "dropped": shaper.dropped if shaper else 0,
                "delayed": shaper.delayed if shaper else 0,
                "duplicated": shaper.duplicated if shaper else 0,
                "asym_dropped": shaper.asym_dropped if shaper else 0,
                "asym_delayed": shaper.asym_delayed if shaper else 0,
            },
            snapshots=snapshots,
        )

    async def _apply(self, harness: SimHarness, event: FaultEvent) -> int:
        """Apply one event; returns the expected-membership delta."""
        kind, slots, args = event.kind, list(event.slots), event.args
        if kind == "crash":
            harness.crash(slots)
            return -len(slots)
        if kind == "join":
            await harness.join_wave(slots)
            return len(slots)
        if kind == "restart":
            for s in slots:
                await harness.restart(s)
            return len(slots)
        if kind == "leave":
            await harness.leave(slots[0])
            return -1
        if kind == "partition_oneway":
            harness.partition_one_way(slots[0])
            return -1
        if kind == "false_alert":
            await harness.false_alert(
                slots[0], int(args["subject"]),
                [int(r) for r in args["rings"]],  # type: ignore[union-attr]
                str(args.get("status", "DOWN")),
            )
            return 0  # the H-crossing delta is the run loop's (cumulative)
        if kind == "alert_storm":
            await harness.alert_storm(
                slots, int(args["subject"]),
                [int(r) for r in args["rings"]],  # type: ignore[union-attr]
                str(args.get("status", "DOWN")),
            )
            return 0
        if kind == "committee_crash":
            harness.arm_committee_crash(slots[0])
            return 0  # armed, not yet crashed; the run loop expects -1
        if kind == "partition":
            harness.partition(slots)
        elif kind == "ingress_block":
            harness.ingress_block(slots)
        elif kind == "heal_partitions":
            harness.heal_partitions()
        elif kind == "link_block":
            harness.block_link(int(args["src"]), int(args["dst"]))
        elif kind == "link_heal":
            harness.heal_link(int(args["src"]), int(args["dst"]))
        elif kind == "loss":
            assert harness.shaper is not None
            harness.shaper.loss_permille = int(args["permille"])
        elif kind == "delay":
            assert harness.shaper is not None
            harness.shaper.delay_min_ms = float(args.get("min_ms", 0.0))
            harness.shaper.delay_max_ms = float(args["max_ms"])
        elif kind == "duplicate":
            assert harness.shaper is not None
            harness.shaper.dup_permille = int(args["permille"])
        elif kind == "wan_asym":
            assert harness.shaper is not None
            harness.shaper.asym_group = {harness.endpoints[s] for s in slots}
            harness.shaper.asym_loss_permille = int(args.get("loss_permille", 0))
            harness.shaper.asym_delay_min_ms = float(args.get("delay_min_ms", 0.0))
            harness.shaper.asym_delay_max_ms = float(args.get("delay_max_ms", 0.0))
        elif kind == "drop_first_n":
            harness.drop_first_n(slots[0], str(args["message"]), int(args["count"]))
        elif kind == "clock_skew":
            harness.node_clock(slots[0]).set_skew(float(args["offset_ms"]))
        elif kind == "clock_pause":
            harness.node_clock(slots[0]).pause()
        elif kind == "clock_resume":
            harness.node_clock(slots[0]).resume()
        return 0
