"""Invariant oracles executed after every simulated run.

Each oracle inspects a :class:`~rapid_tpu.sim.scenario.RunResult` and
returns zero or more :class:`Violation` records. The set encodes the
protocol's safety and liveness claims (paper §3, §5):

- ``chain-consistency`` — no split-brain: the configuration chain is single.
  Node 0 (never faulted, participates in every decision) delivers the full
  chain; every other node's delivered configuration history must be an
  ordered subsequence of it (catch-up may legitimately SKIP configurations —
  a partition survivor pulls the latest — but may never interleave a
  configuration node 0 never had, i.e. a fork), and any two nodes that
  deliver the same configuration id must agree on its membership.
- ``monotonicity`` — no node ever re-delivers a configuration id: the chain
  only advances (the UUID/identifier-history discipline).
- ``agreement`` — strong consistency at rest: all live nodes end on the
  identical (configuration id, membership).
- ``membership-outcome`` — the final membership is exactly the schedule's
  surviving slots, and only slots the schedule removed were evicted
  (a KICKED on any other node is a false eviction).
- ``stability`` — the flaky/hostile-observer claim (paper §4.2, pushed to
  observers that LIE): a never-crashed subject whose cumulative false-report
  count stayed below H must never be evicted — in any cut, not just the
  final membership; past-H false reports may evict, but the wrong cut must
  still be one agreed, chain-consistent decision (the other oracles enforce
  that half once the schedule accounting counts the subject as removed).
- ``bounded-convergence`` — after the last fault heals, every live node
  reaches the final configuration within the schedule's simulated-time
  budget.
- ``differential`` — the host<->device oracle: the identical fault schedule
  replayed through the jitted engine (``models/virtual_cluster.py``) must
  produce a cut sequence the host's refines, and the identical final
  membership — the cross-stack scenario oracle of test_oracle_parity.py,
  lifted into a reusable checker.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Set, Tuple

from rapid_tpu.sim.faults import WATERMARK_H, FaultSchedule
from rapid_tpu.sim.scenario import RunResult
from rapid_tpu.types import EdgeStatus, Endpoint


@dataclass(frozen=True)
class Violation:
    oracle: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.oracle}] {self.detail}"


# ---------------------------------------------------------------------------
# chain / agreement / eviction oracles (host-only)
# ---------------------------------------------------------------------------


def check_chain_consistency(result: RunResult) -> List[Violation]:
    violations: List[Violation] = []
    reference = [cid for cid, _ in result.configs.get(0, [])]
    ref_index = {cid: i for i, cid in enumerate(reference)}
    membership_of: Dict[int, Tuple[Endpoint, ...]] = {}
    for slot, history in sorted(result.configs.items()):
        for cid, members in history:
            seen = membership_of.setdefault(cid, members)
            if set(seen) != set(members):
                violations.append(Violation(
                    "chain-consistency",
                    f"configuration {cid:#x} has two memberships: slot {slot} "
                    f"delivered {sorted(map(str, members))}, another node "
                    f"{sorted(map(str, seen))}",
                ))
        if slot == 0:
            continue
        positions = [ref_index.get(cid) for cid, _ in history]
        unknown = [f"{cid:#x}" for (cid, _), p in zip(history, positions) if p is None]
        if unknown:
            violations.append(Violation(
                "chain-consistency",
                f"slot {slot} delivered configurations the reference chain "
                f"(node 0) never had — a fork: {unknown}",
            ))
            continue
        if any(b <= a for a, b in zip(positions, positions[1:])):
            violations.append(Violation(
                "chain-consistency",
                f"slot {slot}'s configuration history is not an ordered "
                f"subsequence of the reference chain: positions {positions}",
            ))
    return violations


def check_monotonicity(result: RunResult) -> List[Violation]:
    violations: List[Violation] = []
    for slot, history in sorted(result.configs.items()):
        ids = [cid for cid, _ in history]
        if len(set(ids)) != len(ids):
            repeated = sorted({f"{c:#x}" for c in ids if ids.count(c) > 1})
            violations.append(Violation(
                "monotonicity",
                f"slot {slot} re-delivered configuration id(s) {repeated}",
            ))
    return violations


def check_agreement(result: RunResult) -> List[Violation]:
    finals = {}
    for slot in result.live_slots:
        history = result.configs.get(slot, [])
        if not history:
            return [Violation("agreement", f"slot {slot} has no delivered configuration")]
        cid, members = history[-1]
        finals[slot] = (cid, frozenset(members))
    if len(set(finals.values())) > 1:
        lines = ", ".join(
            f"slot {s}: cfg={cid:#x} n={len(m)}" for s, (cid, m) in sorted(finals.items())
        )
        return [Violation("agreement", f"live nodes disagree at rest: {lines}")]
    return []


def check_membership_outcome(result: RunResult) -> List[Violation]:
    violations: List[Violation] = []
    s = result.schedule
    joined: Set[int] = set(range(s.n0))
    for event in s.events:
        if event.kind in ("join", "restart"):
            joined |= set(event.slots)
    expected_slots = joined - s.expected_removed_slots()
    expected = {result.endpoints[i] for i in sorted(expected_slots)}
    if result.final_membership != expected:
        violations.append(Violation(
            "membership-outcome",
            f"final membership {sorted(map(str, result.final_membership))} != "
            f"schedule's surviving slots {sorted(map(str, expected))}",
        ))
    # KICKED legitimacy is judged against ever-removed, not final-removed: a
    # restarted slot's previous incarnation may rightly discover its own
    # eviction after the fresh incarnation already rejoined.
    false_evictions = set(result.kicked) - s.ever_removed_slots()
    if false_evictions:
        violations.append(Violation(
            "membership-outcome",
            f"healthy slots evicted (KICKED): {sorted(false_evictions)} — "
            "only schedule-removed slots may be kicked",
        ))
    return violations


def check_stability(result: RunResult) -> List[Violation]:
    """The paper's stability claim, extended to HOSTILE observers (the half
    the reference's evaluation never tests): a never-crashed subject whose
    cumulative FALSE-report count stayed below the H watermark must never
    be evicted — not in the final membership (membership-outcome covers
    that) and not in ANY intermediate cut or KICKED signal (this oracle's
    addition: a transient wrongful eviction would slip past an
    outcome-only check). False alerts pushed past H MAY evict — the
    adversary can buy a wrong cut — but the schedule accounting then counts
    the subject as removed, so chain-consistency, agreement, and
    membership-outcome still enforce that the wrong cut is ONE agreed,
    chain-consistent decision."""
    s = result.schedule
    lied_about = {
        int(e.args["subject"])  # type: ignore[arg-type]
        for e in s.events
        if e.kind in ("false_alert", "alert_storm")
    }
    if not lied_about:
        return []
    crossed = {sub for sub, _ in s.adversarial_crossings().values()}
    # Subjects also removed by HONEST schedule events (crash/leave/...) are
    # legitimately evicted regardless of the lies; judge only the rest.
    honestly_removed = {
        slot
        for e in s.events
        if e.kind in ("crash", "leave", "partition_oneway", "committee_crash")
        for slot in e.slots
    }
    protected = lied_about - crossed - honestly_removed
    violations: List[Violation] = []
    for subject in sorted(protected):
        endpoint = result.endpoints[subject]
        for i, cut in enumerate(result.cuts):
            if (endpoint, EdgeStatus.DOWN) in cut:
                violations.append(Violation(
                    "stability",
                    f"slot {subject} was cut DOWN (cut {i}) although its "
                    f"false-report count stayed below H={WATERMARK_H} and it "
                    f"never failed — sub-H reports must delay, not trigger, "
                    f"a view change",
                ))
                break
        if subject in result.kicked:
            violations.append(Violation(
                "stability",
                f"slot {subject} observed its own eviction (KICKED) although "
                f"its false-report count stayed below H={WATERMARK_H}",
            ))
    return violations


def check_bounded_convergence(result: RunResult) -> List[Violation]:
    if result.aborted_at_event is not None:
        return [Violation(
            "bounded-convergence",
            f"run aborted at event {result.aborted_at_event}: a membership "
            f"phase did not converge within its budget",
        )]
    if not result.final_converged:
        return [Violation(
            "bounded-convergence",
            f"live nodes did not reach one view within "
            f"{result.schedule.converge_budget_ms:.0f} simulated ms of the "
            f"schedule's end",
        )]
    return []


# ---------------------------------------------------------------------------
# differential host<->device oracle
# ---------------------------------------------------------------------------


def cuts_refine(fine_cuts: Sequence[Set], coarse_groups: Sequence[Sequence[frozenset]]):
    """None when ``fine_cuts`` is a refinement of ``coarse_groups``, else a
    human-readable mismatch description.

    Refinement: the fine sequence partitions each coarse group's union into
    consecutive sub-cuts — it may split a cut the coarser observer commits
    whole (sub-interval alert timing), but may never produce an element
    outside the current group's union, reorder across groups, or leave a
    group's union unreached. Strict equality is the degenerate refinement
    (each group one cut, each fine cut the whole union) — which is how the
    2-D mesh parity tests reuse this as their comparator: a bit-identical
    engine must refine in BOTH directions. THE definition shared by
    ``check_differential`` (host run vs engine replay) and
    ``tests/test_parallel_2d.py`` (sharded engine vs single-device engine).
    """
    fine = [set(c) for c in fine_cuts]
    i = 0
    for group in coarse_groups:
        target = set().union(*group) if group else set()
        acc: set = set()
        while acc != target:
            if i >= len(fine) or not fine[i] <= target:
                return (
                    f"cut sequence does not refine the reference: "
                    f"fine={fine_cuts} coarse={coarse_groups}"
                )
            acc |= fine[i]
            i += 1
    if i != len(fine):
        return (
            f"cut sequence has cuts beyond the reference's: "
            f"fine={fine_cuts} coarse={coarse_groups}"
        )
    return None


def inject_engine_event(vc, event) -> int:
    """Apply one membership-phase event to an engine cluster and return its
    expected-membership delta — THE host-event -> engine-seam mapping,
    shared by the differential replay below and the tenancy chaos compiler
    (rapid_tpu/tenancy/chaos.py), so the two can never diverge on what a
    schedule means at the engine grain:

    - ``join``/``leave`` — the engine's own injection seams;
    - ``crash``/``partition_oneway``/``committee_crash`` — detector-identical
      crash-stops (the engine has no committee; the victim's removal is
      what the membership chain must agree on);
    - ``false_alert``/``alert_storm`` (H-crossing, normalized by
      ``membership_phases`` to carry the cumulative ring set) — per-(subject,
      ring) probe failures (``set_flaky_edges``): the engine's observers of
      those rings report DOWN about the healthy subject, the exact tally the
      host's lying broadcast produces."""
    import numpy as np

    kind, slots, args = event.kind, list(event.slots), event.args
    if kind == "join":
        vc.inject_join_wave(slots)
        return len(slots)
    if kind == "leave":
        vc.initiate_leave(slots)
        return -len(slots)
    if kind in ("false_alert", "alert_storm"):
        subject = int(args["subject"])
        rings = [int(r) for r in args["rings"]]
        probe = np.array(vc.faults.probe_fail, dtype=bool)
        probe[subject, rings] = True
        vc.set_flaky_edges(probe)
        return -1  # only H-crossing lies appear in phase groups
    # crash / partition_oneway / committee_crash are detector-identical.
    vc.crash(slots)
    return -len(slots)


def replay_through_engine(
    schedule: FaultSchedule, endpoints: Sequence[Endpoint]
) -> Tuple[List[List[frozenset]], Set[Endpoint]]:
    """Replay the schedule's membership phases through the fused device
    engine (same ring topology as the host view, matched FD/batching
    semantics: one engine round = one detector interval, fd_threshold=1 for
    the host's static detector, delivery_spread=0 for the in-process
    transport's same-window delivery). Returns (cuts per phase group, final
    membership). Environment-only faults (loss, delay, symmetric partitions
    that heal) change no membership and are not replayed — by the protocol's
    own claim they must not affect WHAT is decided, only when, which is
    exactly what comparing against this replay verifies."""
    import numpy as np

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    if not schedule.engine_compatible:
        raise ValueError("schedule contains restarts (engine slots are spent forever)")
    vc = VirtualCluster.from_endpoints(
        list(endpoints), n_slots=len(endpoints), n_members=schedule.n0,
        k=10, h=9, l=4, fd_threshold=1, delivery_spread=0,
    )
    groups: List[List[frozenset]] = []
    expected = schedule.n0
    for group in schedule.membership_phases():
        for event in group:
            expected += inject_engine_event(vc, event)
        cuts: List[frozenset] = []
        # One decision per injected event at most; overlapped groups may
        # resolve in fewer cuts (one combined decision) or one per event.
        for _ in range(len(group) + 1):
            was_alive = np.asarray(vc.state.alive)
            rounds, decided, winner, n_members = vc.run_to_decision(max_steps=48)
            if not decided:
                raise AssertionError(
                    f"engine did not decide for phase group {group}"
                )
            mask = np.asarray(winner)
            cuts.append(frozenset(
                (
                    endpoints[s],
                    EdgeStatus.DOWN if was_alive[s] else EdgeStatus.UP,
                )
                for s in np.nonzero(mask)[0].tolist()
            ))
            if n_members == expected:
                break
        else:
            raise AssertionError(f"phase group {group} never reached {expected}")
        groups.append(cuts)
    alive = np.asarray(vc.state.alive)
    final = {endpoints[s] for s in np.nonzero(alive)[0].tolist()}
    return groups, final


def check_differential(result: RunResult) -> List[Violation]:
    """The host run's cut sequence must refine the engine replay's, group by
    group, and the final memberships must match. Refinement (not strict
    per-cut equality): within one multi-node phase the host's sub-interval
    alert timing can split a cut the round-granular engine commits whole —
    the almost-everywhere-agreement batching artifact test_oracle_parity.py
    documents. Skipped (empty result) when the run did not converge — the
    convergence oracles already own that failure — or when the schedule is
    not engine-replayable (restarts)."""
    if not result.final_converged or result.aborted_at_event is not None:
        return []
    if not result.schedule.engine_compatible:
        return []
    try:
        engine_groups, engine_final = replay_through_engine(
            result.schedule, result.endpoints
        )
    except AssertionError as exc:
        return [Violation("differential", f"engine replay failed: {exc}")]
    if engine_final != result.final_membership:
        return [Violation(
            "differential",
            f"final membership diverged: host "
            f"{sorted(map(str, result.final_membership))} vs engine "
            f"{sorted(map(str, engine_final))}",
        )]
    mismatch = cuts_refine(result.cuts, engine_groups)
    if mismatch is not None:
        return [Violation("differential", f"host vs engine: {mismatch}")]
    return []


# ---------------------------------------------------------------------------
# the full battery
# ---------------------------------------------------------------------------

HOST_ORACLES = (
    check_chain_consistency,
    check_monotonicity,
    check_agreement,
    check_membership_outcome,
    check_stability,
    check_bounded_convergence,
)


def check_all(result: RunResult, differential: bool = True) -> List[Violation]:
    """Run every oracle; returns all violations (empty = the run upheld
    every invariant). ``differential=False`` skips the engine replay (used
    by shrink loops, which re-verify the surviving violation set against
    the full battery at the end)."""
    violations: List[Violation] = []
    for oracle in HOST_ORACLES:
        violations.extend(oracle(result))
    if differential:
        violations.extend(check_differential(result))
    return violations
