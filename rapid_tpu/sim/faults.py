"""Declarative, serializable fault schedules over the in-process seams.

A :class:`FaultSchedule` is an ordered list of :class:`FaultEvent` records —
JSON-serializable, validated, and *slot-indexed* (slots are indices into the
scenario's endpoint table, the same indexing the device engine uses), so one
schedule drives both the asyncio stack and the jitted engine. Every source
of nondeterminism is seeded: the statistical link faults draw from one
``random.Random(seed)``, clock faults act on per-node
:class:`~rapid_tpu.utils.clock.NodeClock` wrappers over the scenario's one
``ManualClock``, and the runner applies events in schedule order — a whole
run is a pure function of the schedule.

Event vocabulary (reference seams in parentheses):

==================  ========================================================
kind                 semantics
==================  ========================================================
``crash``            crash-stop the slots: blackholed + failure detectors
                     observe it (``StaticFailureDetector`` blacklist — the
                     reference's fault fixture, StaticFailureDetector.java)
``restart``          a previously removed slot rejoins at the same endpoint
                     with a fresh identity (UUID re-use is rejected by the
                     protocol, so a restart is a new incarnation)
``join``             admit fresh slots through the seed (a join wave)
``leave``            one slot departs gracefully (LeaveMessage path)
``partition_oneway`` all ingress INTO the victim drops; it still sends.
                     Observers lose probe responses, so detection fires
                     (the reference's asymmetric-failure scenarios)
``partition``        symmetric isolation of the slot set: links both ways
                     drop, detection does NOT fire (a pure network fault
                     below the detection threshold). The isolated members
                     can neither hear nor be heard — if they gatekeep a
                     concurrent cut, detection can wedge below H until the
                     heal; if never healed, they go stale forever. This is
                     the canonical oracle-violating shape the shrinker
                     regression pins.
``ingress_block``    one-way isolation of each slot in the set: all links
                     INTO it drop, its egress stays open, detection does
                     NOT fire. Its alerts/votes still reach the cluster and
                     its config pulls ride request/response THROUGH the
                     partition (the catch-up shape of the chaos soak)
``heal_partitions``  clear every link-level block
``link_block``       one directional link drops (``blackholed_links`` seam)
``link_heal``        re-open one directional link
``loss``             seeded symmetric message loss, permille, all links
``delay``            seeded per-message delivery delay, uniform in
                     [min_ms, max_ms] of simulated time
``duplicate``        seeded per-message duplication, permille (the server
                     handles the request twice — receiver-side dedup)
``drop_first_n``     drop the first N requests of one type at a slot's
                     server (MessageDropInterceptor.java:24-49 semantics)
``wan_asym``         WAN-shaped asymmetry: messages crossing the boundary
                     between ``slots`` and the rest suffer ADDITIONAL
                     seeded loss (``loss_permille``) and delay
                     (``delay_min_ms``..``delay_max_ms``) on top of any
                     global shaping; intra-group links are untouched (the
                     inter-cohort adverse-network shape of the
                     hierarchical-membership families). Empty slots +
                     zero parameters clears it.
``clock_skew``       shift one slot's clock readings by offset_ms
``clock_pause``      freeze one slot's clock and park its timers (GC pause)
``clock_resume``     thaw a paused clock; parked timers fire late
``false_alert``      a Byzantine observer (``slots[0]``) broadcasts edge
                     reports it never observed about a healthy ``subject``:
                     one alert claiming the given ``rings``. DOWN claims
                     accumulate in every receiver's H/L cut detector; the
                     paper's stability claim is that a cumulative count held
                     in [L, H) DELAYS (never triggers) a view change, while
                     a count pushed past H evicts the healthy subject — but
                     the eviction must still be one agreed, chain-consistent
                     cut. UP claims about a present host are filtered by
                     every receiver (a no-op lie, kept for coverage).
``alert_storm``      K-1-style collusion: every slot in ``slots`` lies
                     simultaneously about ``subject``, the claimed ``rings``
                     distributed round-robin across the liars. Cumulative
                     ring semantics identical to ``false_alert`` (receivers
                     dedup per (subject, ring), so colluders re-claiming
                     the same rings add nothing).
``committee_crash``  arm a tripwire that crash-stops ``slots[0]`` (a
                     hierarchical global-committee member) the instant the
                     first ``CohortCutMessage`` hits any server — i.e.
                     BETWEEN cohort-cut forwarding and the global decision,
                     the hier reconfiguration window (the committee-crash
                     shape of arXiv:1906.01365). Hier-profile only; must be
                     ``settle=False`` (it overlaps the membership event
                     whose reconfiguration trips it).
==================  ========================================================

``dwell_ms`` on every event is how much simulated time the runner advances
after applying it; membership-changing events additionally convergence-wait
(unless ``settle=False``, which overlaps them with the next event — the
crash-during-join shape).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Tuple

from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    BatchedAlertMessage,
    FastRoundPhase2bMessage,
    JoinMessage,
    PreJoinMessage,
    ProbeMessage,
)
from rapid_tpu.utils.clock import Clock

#: The protocol watermarks adversarial schedules are judged against — the
#: reference defaults every sim profile boots with (Settings(); the engine
#: twins compile the same triple in compile_tenant). Schedule-level
#: accounting (does this false-report total evict?) must use ONE definition
#: or the runner, the oracles, and the tenancy compiler would disagree
#: about what a hostile schedule is expected to do. Per-tenant knob
#: overrides in the fleet deliberately diverge from these (the
#: knob/schedule-mismatch repro shape of tests/test_tenancy_chaos.py).
_DEFAULTS = Settings()
WATERMARK_K = _DEFAULTS.k
WATERMARK_H = _DEFAULTS.h
WATERMARK_L = _DEFAULTS.l

#: drop_first_n message-type vocabulary: the serializable names a schedule
#: may target (mirrors the reference interceptor fixtures' targeted types).
#: Lives with the schedule model so validate() can reject a typo'd name
#: instead of letting the runner KeyError mid-scenario.
DROPPABLE_MESSAGES = {
    "prejoin": PreJoinMessage,
    "join": JoinMessage,
    "probe": ProbeMessage,
    "batched_alert": BatchedAlertMessage,
    "fast_round_vote": FastRoundPhase2bMessage,
}

#: Events that change the expected membership (and are therefore replayable
#: through the device engine by the differential oracle).
MEMBERSHIP_KINDS = frozenset({"crash", "restart", "join", "leave", "partition_oneway"})

#: Expected membership delta per slot for each membership kind.
MEMBER_DELTA = {"crash": -1, "restart": +1, "join": +1, "leave": -1, "partition_oneway": -1}

#: Network/clock events: applied instantaneously, never convergence-waited.
ENVIRONMENT_KINDS = frozenset({
    "partition", "ingress_block", "heal_partitions", "link_block", "link_heal",
    "loss", "delay", "duplicate", "drop_first_n", "wan_asym",
    "clock_skew", "clock_pause", "clock_resume",
})

#: Hostile events: observers that LIE (false_alert / alert_storm — their
#: membership effect is conditional on the cumulative false-report count
#: crossing H) and the committee-member crash armed on the hier
#: reconfiguration window (always -1, applied when the tripwire fires).
ADVERSARIAL_KINDS = frozenset({"false_alert", "alert_storm", "committee_crash"})

ALL_KINDS = MEMBERSHIP_KINDS | ENVIRONMENT_KINDS | ADVERSARIAL_KINDS


class LinkPlan(NamedTuple):
    """One message's fate under the shaper."""

    drop: bool
    delay_ms: float
    duplicate: bool


class LinkShaper:
    """Seeded statistical link faults, consulted per in-process send attempt
    (the ``InProcessNetwork.shaper`` seam).

    One ``random.Random`` drives every draw, so given a fixed schedule of
    protocol operations the sequence of drops/delays/duplications is a pure
    function of the seed. Delays hold the message for *simulated* time (the
    scenario's ManualClock), so a delayed message interleaves exactly where
    the schedule says it does, independent of host speed.
    """

    def __init__(self, rng: random.Random, clock: Clock) -> None:
        self._rng = rng
        self._clock = clock
        self.loss_permille = 0
        self.delay_min_ms = 0.0
        self.delay_max_ms = 0.0
        self.dup_permille = 0
        # WAN asymmetry (the ``wan_asym`` event): links CROSSING the
        # boundary between ``asym_group`` and everyone else pay additional
        # loss/delay; intra-group links are untouched.
        self.asym_group: set = set()
        self.asym_loss_permille = 0
        self.asym_delay_min_ms = 0.0
        self.asym_delay_max_ms = 0.0
        # Observability: totals per fate, for artifacts and assertions.
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0
        self.asym_dropped = 0
        self.asym_delayed = 0

    def plan(self, src, dst) -> LinkPlan:
        drop = self.loss_permille > 0 and self._rng.randrange(1000) < self.loss_permille
        if drop:
            self.dropped += 1
            return LinkPlan(True, 0.0, False)
        cross = bool(self.asym_group) and (
            (src in self.asym_group) != (dst in self.asym_group)
        )
        if (
            cross
            and self.asym_loss_permille > 0
            and self._rng.randrange(1000) < self.asym_loss_permille
        ):
            self.dropped += 1
            self.asym_dropped += 1
            return LinkPlan(True, 0.0, False)
        delay = 0.0
        if self.delay_max_ms > 0:
            delay = self._rng.uniform(self.delay_min_ms, self.delay_max_ms)
        if cross and self.asym_delay_max_ms > 0:
            delay += self._rng.uniform(self.asym_delay_min_ms, self.asym_delay_max_ms)
            self.asym_delayed += 1
        if delay > 0:
            self.delayed += 1
        dup = self.dup_permille > 0 and self._rng.randrange(1000) < self.dup_permille
        if dup:
            self.duplicated += 1
        return LinkPlan(False, delay, dup)

    async def hold_ms(self, delay_ms: float) -> None:
        await self._clock.sleep_ms(delay_ms)


class ScheduleError(ValueError):
    """The schedule is ill-formed (unknown kind, slot-lifecycle violation,
    seed-node fault, ...). Raised by :meth:`FaultSchedule.validate`, and at
    :class:`FaultEvent` construction for kinds outside the registered
    vocabulary."""


@dataclass(frozen=True)
class FaultEvent:
    """One schedule entry. ``slots`` carries the subject slot indices (empty
    for global events); ``args`` the kind-specific parameters; ``dwell_ms``
    the simulated time advanced after the event; ``settle=False`` overlaps a
    membership event with the next one instead of convergence-waiting.

    Construction with an unregistered ``kind`` raises immediately: the
    vocabulary (ALL_KINDS), the fuzz FAMILIES table, and the chaosrun CLI
    all index on these strings, and a typo'd kind must fail at the point it
    is minted — never ride silently into a schedule file the runner then
    crashes on mid-scenario (the chaosvocab lint family pins the static
    half of this)."""

    kind: str
    slots: Tuple[int, ...] = ()
    args: Dict[str, object] = field(default_factory=dict)
    dwell_ms: float = 0.0
    settle: bool = True

    def __post_init__(self) -> None:
        if self.kind not in ALL_KINDS:
            raise ScheduleError(
                f"unknown kind {self.kind!r}; registered kinds: "
                f"{sorted(ALL_KINDS)}"
            )

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {"kind": self.kind}
        if self.slots:
            out["slots"] = list(self.slots)
        if self.args:
            out["args"] = dict(self.args)
        if self.dwell_ms:
            # Coerced: an int-valued dwell must serialize identically before
            # and after a round trip (repro files diff clean).
            out["dwell_ms"] = float(self.dwell_ms)
        if not self.settle:
            out["settle"] = False
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "FaultEvent":
        return cls(
            kind=str(data["kind"]),
            slots=tuple(int(s) for s in data.get("slots", ())),
            args=dict(data.get("args", {})),  # type: ignore[arg-type]
            dwell_ms=float(data.get("dwell_ms", 0.0)),  # type: ignore[arg-type]
            settle=bool(data.get("settle", True)),
        )


@dataclass
class FaultSchedule:
    """A complete, replayable fault scenario.

    ``n0`` slots [0, n0) boot as the initial cluster; slots [n0, n_slots)
    are the joiner pool. Slot 0 is the seed and reference observer — the
    oracles anchor the configuration chain at it — and may never be faulted.
    ``converge_budget_ms`` bounds (in simulated time) the final
    all-live-nodes convergence the bounded-convergence oracle asserts.
    """

    n0: int
    n_slots: int
    seed: int = 0
    events: List[FaultEvent] = field(default_factory=list)
    converge_budget_ms: float = 120_000.0
    #: Simulated-time budget for each settling membership phase (how long a
    #: single decision + catch-up may take before the run counts as wedged).
    phase_budget_ms: float = 90_000.0
    name: str = ""
    #: Protocol profile the runner boots the cluster with: "flat" (the
    #: classic O(N) protocol) or "hier" (two-level hierarchical membership,
    #: rapid_tpu/hier — the WAN-shaped families run under it).
    profile: str = "flat"

    # -- serialization (the repro artifact format) ----------------------

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "version": 1,
            "name": self.name,
            "n0": self.n0,
            "n_slots": self.n_slots,
            "seed": self.seed,
            "converge_budget_ms": self.converge_budget_ms,
            "phase_budget_ms": self.phase_budget_ms,
            "events": [e.to_dict() for e in self.events],
        }
        if self.profile != "flat":
            # Written only when non-default: pre-hier repro files stay
            # byte-identical through a load/save round trip.
            out["profile"] = self.profile
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1) + "\n"

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "FaultSchedule":
        version = data.get("version", 1)
        if version != 1:
            raise ScheduleError(f"unknown schedule version {version!r}")
        try:
            return cls(
                n0=int(data["n0"]),  # type: ignore[arg-type]
                n_slots=int(data["n_slots"]),  # type: ignore[arg-type]
                seed=int(data.get("seed", 0)),  # type: ignore[arg-type]
                events=[FaultEvent.from_dict(e) for e in data.get("events", ())],  # type: ignore[union-attr]
                converge_budget_ms=float(data.get("converge_budget_ms", 120_000.0)),  # type: ignore[arg-type]
                phase_budget_ms=float(data.get("phase_budget_ms", 90_000.0)),  # type: ignore[arg-type]
                name=str(data.get("name", "")),
                profile=str(data.get("profile", "flat")),
            )
        except (KeyError, TypeError, ValueError) as exc:
            # A hand-edited or corrupted schedule file must surface as a
            # schedule error (the CLIs' clean-exit contract), not a raw
            # KeyError traceback.
            raise ScheduleError(f"malformed schedule: {exc!r}") from exc

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        return cls.from_dict(json.loads(text))

    # -- adversarial accounting ----------------------------------------

    def adversarial_crossings(self) -> Dict[int, Tuple[int, Tuple[int, ...]]]:
        """``{event index: (subject slot, cumulative claimed rings)}`` for
        every ``false_alert``/``alert_storm`` event whose cumulative
        distinct-ring count about its subject crosses the H watermark —
        THE definition of "this lie evicts", shared by the runner (expected
        membership), the oracles (stability judgment), the phase grouping
        (engine replay), and the tenancy compiler. Receivers dedup reports
        per (subject, ring), so only DISTINCT rings count, and only DOWN
        claims (UP about a present host is filtered by every receiver)."""
        crossings: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        rings_of: Dict[int, set] = {}
        evicted: set = set()
        for i, event in enumerate(self.events):
            if event.kind not in ("false_alert", "alert_storm"):
                continue
            if str(event.args.get("status", "DOWN")) != "DOWN":
                continue
            subject = int(event.args["subject"])  # type: ignore[arg-type]
            if subject in evicted:
                continue
            acc = rings_of.setdefault(subject, set())
            acc.update(int(r) for r in event.args.get("rings", ()))  # type: ignore[union-attr]
            if len(acc) >= WATERMARK_H:
                crossings[i] = (subject, tuple(sorted(acc)))
                evicted.add(subject)
        return crossings

    def _adversarial_removed(self) -> set:
        """Slots the hostile events remove: committee-crash victims plus
        every subject whose false-report count crosses H."""
        removed = {
            s for s, _ in self.adversarial_crossings().values()
        }
        for event in self.events:
            if event.kind == "committee_crash":
                removed |= set(event.slots)
        return removed

    # -- static validation ---------------------------------------------

    def validate(self) -> None:
        """Simulate the slot lifecycle and reject ill-formed schedules —
        the same rules the generator obeys and the shrinker re-checks, so a
        shrink step can never produce a schedule the runner would crash on."""
        if not 1 <= self.n0 <= self.n_slots:
            raise ScheduleError(f"n0 must be in [1, n_slots], got {self.n0}/{self.n_slots}")
        if self.profile not in ("flat", "hier"):
            raise ScheduleError(f"unknown profile {self.profile!r}")
        live = set(range(self.n0))
        fresh = set(range(self.n0, self.n_slots))
        removed: set = set()
        paused: set = set()
        false_rings: Dict[int, set] = {}
        armed_tripwire: int = -1  # index of a committee_crash awaiting its trigger
        for i, event in enumerate(self.events):
            where = f"event {i} ({event.kind})"
            if 0 in event.slots and event.kind in (
                MEMBERSHIP_KINDS
                | {"partition", "ingress_block", "clock_pause", "committee_crash"}
            ):
                raise ScheduleError(f"{where}: slot 0 (seed/observer) may not be faulted")
            if event.dwell_ms < 0:
                raise ScheduleError(f"{where}: negative dwell_ms")
            if event.kind in MEMBERSHIP_KINDS and not event.slots:
                raise ScheduleError(f"{where}: membership event needs slots")
            if event.kind in MEMBERSHIP_KINDS:
                armed_tripwire = -1  # this event's reconfiguration trips it
            if event.kind == "crash":
                bad = set(event.slots) - live
                if bad:
                    raise ScheduleError(f"{where}: crash of non-live slots {sorted(bad)}")
                live -= set(event.slots)
                removed |= set(event.slots)
            elif event.kind == "join":
                bad = set(event.slots) - fresh
                if bad:
                    raise ScheduleError(f"{where}: join of non-fresh slots {sorted(bad)}")
                fresh -= set(event.slots)
                live |= set(event.slots)
            elif event.kind == "restart":
                bad = set(event.slots) - removed
                if bad:
                    raise ScheduleError(f"{where}: restart of never-removed slots {sorted(bad)}")
                removed -= set(event.slots)
                live |= set(event.slots)
            elif event.kind in ("leave", "partition_oneway"):
                if len(event.slots) != 1:
                    raise ScheduleError(f"{where}: takes exactly one slot")
                if event.slots[0] not in live:
                    raise ScheduleError(f"{where}: slot {event.slots[0]} not live")
                live -= set(event.slots)
                removed |= set(event.slots)
            elif event.kind in ("partition", "ingress_block"):
                bad = set(event.slots) - live
                if bad:
                    raise ScheduleError(f"{where}: {event.kind} of non-live slots {sorted(bad)}")
                if not event.slots:
                    raise ScheduleError(f"{where}: empty {event.kind}")
            elif event.kind in ("link_block", "link_heal"):
                if {"src", "dst"} - set(event.args):
                    raise ScheduleError(f"{where}: needs src/dst args")
            elif event.kind == "loss":
                p = int(event.args.get("permille", -1))  # type: ignore[arg-type]
                if not 0 <= p <= 1000:
                    raise ScheduleError(f"{where}: permille must be in [0, 1000]")
            elif event.kind == "duplicate":
                p = int(event.args.get("permille", -1))  # type: ignore[arg-type]
                if not 0 <= p <= 1000:
                    raise ScheduleError(f"{where}: permille must be in [0, 1000]")
            elif event.kind == "delay":
                lo = float(event.args.get("min_ms", 0.0))  # type: ignore[arg-type]
                hi = float(event.args.get("max_ms", -1.0))  # type: ignore[arg-type]
                if not 0 <= lo <= hi:
                    raise ScheduleError(f"{where}: need 0 <= min_ms <= max_ms")
            elif event.kind == "drop_first_n":
                if len(event.slots) != 1:
                    raise ScheduleError(f"{where}: takes exactly one slot")
                message = event.args.get("message")
                if message not in DROPPABLE_MESSAGES:
                    raise ScheduleError(
                        f"{where}: message must be one of "
                        f"{sorted(DROPPABLE_MESSAGES)}, got {message!r}"
                    )
                if int(event.args.get("count", 0)) < 1:  # type: ignore[arg-type]
                    raise ScheduleError(f"{where}: needs count >= 1")
            elif event.kind == "wan_asym":
                bad = set(event.slots) - live
                if bad:
                    raise ScheduleError(f"{where}: wan_asym over non-live slots {sorted(bad)}")
                p = int(event.args.get("loss_permille", 0))  # type: ignore[arg-type]
                if not 0 <= p <= 1000:
                    raise ScheduleError(f"{where}: loss_permille must be in [0, 1000]")
                lo = float(event.args.get("delay_min_ms", 0.0))  # type: ignore[arg-type]
                hi = float(event.args.get("delay_max_ms", 0.0))  # type: ignore[arg-type]
                if not 0 <= lo <= max(hi, 0.0) or hi < 0:
                    raise ScheduleError(f"{where}: need 0 <= delay_min_ms <= delay_max_ms")
                if event.slots and p == 0 and hi == 0:
                    raise ScheduleError(f"{where}: a non-empty group needs loss or delay")
            elif event.kind in ("false_alert", "alert_storm"):
                subject = event.args.get("subject")
                if not isinstance(subject, int):
                    raise ScheduleError(f"{where}: needs an int subject arg")
                if subject == 0:
                    raise ScheduleError(
                        f"{where}: slot 0 (seed/observer) may not be the subject"
                    )
                if subject not in live:
                    raise ScheduleError(f"{where}: subject {subject} not live")
                status = str(event.args.get("status", "DOWN"))
                if status not in ("DOWN", "UP"):
                    raise ScheduleError(f"{where}: status must be DOWN or UP")
                rings = list(event.args.get("rings", ()))  # type: ignore[arg-type]
                if not rings or not all(
                    isinstance(r, int) and 0 <= r < WATERMARK_K for r in rings
                ):
                    raise ScheduleError(
                        f"{where}: rings must be a non-empty list of ints in "
                        f"[0, {WATERMARK_K})"
                    )
                if event.kind == "false_alert":
                    if len(event.slots) != 1:
                        raise ScheduleError(f"{where}: takes exactly one liar slot")
                else:
                    if not event.slots:
                        raise ScheduleError(f"{where}: a storm needs liar slots")
                liars = set(event.slots)
                if 0 in liars:
                    raise ScheduleError(
                        f"{where}: slot 0 (reference observer) never lies"
                    )
                if subject in liars:
                    raise ScheduleError(f"{where}: the subject cannot lie about itself")
                bad = liars - live
                if bad:
                    raise ScheduleError(f"{where}: non-live liars {sorted(bad)}")
                if status == "DOWN":
                    acc = false_rings.setdefault(subject, set())
                    acc.update(int(r) for r in rings)
                    if len(acc) >= WATERMARK_H:
                        # Past H the lie evicts: the subject leaves the
                        # expected membership like any schedule-removed slot.
                        live.discard(subject)
                        removed.add(subject)
            elif event.kind == "committee_crash":
                if self.profile != "hier":
                    raise ScheduleError(
                        f"{where}: only the hier profile has a global committee"
                    )
                if len(event.slots) != 1:
                    raise ScheduleError(f"{where}: takes exactly one victim slot")
                if event.slots[0] not in live:
                    raise ScheduleError(f"{where}: slot {event.slots[0]} not live")
                if event.settle:
                    raise ScheduleError(
                        f"{where}: must be settle=False — the crash fires "
                        f"during the NEXT membership event's reconfiguration"
                    )
                live -= set(event.slots)
                removed |= set(event.slots)
                armed_tripwire = i
            elif event.kind == "clock_skew":
                if len(event.slots) != 1 or "offset_ms" not in event.args:
                    raise ScheduleError(f"{where}: needs one slot and offset_ms")
                if event.slots[0] in paused:
                    # NodeClock rejects re-skewing a frozen clock; catch the
                    # shape here so a shrink step can never produce a
                    # schedule the runner would crash on.
                    raise ScheduleError(f"{where}: slot {event.slots[0]} is paused")
            elif event.kind == "clock_pause":
                if len(event.slots) != 1 or event.slots[0] in paused:
                    raise ScheduleError(f"{where}: needs one un-paused slot")
                paused |= set(event.slots)
            elif event.kind == "clock_resume":
                if len(event.slots) != 1 or event.slots[0] not in paused:
                    raise ScheduleError(f"{where}: needs one paused slot")
                paused -= set(event.slots)
        if armed_tripwire >= 0:
            # The tripwire only fires when a reconfiguration forwards a
            # cohort cut; a schedule with nothing membership-changing after
            # the arming would leave the victim alive while the expected-
            # membership accounting (runner + oracles) counts it removed —
            # false violations against a correct cluster.
            raise ScheduleError(
                f"event {armed_tripwire} (committee_crash): no membership "
                f"event follows to trigger the reconfiguration tripwire"
            )
        if self.events and not self.events[-1].settle:
            raise ScheduleError("last event must settle (nothing follows to absorb it)")

    # -- derived views --------------------------------------------------

    def membership_phases(self) -> List[List[FaultEvent]]:
        """The membership-changing events, grouped: consecutive
        ``settle=False`` events merge with the next settling one into one
        overlapped group (the runner converges once per group, and the
        differential oracle replays group-at-a-time).

        Adversarial events ride along exactly when they change membership:
        a ``committee_crash`` always (its victim is evicted), a
        ``false_alert``/``alert_storm`` only at its H-crossing event — and
        the crossing event is NORMALIZED to carry the cumulative claimed
        ring set in ``args["rings"]``, so a group consumer (engine replay,
        tenancy compiler) sees the full ≥H report load in one entry without
        re-deriving the accumulation. Sub-H lies are environment-shaped:
        they delay, never change, membership, and appear in no group."""
        crossings = self.adversarial_crossings()
        groups: List[List[FaultEvent]] = []
        current: List[FaultEvent] = []
        for i, event in enumerate(self.events):
            if event.kind in ADVERSARIAL_KINDS:
                if event.kind == "committee_crash":
                    current.append(event)
                elif i in crossings:
                    subject, rings = crossings[i]
                    current.append(FaultEvent(
                        event.kind, event.slots,
                        {"subject": subject, "rings": list(rings)},
                        event.dwell_ms, event.settle,
                    ))
                else:
                    continue
            elif event.kind in MEMBERSHIP_KINDS:
                current.append(event)
            else:
                continue
            if event.settle:
                groups.append(current)
                current = []
        if current:
            groups.append(current)
        return groups

    def expected_members(self) -> int:
        """Final expected membership after every phase resolves."""
        n = self.n0
        for event in self.events:
            if event.kind in MEMBERSHIP_KINDS:
                n += MEMBER_DELTA[event.kind] * len(event.slots)
        return n - len(self._adversarial_removed())

    def expected_removed_slots(self) -> set:
        """Slots the schedule itself removes from membership (crashed, left,
        evicted by an asymmetric partition, committee-crashed, or falsely
        accused past H) and never restarts — the set absent from the
        expected FINAL membership."""
        removed: set = set()
        for event in self.events:
            if event.kind in ("crash", "leave", "partition_oneway", "committee_crash"):
                removed |= set(event.slots)
            elif event.kind == "restart":
                removed -= set(event.slots)
        crossed = {s for s, _ in self.adversarial_crossings().values()}
        for event in self.events:
            if event.kind == "restart":
                crossed -= set(event.slots)
        return removed | crossed

    def ever_removed_slots(self) -> set:
        """Slots removed at ANY point, restarts notwithstanding — the set
        whose KICKED signals are legitimate (a restarted slot's PREVIOUS
        incarnation may rightly learn of its own eviction)."""
        removed: set = set()
        for event in self.events:
            if event.kind in ("crash", "leave", "partition_oneway", "committee_crash"):
                removed |= set(event.slots)
        return removed | {s for s, _ in self.adversarial_crossings().values()}

    @property
    def engine_compatible(self) -> bool:
        """Whether the differential oracle can replay this schedule through
        the device engine. Restarts cannot: a restarted endpoint maps to its
        original (now retired) engine slot — identity lanes are spent
        forever there (the engine's UUIDAlreadySeen discipline) — while the
        host correctly admits the fresh incarnation."""
        return not any(e.kind == "restart" for e in self.events)


def loss_as_engine_delivery(
    loss_permille: int, retry_horizon_rounds: int = 2
) -> Dict[str, int]:
    """Compile a symmetric-loss fault onto the device engine's delivery
    knobs: a message lost on a broadcast link is re-delivered by the alert
    redelivery machinery one interval later, which the round-granular engine
    models as a delivery *delayed* up to ``retry_horizon_rounds`` rounds
    with probability ``loss_permille``/1000 (``EngineConfig``'s
    delivery_prob_permille / delivery_spread pair). Used by bench.py's
    churn_under_loss variant so host schedules and engine benchmarks share
    one definition of "5% loss"."""
    if not 0 <= loss_permille <= 1000:
        raise ScheduleError(f"loss permille must be in [0, 1000], got {loss_permille}")
    return {
        "delivery_prob_permille": loss_permille,
        "delivery_spread": retry_horizon_rounds if loss_permille else 0,
    }


def schedule_rng(schedule: FaultSchedule) -> random.Random:
    """THE seeded stream for a schedule's statistical faults — one
    definition, so the runner and any replay derive identical draws."""
    return random.Random(f"rapid-sim:{schedule.seed}")
