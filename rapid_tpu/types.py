"""Wire-schema types for the membership protocol.

These mirror the *semantics* of the reference's protobuf schema
(``rapid/src/main/proto/rapid.proto``): one request envelope carrying exactly
one protocol message, one response envelope. We use frozen dataclasses instead
of protobuf — the in-process and TCP transports serialize them with
``rapid_tpu.messaging.codec``; they are hashable so they can key vote tallies
exactly the way the reference keys ``Map<List<Endpoint>, AtomicInteger>``
(``FastPaxos.java:53``).
"""

from __future__ import annotations

import enum
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Optional, Tuple, Union

# Trace-context note: ``BatchedAlertMessage`` and the five consensus messages
# carry an optional ``trace_id`` — the correlation key minted at the first
# alert of a membership change (protocol/service.py) and propagated on the
# wire (messaging/codec.py appends it as an optional trailing field, so
# frames without it are byte-identical to the pre-trace layout). The field is
# declared ``compare=False``: equality/hash stay keyed on protocol content
# exactly as the reference keys vote tallies, so two identical votes with
# different trace stamps still dedup as one vote.


@dataclass(frozen=True, order=True)
class Endpoint:
    """A process address (``rapid.proto:13-17``)."""

    hostname: str
    port: int

    def __str__(self) -> str:
        return f"{self.hostname}:{self.port}"

    @staticmethod
    def parse(host_port: str) -> "Endpoint":
        host, _, port = host_port.rpartition(":")
        return Endpoint(host, int(port))


@dataclass(frozen=True, order=True)
class NodeId:
    """A 128-bit logical node identifier (``rapid.proto:50-54``)."""

    high: int
    low: int

    @staticmethod
    def from_uuid(u: Optional[_uuid.UUID] = None) -> "NodeId":
        u = u if u is not None else _uuid.uuid4()
        as_int = u.int
        high = (as_int >> 64) & ((1 << 64) - 1)
        low = as_int & ((1 << 64) - 1)
        return NodeId(high=high, low=low)


class EdgeStatus(enum.IntEnum):
    """``rapid.proto:112-115``."""

    UP = 0
    DOWN = 1


class JoinStatusCode(enum.IntEnum):
    """``rapid.proto:85-91``."""

    HOSTNAME_ALREADY_IN_RING = 0
    UUID_ALREADY_IN_RING = 1
    SAFE_TO_JOIN = 2
    CONFIG_CHANGED = 3
    MEMBERSHIP_REJECTED = 4


class NodeStatus(enum.IntEnum):
    """Probe responses (``rapid.proto:203-206``)."""

    OK = 0
    BOOTSTRAPPING = 1


# --------------------------------------------------------------------------
# Request messages (the RapidRequest oneof, rapid.proto:21-35)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class PreJoinMessage:
    """Phase-1 join: joiner → seed (``rapid.proto:57-63``)."""

    sender: Endpoint
    node_id: NodeId


@dataclass(frozen=True)
class JoinMessage:
    """Phase-2 join: joiner → each observer (``rapid.proto:65-72``)."""

    sender: Endpoint
    node_id: NodeId
    ring_numbers: Tuple[int, ...]
    configuration_id: int
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class AlertMessage:
    """An edge status report (``rapid.proto:101-110``). ``node_id``/``metadata``
    are only set on UP alerts emitted for joiners."""

    edge_src: Endpoint
    edge_dst: Endpoint
    edge_status: EdgeStatus
    configuration_id: int
    ring_numbers: Tuple[int, ...]
    node_id: Optional[NodeId] = None
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class BatchedAlertMessage:
    """``rapid.proto:95-99``."""

    sender: Endpoint
    messages: Tuple[AlertMessage, ...]
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class ProbeMessage:
    """Failure-detector ping (``rapid.proto:192-196``)."""

    sender: Endpoint


@dataclass(frozen=True)
class Rank:
    """Paxos rank: ordered by (round, node_index) (``rapid.proto:133-137``)."""

    round: int
    node_index: int

    def as_tuple(self) -> Tuple[int, int]:
        return (self.round, self.node_index)


@dataclass(frozen=True)
class FastRoundPhase2bMessage:
    """A fast-round vote: the sender's cut proposal (``rapid.proto:124-129``)."""

    sender: Endpoint
    configuration_id: int
    endpoints: Tuple[Endpoint, ...]
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class Phase1aMessage:
    sender: Endpoint
    configuration_id: int
    rank: Rank
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class Phase1bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vrnd: Rank
    vval: Tuple[Endpoint, ...]
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class Phase2aMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vval: Tuple[Endpoint, ...]
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class Phase2bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    endpoints: Tuple[Endpoint, ...]
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class LeaveMessage:
    """Graceful-leave intent (``rapid.proto:185-188``)."""

    sender: Endpoint


@dataclass(frozen=True)
class CohortCutMessage:
    """Hierarchical membership (rapid_tpu/hier): a cohort's *decided* cut
    proposal, forwarded by the cohort's delegate (or a failover candidate)
    to the global reconfiguration committee. ``cohort`` is the sender's
    cohort index under the current configuration's cohort map; ``endpoints``
    is the cut the cohort's Fast Paxos agreed on. ``joiner_eps``/``joiner_ids``
    carry the identifiers of any joiners in the cut (their UP alerts only
    circulated inside the gatekeeper cohort, so the committee — and later
    every other cohort — learns them here)."""

    sender: Endpoint
    configuration_id: int
    cohort: int
    endpoints: Tuple[Endpoint, ...]
    joiner_eps: Tuple[Endpoint, ...] = ()
    joiner_ids: Tuple[NodeId, ...] = ()
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class DelegateDecisionMessage:
    """Hierarchical membership (rapid_tpu/hier): the globally-decided view
    change, disseminated by each committee member to its own cohort's plain
    members so every node applies the identical, totally-ordered
    configuration change without having participated in the global tier."""

    sender: Endpoint
    configuration_id: int
    endpoints: Tuple[Endpoint, ...]
    joiner_eps: Tuple[Endpoint, ...] = ()
    joiner_ids: Tuple[NodeId, ...] = ()
    trace_id: Optional[int] = field(default=None, compare=False)


@dataclass(frozen=True)
class GlobalTierMessage:
    """Hierarchical membership (rapid_tpu/hier): envelope distinguishing the
    global reconfiguration tier's consensus traffic (the five Fast-Paxos /
    classic-Paxos message types, scoped to the delegate committee) from the
    cohort-local fast path's — both tiers speak the same consensus message
    types over the same configuration id, so the envelope is what routes a
    frame to the right engine. ``payload`` is a complete consensus request."""

    sender: Endpoint
    payload: "RapidRequest"


@dataclass(frozen=True)
class GossipMessage:
    """Epidemic-relay envelope for broadcast traffic — the alternate
    broadcast strategy ``IBroadcaster.java:24-29``'s docs name but the
    reference never ships. ``payload`` is the broadcast request being
    spread; (origin, msg_id) dedups redeliveries; ttl bounds relay depth.
    """

    origin: Endpoint
    msg_id: int  # uint64, drawn per broadcast
    ttl: int
    payload: "RapidRequest"


RapidRequest = Union[
    PreJoinMessage,
    JoinMessage,
    BatchedAlertMessage,
    ProbeMessage,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    LeaveMessage,
    GossipMessage,
    CohortCutMessage,
    DelegateDecisionMessage,
    GlobalTierMessage,
]


# --------------------------------------------------------------------------
# Response messages (the RapidResponse oneof, rapid.proto:37-45)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class JoinResponse:
    """``rapid.proto:74-83``."""

    sender: Endpoint
    status_code: JoinStatusCode
    configuration_id: int
    endpoints: Tuple[Endpoint, ...] = ()
    identifiers: Tuple[NodeId, ...] = ()
    metadata_keys: Tuple[Endpoint, ...] = ()
    metadata_values: Tuple[Tuple[Tuple[str, bytes], ...], ...] = ()


@dataclass(frozen=True)
class Response:
    """Empty acknowledgement (``rapid.proto:117-119``)."""


@dataclass(frozen=True)
class ConsensusResponse:
    """Empty consensus acknowledgement (``rapid.proto:172-174``)."""


@dataclass(frozen=True)
class ProbeResponse:
    """``rapid.proto:198-201``."""

    status: NodeStatus = NodeStatus.OK


RapidResponse = Union[JoinResponse, Response, ConsensusResponse, ProbeResponse]
