from rapid_tpu.models.state import (
    CompactionPolicy,
    EngineConfig,
    EngineState,
    FaultInputs,
    StepEvents,
    compaction_policy,
    initial_state,
    pack_masks,
    state_bytes_per_member,
    unpack_masks,
    widen_state,
)
from rapid_tpu.models.virtual_cluster import VirtualCluster, engine_step

__all__ = [
    "CompactionPolicy",
    "EngineConfig",
    "EngineState",
    "FaultInputs",
    "StepEvents",
    "compaction_policy",
    "initial_state",
    "pack_masks",
    "state_bytes_per_member",
    "unpack_masks",
    "widen_state",
    "VirtualCluster",
    "engine_step",
]
