from rapid_tpu.models.state import (
    EngineConfig,
    EngineState,
    FaultInputs,
    StepEvents,
    initial_state,
)
from rapid_tpu.models.virtual_cluster import VirtualCluster, engine_step

__all__ = [
    "EngineConfig",
    "EngineState",
    "FaultInputs",
    "StepEvents",
    "initial_state",
    "VirtualCluster",
    "engine_step",
]
