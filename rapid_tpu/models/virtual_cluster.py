"""The flagship model: a whole Rapid-style cluster of N virtual endpoints
executing the membership protocol as one fused device program.

One ``engine_step`` = one protocol round for every virtual node at once
(the device analog of ``MembershipService``'s per-message pipeline,
MembershipService.java:300-354):

  probe tick -> edge alerts -> cohort delivery -> watermark cut detection ->
  fast-round votes -> quorum tally -> view-change application.

Everything is static-shaped: membership is an ``alive`` mask, faults are
masks, and the view change is a ``lax.cond`` that re-derives ring topology.
The N axis shards over a device mesh (see rapid_tpu.parallel); every global
reduction here is a sum/any over N, which XLA lowers to psum over ICI.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rapid_tpu.models.state import (
    TELEMETRY_BUCKETS,
    EngineConfig,
    EngineState,
    FaultInputs,
    StepEvents,
    TelemetryLanes,
    TraceRing,
    compaction_policy,
    initial_state,
    initial_telemetry,
    initial_trace,
)
from rapid_tpu.ops.consensus import tally_candidates, undecided_log2_bucket
from rapid_tpu.ops.cut_detection import cohort_watermark_pass, telemetry_cut_masks
from rapid_tpu.ops.hashing import masked_set_hash, mix32
from rapid_tpu.ops.pallas_kernels import (
    _popcount32,
    delivery_new_bits_pallas,
)
from rapid_tpu.ops.rings import (
    endpoint_ring_keys,
    predecessor_of_keys,
    ring_topology_from_perm,
)
from rapid_tpu.utils import engine_telemetry, exposition
from rapid_tpu.utils.dispatch import DispatchSeam
from rapid_tpu.utils.health import NodeHealth
from rapid_tpu.utils.metrics import Metrics


def cohort_words(c: int) -> int:
    """uint32 words needed to carry one bit per receiver cohort."""
    return (c + 31) // 32


def _validate_delivery_prob(permille: int) -> None:
    """A negative value would wrap through uint32 in the delivery gate and
    silently behave as p=1; every constructor funnels through this."""
    if not 0 <= permille <= 1000:
        raise ValueError(
            f"delivery_prob_permille must be in [0, 1000], got {permille}"
        )


def _edge_masks(cfg: EngineConfig, state: EngineState, faults: FaultInputs):
    """Per-edge observer masks: (observer_active[n,k], blocked_rows[w*k,n]).

    ``blocked_rows`` packs "cohort c cannot hear the observer of edge
    (subject, ring)" bitwise over cohorts — row ``wi*k + ring``, bit j of a
    word covers cohort ``32*wi + j`` — so the hoisted delivery mask costs
    O(K·N·C/32) uint32 instead of O(K·N·C) bools, which is what lets C
    scale to hundreds of independently-diverging receiver cohorts. (Slots
    on the last axis: the layout the delivery kernel tiles over lanes.)
    Both outputs depend only on (topology, faults), fixed between view
    changes, so convergence loops hoist this out of the round body
    entirely.
    """
    n, k, c = cfg.n, cfg.k, cfg.c
    w = cohort_words(c)
    obs = state.obs_idx.T  # [n, k] — observer of (subject s, ring k)
    obs_clamped = jnp.clip(obs, 0, n - 1)

    active = state.alive & ~faults.crashed
    observer_active = (obs >= 0) & active[obs_clamped]

    # Pack rx_block over the cohort axis, then gather per observer.
    pad = w * 32 - c
    rxb = jnp.pad(faults.rx_block, ((0, pad), (0, 0))).astype(jnp.uint32)  # [32w, n]
    rxb = rxb.reshape(w, 32, n)
    bit_weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    words = jnp.sum(rxb * bit_weights[None, :, None], axis=1, dtype=jnp.uint32)  # [w, n]
    blocked_rows = words[:, obs_clamped.T].reshape(w * k, n)  # THE gather
    return observer_active, blocked_rows


def _fd_tick(cfg: EngineConfig, state: EngineState, faults: FaultInputs, observer_active):
    """Every observer probes its subjects; edges past the failure threshold
    emit one DOWN alert (semantics of PingPongFailureDetector + the
    edge-failure notification path, MembershipService.java:472-495).

    Two policies (cfg.fd_window): the reference code's cumulative counter,
    or the paper's windowed fraction — a uint32 bit-history per edge, fire
    when >= fd_threshold of the last fd_window probe outcomes failed.
    Intermittent blips age out of the window; the counter latches them."""
    subject_down = faults.crashed[:, None] | faults.probe_fail
    probe_failed = observer_active & subject_down & state.alive[:, None]

    if cfg.fd_window:
        # Windowed mode, matching the host twin exactly: the history shifts
        # only when a probe actually happened (an inactive observer
        # contributes no outcome — implicit successes would decay real
        # failure history), and the edge cannot fire until a FULL window of
        # probes has been observed. fd_count counts PROBES here (its only
        # windowed-mode meaning), so stagger_fd_counts' negative offsets
        # still jitter detection by delaying window-full.
        probed = observer_active & state.alive[:, None]
        fd_count = jnp.where(probed, state.fd_count + 1, state.fd_count)
        # Mask and OR-in at the lane's own (policy) dtype: a uint32 operand
        # here would silently re-widen the whole history lane (the
        # dtype-widening lint class) — fd_window <= 8*itemsize by policy.
        hdt = state.fd_hist.dtype
        window_mask = jnp.asarray((1 << cfg.fd_window) - 1, hdt)
        shifted = ((state.fd_hist << 1) | probe_failed.astype(hdt)) & window_mask
        fd_hist = jnp.where(probed, shifted, state.fd_hist)
        past_threshold = (_popcount32(fd_hist) >= cfg.fd_threshold) & (
            fd_count >= cfg.fd_window
        )
    else:
        # Counter mode (the reference code): fd_count counts FAILURES.
        fd_count = jnp.where(probe_failed, state.fd_count + 1, state.fd_count)
        fd_hist = state.fd_hist
        past_threshold = fd_count >= cfg.fd_threshold
    fire = past_threshold & ~state.fd_fired & state.alive[:, None]
    fd_fired = state.fd_fired | fire
    return fd_count, fd_hist, fd_fired, fire


def _deliver_alerts(cfg: EngineConfig, state: EngineState, fire_round, blocked_rows):
    """Per-cohort delivered alert bitmasks, ``new_bits[c, n]`` (bit k = ring
    k's alert for subject n has reached cohort c).

    The device analog of UnicastToAllBroadcaster + per-receiver arrival
    timing: an alert fired at round f reaches cohort c at round
    ``f + delay(c, edge)`` where the delay is drawn deterministically from a
    hash of (cohort, edge, configuration) in ``[0, delivery_spread]``
    (sub-round granularity via cfg.delivery_prob_permille) — different
    cohorts genuinely hear different alert subsets at any instant, which is
    where almost-everywhere-agreement conflicts come from (paper Fig. 11).
    Delivery is recomputed cumulatively each round (cheap bitwise work); the
    OR-merge into ``report_bits`` makes redelivery idempotent. Materializes
    [c, n] per ring — never [c, n, k]. With cfg.use_pallas the whole
    (cohort-word x ring) loop nest runs as one fused VMEM kernel
    (rapid_tpu.ops.pallas_kernels.delivery_new_bits_pallas, hash-stream
    bit-identical to this path).
    """
    n, k, c = cfg.n, cfg.k, cfg.c
    age_kn = state.round_idx - fire_round.T  # [k, n]; hugely negative if unfired
    if cfg.use_pallas:
        out = delivery_new_bits_pallas(
            blocked_rows,
            age_kn,
            state.config_epoch.astype(jnp.uint32).reshape(1),
            cfg.k,
            cfg.delivery_spread,
            cfg.delivery_prob_permille,
            lanes=cfg.pallas_lanes,
        )
        return out[:c, :]

    c_ids = jnp.arange(c, dtype=jnp.uint32)
    word_idx = (c_ids // 32).astype(jnp.int32)  # [c]
    bit_idx = c_ids % 32  # [c]
    slot_salt = jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(0x85EBCA77)
    epoch_salt = state.config_epoch.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)

    # Accumulate at the report lane's own (policy) dtype: K <= 8*itemsize
    # by construction, and a uint32 accumulator would re-widen the merge.
    rdt = state.report_bits.dtype
    new_bits = jnp.zeros((c, n), dtype=rdt)
    for ring in range(k):
        blocked = (blocked_rows[word_idx * k + ring, :] >> bit_idx[:, None]) & 1  # [c, n]
        if cfg.delivery_spread > 0:
            rnd = mix32(
                (c_ids[:, None] * jnp.uint32(0x9E3779B1))
                ^ slot_salt[None, :]
                ^ jnp.uint32((ring * 0xC2B2AE3D) & 0xFFFFFFFF)
                ^ epoch_salt
            )
            if cfg.delivery_prob_permille >= 1000:
                delay = (rnd % jnp.uint32(cfg.delivery_spread + 1)).astype(jnp.int32)
            else:
                # Sub-round skew: delay is nonzero (uniform in
                # [1, delivery_spread]) only with probability p; an
                # independent hash stream gates so magnitude and gate are
                # uncorrelated.
                gate = (mix32(rnd ^ jnp.uint32(0xA511E9B3)) % jnp.uint32(1000)) < jnp.uint32(
                    cfg.delivery_prob_permille
                )
                magnitude = 1 + (rnd % jnp.uint32(cfg.delivery_spread)).astype(jnp.int32)
                delay = jnp.where(gate, magnitude, 0)
        else:
            delay = 0
        delivered = (age_kn[ring][None, :] >= delay) & (blocked == 0)  # [c, n]
        new_bits = new_bits | (delivered.astype(rdt) << jnp.asarray(ring, rdt))
    return new_bits


def _cohort_cut_detection(cfg: EngineConfig, state: EngineState, new_bits, heard_down):
    """The engine's cut-detection seam: C independent watermark detectors
    batched over the (mesh-sharded) cohort axis. The pass itself lives in
    ``rapid_tpu.ops.cut_detection.cohort_watermark_pass`` (the cohort-grain
    twin of ``process_alert_batch``, with the sharding discipline documented
    there); this wrapper only adapts the state pytree."""
    return cohort_watermark_pass(
        state.report_bits,
        new_bits,
        state.seen_down,
        state.released,
        state.announced,
        state.alive | state.join_pending,
        state.inval_obs,
        heard_down,
        cfg.h,
        cfg.l,
        cfg.k,
    )


def _compute_round(
    cfg: EngineConfig, state: EngineState, faults: FaultInputs, edge_masks=None,
    telem: Optional[TelemetryLanes] = None,
    trace: Optional[TraceRing] = None,
):
    """One protocol round WITHOUT view-change application: returns the
    round-advanced state plus (decided, winner_mask, events). Keeping the
    ring re-sort out of the round body lets the convergence loop run
    sort-free and apply the view change exactly once on exit; loops also
    hoist the per-edge gather by passing precomputed ``edge_masks``.

    ``telem`` (the device telemetry plane, ``cfg.telemetry == 1``): when a
    :class:`TelemetryLanes` pytree is passed, the round accumulates into it
    and the return grows a fifth element — the updated lanes. The branch is
    a PYTHON-level ``if``: with ``telem=None`` (telemetry off) no telemetry
    code is traced at all, so the compiled program is byte-identical to the
    pre-telemetry engine (the hlo.lock.json gate freezes that). Telemetry
    is write-only — nothing below reads a ``tl_`` lane — so engine results
    are bit-identical on vs off by construction, and every accumulation is
    either an already-computed round scalar or elementwise at the lane's
    native [c, n]/[c] grain: zero new collectives in the round body (the
    cross-shard reductions live in ``telemetry_digest_impl``, dispatched
    only at host-sync boundaries).

    ``trace`` (the device round-trace ring, ``cfg.trace == R > 0``): when a
    :class:`TraceRing` is passed the round also writes ONE per-round record
    into slot ``tr_cursor % R`` and the return grows a sixth element — the
    updated ring. Same discipline as the telemetry plane (a Python-level
    ``if``, write-only lanes, zero new collectives: every record field is a
    scalar the round already computed), and the ring's active-subject count
    reuses the telemetry block's cut-mask reduction — which is why
    ``trace`` requires ``telem`` (trace is a refinement of the telemetry
    plane, enforced at driver construction)."""
    n, k, c = cfg.n, cfg.k, cfg.c

    # 1. Failure-detector tick -> fresh DOWN alerts per (subject, ring) edge.
    if edge_masks is None:
        edge_masks = _edge_masks(cfg, state, faults)
    observer_active, blocked_rows = edge_masks
    fd_count, fd_hist, fd_fired, fire = _fd_tick(cfg, state, faults, observer_active)
    # Stamp at the lane's (policy) dtype: round_idx is int32 and a bare
    # where() would re-widen the whole [n, k] lane. In-envelope round
    # indices (< fire_never) cast losslessly.
    fire_round = jnp.where(
        fire, state.round_idx.astype(state.fire_round.dtype), state.fire_round
    )
    alerts_emitted = jnp.sum(fire, dtype=jnp.int32)

    # 2. Broadcast delivery: alert for edge (s, ring) originates at the edge's
    #    observer; cohort c hears it unless that observer is rx-blocked, and
    #    only once the per-(cohort, edge) delivery delay has matured
    #    (the device analog of UnicastToAllBroadcaster + drop interceptors +
    #    arrival-timing skew). Delivered alerts pack straight into
    #    per-subject ring bitmasks.
    #    Delivery work is cond-skipped once every fired alert has matured:
    #    delays and rx-blocks are fixed between view changes, so past
    #    max(fire_round) + spread the delivered mask is static and already
    #    OR-merged into report_bits — recomputing it adds nothing.
    fired_any = jnp.any(fd_fired)
    last_mature = (
        jnp.max(jnp.where(fd_fired, fire_round, jnp.int32(-1)))
        + cfg.delivery_spread
    )
    need_delivery = fired_any & (state.round_idx <= last_mature)
    new_bits = jax.lax.cond(
        need_delivery,
        lambda: _deliver_alerts(cfg, state, fire_round, blocked_rows),
        lambda: jnp.zeros((c, n), dtype=state.report_bits.dtype),
    )
    # Alerts for ALIVE subjects are DOWN reports; join-pending subjects'
    # reports are UP and must not arm implicit invalidation.
    heard_down = jnp.any((new_bits != 0) & state.alive[None, :], axis=1)  # [c]

    # 3. Cut detection per cohort.
    report_bits, released, announced, seen_down, proposed_now, prop_masks = _cohort_cut_detection(
        cfg, state, new_bits, heard_down
    )
    # Proposal identity = commutative set-hash of the cut's member identities
    # (the canonical-sort-free equivalent of the ring-0-sorted endpoint list,
    # MembershipService.java:346-348). Per-cohort hash reductions over N —
    # node-axis psums on the mesh, cohort-local otherwise (deliberately NOT
    # cond-gated: an extra lax.cond in the round body costs more compile
    # time across every engine program than the masked reductions cost to
    # run).
    prop_hi_new, prop_lo_new = jax.vmap(
        lambda mask: masked_set_hash(state.id_hi, state.id_lo, mask)
    )(prop_masks)
    prop_hi = jnp.where(proposed_now, prop_hi_new, state.prop_hi)
    prop_lo = jnp.where(proposed_now, prop_lo_new, state.prop_lo)
    prop_mask = jnp.where(proposed_now[:, None], prop_masks, state.prop_mask)

    # 4. Fast-round votes: each live member votes its cohort's proposal, once
    #    per configuration (FastPaxos.java:94-108).
    cohort = state.cohort_of
    cohort_announced = announced[cohort]
    can_vote = state.alive & ~faults.crashed & ~state.vote_valid & cohort_announced
    vote_hi = jnp.where(can_vote, prop_hi[cohort], state.vote_hi)
    vote_lo = jnp.where(can_vote, prop_lo[cohort], state.vote_lo)
    vote_valid = state.vote_valid | can_vote

    # 5. Quorum tally over all N votes (FastPaxos.java:125-156).
    tally = tally_candidates(
        vote_hi, vote_lo, vote_valid, prop_hi, prop_lo, announced, state.n_members
    )
    fast_decided = tally.decided

    # 5a'. Casting a fast-round vote also primes the classic acceptor state:
    #      rnd = vrnd = (1, 1), vval = the vote (Paxos.java:246-260). The
    #      fast round is always round 1; classic rounds start at 2.
    prime = can_vote & (state.cp_rnd_r < 1)
    cp_rnd_r = jnp.where(prime, 1, state.cp_rnd_r)
    cp_rnd_i = jnp.where(prime, 1, state.cp_rnd_i)
    cp_vrnd_r = jnp.where(prime, 1, state.cp_vrnd_r)
    cp_vrnd_i = jnp.where(prime, 1, state.cp_vrnd_i)
    cp_vval_src = jnp.where(prime, cohort, state.cp_vval_src)

    rounds_undecided = jnp.where(
        jnp.any(announced) & ~fast_decided, state.rounds_undecided + 1, state.rounds_undecided
    )
    fallback_due = (rounds_undecided >= cfg.fallback_rounds) & jnp.any(announced) & ~fast_decided

    # 5b. Classic-Paxos fallback, message-level (Paxos.java:98-238): one
    #     attempt per engine round once the recovery delay expires. R =
    #     cfg.concurrent_coordinators rotating coordinators race within the
    #     attempt, rank-ordered as in the reference (Paxos.java:93-97,
    #     333-339): every acceptor promises to each heard phase1a in rank
    #     order, so several coordinators can win phase 1, but an acceptor's
    #     final rnd is the max heard rank and phase2a messages below it are
    #     rejected — a lower-ranked coordinator's phase 2 loses wherever a
    #     higher rank reached. Each coordinator picks a value with the Fast
    #     Paxos coordinator rule (Paxos.java:271-328); decision at a
    #     majority of accepts for one rank (majorities intersect, so at most
    #     one rank can decide per attempt). Delivery respects the same
    #     per-cohort rx-block masks as alerts, so partitioned coordinators
    #     genuinely fail and rotation recovers. Cond-gated: the common fast
    #     path skips the cumsum/gathers entirely.
    def classic_attempt(cp):
        cp_rnd_r, cp_rnd_i, cp_vrnd_r, cp_vrnd_i, cp_vval_src = cp
        # Lane (policy) dtypes the attempt's stores must land at: racer
        # indices/ranks computed in int32 and narrowed on store — a bare
        # int32 operand in a where() would silently re-widen the lane.
        idt = cp_rnd_i.dtype
        cdt = cp_vval_src.dtype
        active = state.alive & ~faults.crashed
        n_active = jnp.sum(active, dtype=jnp.int32)
        majority = state.n_members // 2 + 1
        round_num = 2 + state.classic_epoch  # stays at the counter dtype
        slot_ids = jnp.arange(n, dtype=jnp.int32)
        cohort_ids = jnp.arange(c, dtype=jnp.int32)
        active_rank = jnp.cumsum(active.astype(jnp.int32))

        def rank_gt(ar, ai, br, bi):
            return (ar > br) | ((ar == br) & (ai > bi))

        # Pseudo-random coordinator picks, one hash stream per racer: the
        # real protocol's expovariate jitter makes concurrent recoverers
        # effectively random slots, so a contiguous run of partitioned slots
        # is escaped in O(1) expected attempts.
        coords = []
        for j in range(cfg.concurrent_coordinators):
            pick = mix32(_rotation_seed(state.classic_epoch.astype(jnp.uint32), j))
            target = jnp.where(
                n_active > 0,
                (pick % jnp.maximum(n_active, 1).astype(jnp.uint32)).astype(jnp.int32)
                + 1,
                1,
            )
            coords.append(jnp.argmax(active & (active_rank == target)).astype(idt))

        # Distinct racers only: a duplicate pick would duplicate a rank.
        valid = []
        for j, coord in enumerate(coords):
            v = jnp.bool_(True)
            for prev in coords[:j]:
                v = v & (coord != prev)
            valid.append(v)

        # Phase 1a/1b per racer. Arrival in rank order within the attempt
        # means a lower-ranked phase1a is never blocked by a concurrent
        # higher one — each racer collects promises from every reachable
        # acceptor whose rnd predates this attempt (Paxos.java:118-148).
        per = []
        for coord, v in zip(coords, valid):
            coord_cohort = state.cohort_of[coord]
            hears_coord = active & v & ~faults.rx_block[state.cohort_of, coord]
            coord_hears = active & v & ~faults.rx_block[coord_cohort, slot_ids]
            promise = hears_coord & rank_gt(round_num, coord, cp_rnd_r, cp_rnd_i)
            q1 = promise & coord_hears
            phase1_ok = jnp.sum(q1, dtype=jnp.int32) >= majority

            # Coordinator value-pick rule over the quorum's (vrnd, vval)
            # pairs — the plurality among max-vrnd accepted values (a safe
            # instance of Paxos.java:287-308: a fast-chosen value holds
            # > N/4 of any majority quorum and at most one value can be
            # fast-chosen, so the plurality contains it whenever one
            # exists). If NO quorum member has accepted anything, safety
            # permits a free choice: propose an announced cut
            # (Paxos.java:310-326's any-proposed-value clause).
            voters = q1 & (cp_vval_src >= 0)
            mv_r = jnp.max(jnp.where(voters, cp_vrnd_r, -1))
            mv_i = jnp.max(jnp.where(voters & (cp_vrnd_r == mv_r), cp_vrnd_i, -1))
            at_max = voters & (cp_vrnd_r == mv_r) & (cp_vrnd_i == mv_i)
            max_counts = jnp.sum(
                at_max[None, :] & (cp_vval_src[None, :] == cohort_ids[:, None]),
                axis=1,
                dtype=jnp.int32,
            )
            chosen = jnp.where(
                jnp.any(max_counts > 0),
                jnp.argmax(max_counts).astype(cdt),
                jnp.where(
                    jnp.any(announced), jnp.argmax(announced).astype(cdt), -1
                ),
            )
            per.append((coord, hears_coord, promise, phase1_ok, chosen))

        # After every phase1a has arrived, an acceptor's rnd is the max rank
        # it heard (promises in rank order).
        rnd1_r, rnd1_i = cp_rnd_r, cp_rnd_i
        for coord, hears_coord, promise, _, _ in per:
            bump = promise & rank_gt(round_num, coord, rnd1_r, rnd1_i)
            rnd1_r = jnp.where(bump, round_num, rnd1_r)
            rnd1_i = jnp.where(bump, coord, rnd1_i)

        # Phase 2a/2b: an acceptor accepts only a phase2a matching its final
        # rnd (Paxos.java:195-216) — so where a higher rank's phase1a
        # reached, the lower racer's phase2a is rejected. Ranks are distinct,
        # hence at most one accept per acceptor. Decision at a majority of
        # accepts for one rank (Paxos.java:223-238).
        acc_r, acc_i = cp_vrnd_r, cp_vrnd_i
        acc_src = cp_vval_src
        fb_decided = jnp.bool_(False)
        chosen_winner = jnp.int32(-1)
        any_promise = jnp.zeros((n,), dtype=bool)
        any_accept = jnp.zeros((n,), dtype=bool)
        for coord, hears_coord, promise, phase1_ok, chosen in per:
            # A heard acceptor's final rnd is >= this racer's rank (it
            # promised in rank order), so acceptance means equality: this
            # racer was the highest rank the acceptor heard.
            can_accept = (
                phase1_ok
                & (chosen >= 0)
                & hears_coord
                & (rnd1_r == round_num)
                & (rnd1_i == coord)
            )
            accept_count = jnp.sum(can_accept, dtype=jnp.int32)
            won = phase1_ok & (chosen >= 0) & (accept_count >= majority)
            fb_decided = fb_decided | won
            chosen_winner = jnp.where(won, chosen.astype(jnp.int32), chosen_winner)
            acc_r = jnp.where(can_accept, round_num, acc_r)
            acc_i = jnp.where(can_accept, coord, acc_i)
            acc_src = jnp.where(can_accept, chosen, acc_src)
            any_promise = any_promise | promise
            any_accept = any_accept | can_accept

        return (
            jnp.where(any_promise | any_accept, rnd1_r, cp_rnd_r),
            jnp.where(any_promise | any_accept, rnd1_i, cp_rnd_i),
            acc_r,
            acc_i,
            acc_src,
            fb_decided,
            chosen_winner,
        )

    def no_attempt(cp):
        cp_rnd_r, cp_rnd_i, cp_vrnd_r, cp_vrnd_i, cp_vval_src = cp
        return (
            cp_rnd_r, cp_rnd_i, cp_vrnd_r, cp_vrnd_i, cp_vval_src,
            jnp.bool_(False), jnp.int32(-1),
        )

    cp_rnd_r, cp_rnd_i, cp_vrnd_r, cp_vrnd_i, cp_vval_src, fb_decided, chosen = jax.lax.cond(
        fallback_due,
        classic_attempt,
        no_attempt,
        (cp_rnd_r, cp_rnd_i, cp_vrnd_r, cp_vrnd_i, cp_vval_src),
    )
    classic_epoch = jnp.where(fallback_due, state.classic_epoch + 1, state.classic_epoch)

    decided = fast_decided | fb_decided
    winner_cohort = jnp.where(
        fast_decided,
        jnp.argmax(announced & (prop_hi == tally.winner_hi) & (prop_lo == tally.winner_lo)),
        jnp.maximum(chosen, 0),
    )
    # Materialize the decided cut as a one-hot masked reduction over the
    # cohort axis — on the cohort-meshed state this lowers to a reduce-class
    # psum of [n] bools, where the old dynamic row gather
    # (prop_mask[winner_cohort]) would redistribute across the cohort axis
    # as gather/permute traffic in every round of the hot loop.
    winner_mask = decided & jnp.any(
        prop_mask & (jnp.arange(c, dtype=jnp.int32) == winner_cohort)[:, None],
        axis=0,
    )

    round_state = state._replace(
        fd_count=fd_count,
        fd_hist=fd_hist,
        fd_fired=fd_fired,
        fire_round=fire_round,
        round_idx=state.round_idx + 1,
        report_bits=report_bits,
        seen_down=seen_down,
        released=released,
        announced=announced,
        prop_mask=prop_mask,
        prop_hi=prop_hi,
        prop_lo=prop_lo,
        vote_hi=vote_hi,
        vote_lo=vote_lo,
        vote_valid=vote_valid,
        rounds_undecided=rounds_undecided,
        cp_rnd_r=cp_rnd_r,
        cp_rnd_i=cp_rnd_i,
        cp_vrnd_r=cp_vrnd_r,
        cp_vrnd_i=cp_vrnd_i,
        cp_vval_src=cp_vval_src,
        classic_epoch=classic_epoch,
    )
    events = StepEvents(
        decided=decided,
        fast_decided=fast_decided,
        winner_mask=winner_mask,
        proposals_announced=proposed_now,
        alerts_emitted=alerts_emitted,
        total_votes=tally.total_votes,
        max_votes=tally.max_count,
        prop_hi=prop_hi,
        prop_lo=prop_lo,
    )
    if telem is None:
        return round_state, decided, winner_mask, events

    # Device telemetry plane (write-only; see the docstring contract).
    # Scalars reuse reductions computed above; [c, n]/[c] lanes accumulate
    # elementwise at their native grain.
    active_cn, invalidated_cn = telemetry_cut_masks(
        state.report_bits, new_bits, report_bits,
        state.alive | state.join_pending, cfg.h, cfg.l,
    )
    decided_i = decided.astype(jnp.int32)
    # Decision-path split, same vocabulary as the host protocol's
    # FastPaxos.decided_path ("classic" iff the classic fallback decided).
    bucket = undecided_log2_bucket(rounds_undecided, TELEMETRY_BUCKETS)
    telem = TelemetryLanes(
        tl_rounds=telem.tl_rounds + 1,
        tl_alerts=telem.tl_alerts + alerts_emitted,
        tl_active=telem.tl_active + active_cn.astype(jnp.int32),
        tl_invalidated=telem.tl_invalidated + invalidated_cn.astype(jnp.int32),
        tl_proposals=telem.tl_proposals + proposed_now.astype(jnp.int32),
        tl_tally_sum=telem.tl_tally_sum + jnp.where(decided, tally.max_count, 0),
        tl_fast_decisions=telem.tl_fast_decisions + fast_decided.astype(jnp.int32),
        tl_classic_decisions=telem.tl_classic_decisions + fb_decided.astype(jnp.int32),
        tl_conflict_rounds=telem.tl_conflict_rounds
        + (jnp.any(announced) & ~fast_decided).astype(jnp.int32),
        tl_undecided_hist=telem.tl_undecided_hist.at[bucket].add(decided_i),
    )
    if trace is None:
        return round_state, decided, winner_mask, events, telem

    # Device round-trace ring (write-only; one record per round into slot
    # cursor % R). Every field is a scalar computed above — the ring adds
    # nine scatter-stores and two int adds, nothing else. The round/epoch
    # stamps are the PRE-round values (round_idx increments in round_state;
    # the epoch bumps only when the caller commits the view change), so the
    # decoded (epoch, round) pairs are lexicographically strictly increasing
    # — the wrap-monotonicity contract tests/test_trace_ring.py pins.
    slot = jax.lax.rem(trace.tr_cursor, jnp.int32(cfg.trace))
    trace = TraceRing(
        tr_round=trace.tr_round.at[slot].set(state.round_idx),
        tr_epoch=trace.tr_epoch.at[slot].set(state.config_epoch),
        tr_active=trace.tr_active.at[slot].set(
            jnp.sum(active_cn, dtype=jnp.int32)
        ),
        tr_alerts=trace.tr_alerts.at[slot].set(alerts_emitted),
        tr_proposals=trace.tr_proposals.at[slot].set(
            jnp.sum(proposed_now, dtype=jnp.int32)
        ),
        tr_tally=trace.tr_tally.at[slot].set(jnp.where(decided, tally.max_count, 0)),
        tr_path=trace.tr_path.at[slot].set(
            fast_decided.astype(jnp.int32) + 2 * fb_decided.astype(jnp.int32)
        ),
        tr_conflict=trace.tr_conflict.at[slot].set(
            (jnp.any(announced) & ~fast_decided).astype(jnp.int32)
        ),
        tr_undecided=trace.tr_undecided.at[slot].set(
            rounds_undecided.astype(jnp.int32)
        ),
        tr_cursor=trace.tr_cursor + 1,
        tr_wraps=trace.tr_wraps + (slot == cfg.trace - 1).astype(jnp.int32),
    )
    return round_state, decided, winner_mask, events, telem, trace


def _rotation_seed(epoch_u32, j: int):
    """Per-racer hash-stream seed for coordinator rotation — THE definition,
    shared by the device attempt and the host predictor (uint32 wraparound
    semantics in both)."""
    return epoch_u32 * jnp.uint32(0x9E3779B1) + jnp.uint32(
        (0x5BD1E995 * (j + 1)) & 0xFFFFFFFF
    )


def classic_coordinator_targets(epoch: int, n_active: int, racers: int):
    """Host-side replica of the classic fallback's coordinator rotation:
    the 1-based active-rank target of each racer at ``epoch``. Uses the same
    ``_rotation_seed``/``mix32`` the device attempt uses, so tests and
    diagnostics predict picks from one definition."""
    targets = []
    for j in range(racers):
        pick = int(mix32(_rotation_seed(jnp.uint32(epoch & 0xFFFFFFFF), j)))
        targets.append(pick % max(n_active, 1) + 1)
    return targets


def apply_view_change_impl(
    cfg: EngineConfig, state: EngineState, winner_mask
) -> EngineState:
    """Commit a decided cut: flip membership, re-derive ring topology, reset
    all per-configuration state (MembershipService.java:385-444).

    Joiners NOT in this cut stay pending into the new configuration: their
    UP edges remain armed (gatekeeper observers kept, fired edges re-stamped
    to round 0) so the alerts redeliver and a later cut admits them — unlike
    DOWN alerts, which re-fire from the persistent crash masks, a wiped UP
    edge would never re-fire and the joiner would be stranded forever."""
    n, k, c = cfg.n, cfg.k, cfg.c
    pol = compaction_policy(cfg)
    idt, cdt = jnp.dtype(pol.idx), jnp.dtype(pol.cohort)
    ndt, rdt = jnp.dtype(pol.counter), jnp.dtype(pol.round)
    alive2 = state.alive ^ winner_mask
    # Sort-free: O(N) scans over the static key-order perms, not a K-ring
    # argsort — at N=1M the re-sort was the commit path's largest block.
    # The topology kernels compute at int32; stores narrow to the policy's
    # index dtype (lossless: values in [-1, n-1]).
    topo = ring_topology_from_perm(state.ring_perm, alive2)
    config_hi, config_lo = masked_set_hash(state.id_hi, state.id_lo, alive2)
    still_pending = state.join_pending & ~winner_mask  # [n]
    fd_fired2 = state.fd_fired & still_pending[:, None]
    return state._replace(
        alive=alive2,
        # Departing members' identity lanes are spent forever.
        retired=state.retired | (winner_mask & state.alive),
        obs_idx=jnp.where(
            still_pending[None, :], state.obs_idx, topo.obs_idx.astype(idt)
        ),
        subj_idx=topo.subj_idx.astype(idt),
        inval_obs=jnp.where(
            still_pending[None, :], state.inval_obs, topo.obs_idx.astype(idt)
        ),
        config_epoch=state.config_epoch + 1,
        config_hi=config_hi,
        config_lo=config_lo,
        n_members=jnp.sum(alive2, dtype=jnp.int32),
        fd_count=jnp.zeros((n, k), dtype=ndt),
        fd_hist=jnp.zeros((n, k), dtype=jnp.dtype(pol.hist)),
        fd_fired=fd_fired2,
        fire_round=jnp.where(fd_fired2, 0, jnp.asarray(pol.fire_never, rdt)),
        join_pending=still_pending,
        report_bits=jnp.zeros((c, n), dtype=jnp.dtype(pol.report)),
        seen_down=jnp.zeros((c,), dtype=bool),
        released=jnp.zeros((c, n), dtype=bool),
        announced=jnp.zeros((c,), dtype=bool),
        prop_mask=jnp.zeros((c, n), dtype=bool),
        prop_hi=jnp.zeros((c,), dtype=jnp.uint32),
        prop_lo=jnp.zeros((c,), dtype=jnp.uint32),
        vote_hi=jnp.zeros((n,), dtype=jnp.uint32),
        vote_lo=jnp.zeros((n,), dtype=jnp.uint32),
        vote_valid=jnp.zeros((n,), dtype=bool),
        rounds_undecided=jnp.zeros((), dtype=ndt),
        cp_rnd_r=jnp.zeros((n,), dtype=ndt),
        cp_rnd_i=jnp.zeros((n,), dtype=idt),
        cp_vrnd_r=jnp.zeros((n,), dtype=ndt),
        cp_vrnd_i=jnp.zeros((n,), dtype=idt),
        cp_vval_src=jnp.full((n,), -1, dtype=cdt),
        classic_epoch=jnp.zeros((), dtype=ndt),
        round_idx=jnp.int32(0),
    )


def engine_step_impl(
    cfg: EngineConfig, state: EngineState, faults: FaultInputs
) -> Tuple[EngineState, StepEvents]:
    """One full protocol round including conditional view-change application
    (the per-step driver path)."""
    round_state, decided, winner_mask, events = _compute_round(cfg, state, faults)
    new_state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner_mask),
        lambda s: s,
        round_state,
    )
    return new_state, events


# Donating step for the long-running driver loop (state buffers reused in
# place) and a non-donating variant for compile checks / sharded dry-runs.
engine_step = jax.jit(engine_step_impl, static_argnums=(0,), donate_argnums=(1,))
engine_step_nodonate = jax.jit(engine_step_impl, static_argnums=(0,))  # donate-ok: compile-check / dry-run variant; callers keep their state buffers


def engine_step_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
) -> Tuple[EngineState, TelemetryLanes, StepEvents]:
    """:func:`engine_step_impl` with the telemetry plane riding along — a
    SEPARATE entrypoint (not a default argument on the existing one) so the
    telemetry=0 programs and their donation layout stay untouched, which is
    what lets the hlo.lock.json diff stay purely additive."""
    round_state, decided, winner_mask, events, telem = _compute_round(
        cfg, state, faults, None, telem
    )
    new_state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner_mask),
        lambda s: s,
        round_state,
    )
    return new_state, telem, events


engine_step_telem = jax.jit(
    engine_step_telem_impl, static_argnums=(0,), donate_argnums=(1, 2)
)


def engine_step_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
) -> Tuple[EngineState, TelemetryLanes, TraceRing, StepEvents]:
    """:func:`engine_step_telem_impl` with the round-trace ring riding along
    — a SEPARATE entrypoint again (the ``telemetry`` convention), so the
    trace=0 programs and their donation layout stay untouched and the
    hlo.lock.json diff stays purely additive."""
    round_state, decided, winner_mask, events, telem, trace = _compute_round(
        cfg, state, faults, None, telem, trace
    )
    new_state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner_mask),
        lambda s: s,
        round_state,
    )
    return new_state, telem, trace, events


engine_step_trace = jax.jit(
    engine_step_trace_impl, static_argnums=(0,), donate_argnums=(1, 2, 3)
)


def telemetry_digest_impl(telem: TelemetryLanes) -> jnp.ndarray:
    """The telemetry lanes reduced to one small int32 vector — THE place the
    plane's cross-shard reductions live, dispatched only at the existing
    host-sync boundaries (``sync`` / ``stream_fetch`` / ``health_scan``;
    each fetch site carries a ``# telemetry-fetch-ok:`` marker the
    ``telemetry`` analyzer family enforces), never inside a convergence
    loop. Layout: ``engine_telemetry.TELEMETRY_DIGEST_FIELDS`` scalars then
    the ``TELEMETRY_BUCKETS`` rounds-undecided histogram buckets."""
    return jnp.concatenate([
        jnp.stack([
            telem.tl_rounds,
            telem.tl_alerts,
            jnp.sum(telem.tl_active, dtype=jnp.int32),
            jnp.max(telem.tl_active),
            jnp.sum(telem.tl_invalidated, dtype=jnp.int32),
            jnp.sum(telem.tl_proposals, dtype=jnp.int32),
            telem.tl_tally_sum,
            telem.tl_fast_decisions,
            telem.tl_classic_decisions,
            telem.tl_conflict_rounds,
        ]),
        telem.tl_undecided_hist,
    ])


telemetry_digest = jax.jit(telemetry_digest_impl)  # donate-ok: read-only boundary fetch; the lanes stay live


def trace_digest_impl(trace: TraceRing) -> jnp.ndarray:
    """The trace ring packed into one int32 vector for a single boundary
    fetch: ``[tr_cursor, tr_wraps]`` then the nine ``[R]`` lanes in
    ``engine_telemetry.TRACE_RECORD_FIELDS`` order. Dispatched only at the
    host-sync boundaries, under the same ``# telemetry-fetch-ok:`` marker
    discipline as :func:`telemetry_digest_impl` — never inside a
    convergence loop."""
    return jnp.concatenate([
        jnp.stack([trace.tr_cursor, trace.tr_wraps]),
        trace.tr_round,
        trace.tr_epoch,
        trace.tr_active,
        trace.tr_alerts,
        trace.tr_proposals,
        trace.tr_tally,
        trace.tr_path,
        trace.tr_conflict,
        trace.tr_undecided,
    ])


trace_digest = jax.jit(trace_digest_impl)  # donate-ok: read-only boundary fetch; the ring stays live


def sync_checksum_impl(state: EngineState, faults: FaultInputs):
    """Scalar checksum depending on every state/fault array — the barrier
    ``VirtualCluster.sync`` fetches (``jax.block_until_ready`` does not
    round-trip on remote-tunnel backends; a dependent scalar fetch does).
    Module-level and jitted so the compiled-program gate audits the sync
    dispatch like every other registered entrypoint."""
    return (
        jnp.sum(state.key_hi, dtype=jnp.uint32)
        + jnp.sum(state.key_lo, dtype=jnp.uint32)
        + jnp.sum(state.id_hi, dtype=jnp.uint32)
        + jnp.sum(state.id_lo, dtype=jnp.uint32)
        + jnp.sum(state.obs_idx).astype(jnp.uint32)
        + jnp.sum(state.fd_count).astype(jnp.uint32)
        + jnp.sum(state.report_bits).astype(jnp.uint32)
        + jnp.sum(state.alive).astype(jnp.uint32)
        + jnp.sum(faults.crashed).astype(jnp.uint32)
        + jnp.sum(faults.probe_fail).astype(jnp.uint32)
    )


sync_checksum = jax.jit(sync_checksum_impl)  # donate-ok: read-only barrier; the state stays live


def run_to_decision_impl(cfg: EngineConfig, state: EngineState, faults: FaultInputs, max_steps):
    """Protocol rounds until a view change commits — entirely on device.

    A ``lax.while_loop`` around ``engine_step_impl``: the host dispatches ONE
    program per convergence instead of one per round, removing the per-round
    device->host sync that dominates small-round convergences. Returns
    (state, steps_taken, decided, winner_mask).
    """
    n = cfg.n

    def cond(carry):
        _, steps, decided, _ = carry
        return (~decided) & (steps < max_steps)

    # Topology and faults are fixed until the loop exits (it exits on the
    # first decision), so the per-edge gather hoists out of the round body.
    edge_masks = _edge_masks(cfg, state, faults)

    def body(carry):
        state, steps, _, _ = carry
        round_state, decided, winner_mask, _ = _compute_round(
            cfg, state, faults, edge_masks
        )
        return (round_state, steps + 1, decided, winner_mask)

    init = (state, jnp.int32(0), jnp.bool_(False), jnp.zeros((n,), dtype=bool))
    state, steps, decided, winner = jax.lax.while_loop(cond, body, init)
    # Apply the (at most one) view change after the loop: the round body stays
    # sort-free, and the ring rebuild runs exactly once per convergence.
    state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner),
        lambda s: s,
        state,
    )
    return (state, steps, decided, winner)


run_to_decision = jax.jit(
    run_to_decision_impl, static_argnums=(0,), donate_argnums=(1,)
)


def run_to_decision_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
    max_steps,
):
    """:func:`run_to_decision_impl` with the telemetry lanes joining the
    while-loop carry (separate entrypoint; same rationale as
    :func:`engine_step_telem_impl`)."""
    n = cfg.n

    def cond(carry):
        _, _, steps, decided, _ = carry
        return (~decided) & (steps < max_steps)

    edge_masks = _edge_masks(cfg, state, faults)

    def body(carry):
        state, telem, steps, _, _ = carry
        round_state, decided, winner_mask, _, telem = _compute_round(
            cfg, state, faults, edge_masks, telem
        )
        return (round_state, telem, steps + 1, decided, winner_mask)

    init = (state, telem, jnp.int32(0), jnp.bool_(False), jnp.zeros((n,), dtype=bool))
    state, telem, steps, decided, winner = jax.lax.while_loop(cond, body, init)
    state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner),
        lambda s: s,
        state,
    )
    return (state, telem, steps, decided, winner)


run_to_decision_telem = jax.jit(
    run_to_decision_telem_impl, static_argnums=(0,), donate_argnums=(1, 2)
)


def run_to_decision_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
    max_steps,
):
    """:func:`run_to_decision_telem_impl` with the trace ring joining the
    while-loop carry — the fused convergence stops being a black box: every
    round of the loop leaves one record, and the ring's last R survive to
    the boundary fetch."""
    n = cfg.n

    def cond(carry):
        _, _, _, steps, decided, _ = carry
        return (~decided) & (steps < max_steps)

    edge_masks = _edge_masks(cfg, state, faults)

    def body(carry):
        state, telem, trace, steps, _, _ = carry
        round_state, decided, winner_mask, _, telem, trace = _compute_round(
            cfg, state, faults, edge_masks, telem, trace
        )
        return (round_state, telem, trace, steps + 1, decided, winner_mask)

    init = (
        state, telem, trace, jnp.int32(0), jnp.bool_(False),
        jnp.zeros((n,), dtype=bool),
    )
    state, telem, trace, steps, decided, winner = jax.lax.while_loop(
        cond, body, init
    )
    state = jax.lax.cond(
        decided,
        lambda s: apply_view_change_impl(cfg, s, winner),
        lambda s: s,
        state,
    )
    return (state, telem, trace, steps, decided, winner)


run_to_decision_trace = jax.jit(
    run_to_decision_trace_impl, static_argnums=(0,), donate_argnums=(1, 2, 3)
)


def run_until_membership_impl(
    cfg: EngineConfig,
    state: EngineState,
    faults: FaultInputs,
    target,
    max_steps,
    max_cuts,
    min_cuts,
):
    """Protocol rounds through MULTIPLE view changes until the membership
    reaches ``target`` — one device dispatch for a whole churn/bootstrap
    wave instead of one per cut.

    Structure: an outer loop of convergences, each of which (a) runs the
    same sort-free inner round loop as ``run_to_decision_impl`` over the
    hoisted per-edge masks, and (b) applies the view change WITH the
    per-edge mask rebuild inside the same lax.cond (topology and the
    implicit-alert stamps change only when a cut commits, so the mask
    pack + permutation gathers are per-CUT work in a gated branch — the
    compiled hot loop stays reduce-class on every mesh, which the
    device_program gate freezes). On a tunnel/remote backend each
    dispatch+fetch pair costs a full RTT, so resolving a 2-cut churn or a
    bootstrap admission wave in one dispatch removes that many round
    trips from the measured wall clock (EVALUATION.md §1's
    device_rtt_ms).

    Returns (state, total_steps, cuts_committed, resolved, sizes) where
    ``sizes[i]`` is the membership after the i-th committed cut (-1 beyond
    ``cuts``) — the paper's Table 1 "intermediate views" instrument,
    observed without any per-cut fetch. ``max_cuts`` is static (it sizes
    the sizes buffer). ``min_cuts`` guards the equal-churn trap: a wave of
    J joins + J crashes TARGETS the starting membership, so "membership ==
    target" alone would resolve vacuously before the first cut — requiring
    at least min_cuts committed cuts makes the loop actually run the churn.
    """
    n = cfg.n

    def outer_cond(carry):
        state, steps, cuts, stalled, _, _ = carry
        resolved = (state.n_members == target) & (cuts >= min_cuts)
        return (~resolved) & (~stalled) & (steps < max_steps) & (cuts < max_cuts)

    def outer_body(carry):
        state, steps, cuts, _, sizes, edge_masks = carry

        def inner_cond(carry):
            _, steps, decided, _ = carry
            return (~decided) & (steps < max_steps)

        def inner_body(carry):
            state, steps, _, _ = carry
            round_state, decided, winner_mask, _ = _compute_round(
                cfg, state, faults, edge_masks
            )
            return (round_state, steps + 1, decided, winner_mask)

        init = (state, steps, jnp.bool_(False), jnp.zeros((n,), dtype=bool))
        state, steps, decided, winner = jax.lax.while_loop(
            inner_cond, inner_body, init
        )
        # The view change AND the per-edge mask rebuild ride one cond:
        # topology (and with it the observer-active/delivery masks) changes
        # ONLY when a cut commits, so the mask rebuild's pack + permutation
        # gathers are per-CUT work, gated exactly like the ring rebuild —
        # never unconditional hot-loop traffic (the compiled-program gate
        # pins this: the wave's hot loop stays reduce-class on both the 1-D
        # and the 2-D mesh).
        def commit(s):
            s2 = apply_view_change_impl(cfg, s, winner)
            return s2, _edge_masks(cfg, s2, faults)

        state, edge_masks = jax.lax.cond(
            decided, commit, lambda s: (s, edge_masks), state
        )
        sizes = jnp.where(
            decided, sizes.at[cuts].set(state.n_members), sizes
        )
        # A convergence that ran out of budget undecided cannot make further
        # progress (the outer loop would spin): latch and exit.
        return (state, steps, cuts + decided.astype(jnp.int32), ~decided, sizes, edge_masks)

    init = (
        state,
        jnp.int32(0),
        jnp.int32(0),
        jnp.bool_(False),
        jnp.full((max_cuts,), -1, dtype=jnp.int32),
        _edge_masks(cfg, state, faults),
    )
    state, steps, cuts, stalled, sizes, _ = jax.lax.while_loop(
        outer_cond, outer_body, init
    )
    resolved = (state.n_members == target) & (cuts >= min_cuts)
    return (state, steps, cuts, resolved, sizes)


run_until_membership = jax.jit(
    run_until_membership_impl, static_argnums=(0, 5), donate_argnums=(1,)
)


def run_until_membership_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
    target,
    max_steps,
    max_cuts,
    min_cuts,
):
    """:func:`run_until_membership_impl` with the telemetry lanes joining
    both loop carries (separate entrypoint; same rationale as
    :func:`engine_step_telem_impl`). Telemetry accumulates ACROSS the
    wave's view changes — the lanes are never reset by a commit, so a
    multi-cut wave reads as one activity story."""
    n = cfg.n

    def outer_cond(carry):
        state, _, steps, cuts, stalled, _, _ = carry
        resolved = (state.n_members == target) & (cuts >= min_cuts)
        return (~resolved) & (~stalled) & (steps < max_steps) & (cuts < max_cuts)

    def outer_body(carry):
        state, telem, steps, cuts, _, sizes, edge_masks = carry

        def inner_cond(carry):
            _, _, steps, decided, _ = carry
            return (~decided) & (steps < max_steps)

        def inner_body(carry):
            state, telem, steps, _, _ = carry
            round_state, decided, winner_mask, _, telem = _compute_round(
                cfg, state, faults, edge_masks, telem
            )
            return (round_state, telem, steps + 1, decided, winner_mask)

        init = (state, telem, steps, jnp.bool_(False), jnp.zeros((n,), dtype=bool))
        state, telem, steps, decided, winner = jax.lax.while_loop(
            inner_cond, inner_body, init
        )

        def commit(s):
            s2 = apply_view_change_impl(cfg, s, winner)
            return s2, _edge_masks(cfg, s2, faults)

        state, edge_masks = jax.lax.cond(
            decided, commit, lambda s: (s, edge_masks), state
        )
        sizes = jnp.where(
            decided, sizes.at[cuts].set(state.n_members), sizes
        )
        return (
            state, telem, steps, cuts + decided.astype(jnp.int32), ~decided,
            sizes, edge_masks,
        )

    init = (
        state,
        telem,
        jnp.int32(0),
        jnp.int32(0),
        jnp.bool_(False),
        jnp.full((max_cuts,), -1, dtype=jnp.int32),
        _edge_masks(cfg, state, faults),
    )
    state, telem, steps, cuts, stalled, sizes, _ = jax.lax.while_loop(
        outer_cond, outer_body, init
    )
    resolved = (state.n_members == target) & (cuts >= min_cuts)
    return (state, telem, steps, cuts, resolved, sizes)


run_until_membership_telem = jax.jit(
    run_until_membership_telem_impl, static_argnums=(0, 6), donate_argnums=(1, 2)
)


def run_until_membership_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
    target,
    max_steps,
    max_cuts,
    min_cuts,
):
    """:func:`run_until_membership_telem_impl` with the trace ring joining
    both loop carries. Like the telemetry lanes the ring is never reset by a
    commit — a multi-cut wave decodes as one round-indexed story, the epoch
    stamp marking where each view change landed."""
    n = cfg.n

    def outer_cond(carry):
        state, _, _, steps, cuts, stalled, _, _ = carry
        resolved = (state.n_members == target) & (cuts >= min_cuts)
        return (~resolved) & (~stalled) & (steps < max_steps) & (cuts < max_cuts)

    def outer_body(carry):
        state, telem, trace, steps, cuts, _, sizes, edge_masks = carry

        def inner_cond(carry):
            _, _, _, steps, decided, _ = carry
            return (~decided) & (steps < max_steps)

        def inner_body(carry):
            state, telem, trace, steps, _, _ = carry
            round_state, decided, winner_mask, _, telem, trace = _compute_round(
                cfg, state, faults, edge_masks, telem, trace
            )
            return (round_state, telem, trace, steps + 1, decided, winner_mask)

        init = (
            state, telem, trace, steps, jnp.bool_(False),
            jnp.zeros((n,), dtype=bool),
        )
        state, telem, trace, steps, decided, winner = jax.lax.while_loop(
            inner_cond, inner_body, init
        )

        def commit(s):
            s2 = apply_view_change_impl(cfg, s, winner)
            return s2, _edge_masks(cfg, s2, faults)

        state, edge_masks = jax.lax.cond(
            decided, commit, lambda s: (s, edge_masks), state
        )
        sizes = jnp.where(
            decided, sizes.at[cuts].set(state.n_members), sizes
        )
        return (
            state, telem, trace, steps, cuts + decided.astype(jnp.int32),
            ~decided, sizes, edge_masks,
        )

    init = (
        state,
        telem,
        trace,
        jnp.int32(0),
        jnp.int32(0),
        jnp.bool_(False),
        jnp.full((max_cuts,), -1, dtype=jnp.int32),
        _edge_masks(cfg, state, faults),
    )
    state, telem, trace, steps, cuts, stalled, sizes, _ = jax.lax.while_loop(
        outer_cond, outer_body, init
    )
    resolved = (state.n_members == target) & (cuts >= min_cuts)
    return (state, telem, trace, steps, cuts, resolved, sizes)


run_until_membership_trace = jax.jit(
    run_until_membership_trace_impl, static_argnums=(0, 7), donate_argnums=(1, 2, 3)
)


class VirtualCluster(DispatchSeam):
    """Host driver around the device engine: owns the state, injects faults
    and join waves, and runs rounds until convergence.

    This is the deployment the BASELINE targets: N virtual Rapid endpoints
    co-located on TPU hosts, alerts/votes as device-array writes.

    The telemetry seams (transfer accounting, the phase-validated
    ``_dispatch`` timer) are the shared :class:`DispatchSeam` — one
    vocabulary across this driver, the fleet, and the streaming pipeline.
    """

    def __init__(self, cfg: EngineConfig, state: EngineState):
        self.cfg = cfg
        self.state = state
        self.faults = FaultInputs.none(cfg)
        self._rng = np.random.default_rng(0)
        # Engine-level telemetry: host-side counters over device dispatches
        # (the per-node flight recorder has no device analog — the engine's
        # observability grain is the dispatch, not the message). Compile
        # events are process-global (one XLA cache per process), captured by
        # the engine_telemetry collector and read at snapshot time.
        self.metrics = Metrics()
        # Attached by rapid_tpu.serving.StreamDriver: the streaming pipeline
        # surfaces its sustained-throughput stats through this cluster's
        # telemetry snapshot (None = batch-only driver, no stream section).
        self.stream = None
        # Attached by rapid_tpu.serving.supervisor.Supervisor: the
        # self-healing tier's checkpoint/retry/wedge stats (None = no
        # supervision, no recovery section).
        self.recovery = None
        # Device telemetry plane (cfg.telemetry == 1): the lanes live on
        # device beside the state; the host keeps only a digest cache,
        # zero-minted at attach (the exposition series exist from the first
        # scrape, never mid-run) and refreshed ONLY at host-sync boundaries.
        self.telem = initial_telemetry(cfg) if cfg.telemetry else None
        self._activity = (
            engine_telemetry.zero_activity_summary(cfg.n, cfg.c)
            if cfg.telemetry
            else None
        )
        # Device round-trace ring (cfg.trace == R > 0): a refinement of the
        # telemetry plane — its active-subject record reuses the telemetry
        # block's reduction, so a ring without the plane has nothing to
        # record from. Not an assert: python -O must not skip this.
        if cfg.trace and not cfg.telemetry:
            raise ValueError(
                "EngineConfig.trace requires telemetry: the round-trace ring "
                "refines the telemetry plane (pass telemetry=True)"
            )
        if cfg.trace < 0:
            raise ValueError(f"trace capacity must be >= 0, got {cfg.trace}")
        self.trace_ring = initial_trace(cfg) if cfg.trace else None
        self._trace = (
            engine_telemetry.zero_trace_summary(cfg.trace)
            if cfg.trace
            else None
        )
        engine_telemetry.install()

    # -- construction ---------------------------------------------------

    @classmethod
    def create(
        cls,
        n_members: int,
        n_slots: Optional[int] = None,
        k: int = 10,
        h: int = 9,
        l: int = 4,
        cohorts: int = 2,
        fd_threshold: int = 3,
        seed: int = 0,
        use_pallas: bool = False,
        fallback_rounds: int = 8,
        delivery_spread: int = 0,
        concurrent_coordinators: int = 1,
        fd_window: int = 0,
        delivery_prob_permille: int = 1000,
        pallas_lanes: int = 128,
        compact: bool = False,
        telemetry: bool = False,
        trace: int = 0,
    ) -> "VirtualCluster":
        """Synthetic cluster: slot identities are random 64-bit lanes (the
        host never materializes 100K endpoint strings; interop deployments
        use from_endpoints). ``compact=True`` stores the engine state at
        the config-derived narrow dtypes (models/state.compaction_policy)
        — bit-identical protocol behavior, a fraction of the bytes/member
        (the wide layout stays the differential oracle). ``telemetry=True``
        carries the device telemetry plane (models/state.TelemetryLanes)
        through every round — engine results stay bit-identical; off, the
        compiled programs are byte-identical to a pre-telemetry engine.
        ``trace=R`` (requires telemetry) additionally records the last R
        rounds into the device round-trace ring (models/state.TraceRing) —
        same bit-identity and byte-identity contracts, pinned by
        tests/test_trace_ring.py."""
        n = n_slots if n_slots is not None else n_members
        assert n >= n_members
        _validate_delivery_prob(delivery_prob_permille)
        cfg = EngineConfig(
            n=n, k=k, h=h, l=l, c=cohorts, fd_threshold=fd_threshold,
            use_pallas=use_pallas, fallback_rounds=fallback_rounds,
            delivery_spread=delivery_spread,
            concurrent_coordinators=concurrent_coordinators,
            fd_window=fd_window,
            delivery_prob_permille=delivery_prob_permille,
            pallas_lanes=pallas_lanes,
            compact=int(compact),
            telemetry=int(telemetry),
            trace=int(trace),
        )
        rng = np.random.default_rng(seed)
        key_hi = rng.integers(0, 2**32, size=(k, n), dtype=np.uint32)
        key_lo = rng.integers(0, 2**32, size=(k, n), dtype=np.uint32)
        id_hi = rng.integers(0, 2**32, size=(n,), dtype=np.uint32)
        id_lo = rng.integers(0, 2**32, size=(n,), dtype=np.uint32)
        alive = np.zeros(n, dtype=bool)
        alive[:n_members] = True
        cluster = cls(cfg, initial_state(cfg, key_hi, key_lo, id_hi, id_lo, alive))
        cluster._rng = rng
        cluster._account_h2d(key_hi, key_lo, id_hi, id_lo, alive)
        return cluster

    @classmethod
    def from_endpoints(
        cls,
        endpoints: Sequence,
        n_slots: Optional[int] = None,
        k: int = 10,
        h: int = 9,
        l: int = 4,
        cohorts: int = 2,
        fd_threshold: int = 3,
        use_pallas: bool = False,
        fallback_rounds: int = 8,
        delivery_spread: int = 0,
        concurrent_coordinators: int = 1,
        fd_window: int = 0,
        delivery_prob_permille: int = 1000,
        pallas_lanes: int = 128,
        n_members: Optional[int] = None,
        topology: str = "native",
        compact: bool = False,
        telemetry: bool = False,
        trace: int = 0,
    ) -> "VirtualCluster":
        """Build from real endpoints with the host view's exact ring keys, so
        the engine's topology matches a host MembershipView bit-for-bit.

        ``n_members`` (default: all) marks how many of ``endpoints`` start as
        live members; the rest become keyed-but-dead slots reserved for a
        later ``inject_join_wave`` — their ring keys are already the host
        view's keys for those endpoints, so a join admits them at exactly the
        ring positions the host stack would.

        Callers pairing the engine with a host ``MembershipView`` must thread
        ``topology=view.topology``: the engine's u64 keyspace cannot
        represent the java-compat signed ring order, so java mode is
        rejected (``endpoint_ring_keys``). The parameter defaults to native —
        the only mode the engine supports — so a caller that omits it while
        holding a java view still diverges; threading the view's mode is
        what turns that into a loud failure."""
        if n_members is None:
            n_members = len(endpoints)
        if not 0 < n_members <= len(endpoints):
            # Not an assert: python -O must not skip this — slots past the
            # keyed endpoints would go live with all-zero ring keys.
            raise ValueError(
                f"n_members must be in [1, {len(endpoints)}], got {n_members}"
            )
        n = n_slots if n_slots is not None else len(endpoints)
        _validate_delivery_prob(delivery_prob_permille)
        cfg = EngineConfig(
            n=n, k=k, h=h, l=l, c=cohorts, fd_threshold=fd_threshold,
            use_pallas=use_pallas, fallback_rounds=fallback_rounds,
            delivery_spread=delivery_spread,
            concurrent_coordinators=concurrent_coordinators,
            fd_window=fd_window,
            delivery_prob_permille=delivery_prob_permille,
            pallas_lanes=pallas_lanes,
            compact=int(compact),
            telemetry=int(telemetry),
            trace=int(trace),
        )
        key_hi0, key_lo0 = endpoint_ring_keys(endpoints, k, topology=topology)
        key_hi = np.zeros((k, n), dtype=np.uint32)
        key_lo = np.zeros((k, n), dtype=np.uint32)
        key_hi[:, : len(endpoints)] = np.asarray(key_hi0)
        key_lo[:, : len(endpoints)] = np.asarray(key_lo0)
        rng = np.random.default_rng(1234)
        id_hi = rng.integers(0, 2**32, size=(n,), dtype=np.uint32)
        id_lo = rng.integers(0, 2**32, size=(n,), dtype=np.uint32)
        alive = np.zeros(n, dtype=bool)
        alive[:n_members] = True
        cluster = cls(cfg, initial_state(cfg, key_hi, key_lo, id_hi, id_lo, alive))
        cluster._account_h2d(key_hi, key_lo, id_hi, id_lo, alive)
        return cluster

    # -- fault & membership injection ----------------------------------

    def _slot_index(self, slots: Sequence[int]) -> jnp.ndarray:
        """Host-side bounds check, then upload. jnp's gather/scatter CLAMPS
        out-of-range indices instead of raising (a typo'd slot would silently
        inspect/mutate slot n-1), so every lifecycle mutation validates on
        host where it is free — no extra fetch, the indices originate here."""
        arr = np.asarray(slots, dtype=np.int32)
        if arr.size and (arr.min() < 0 or arr.max() >= self.cfg.n):
            raise IndexError(
                f"slot indices out of range [0, {self.cfg.n}): "
                f"{arr[(arr < 0) | (arr >= self.cfg.n)].tolist()}"
            )
        self._account_h2d(arr)
        return jnp.asarray(arr)

    def crash(self, slots: Sequence[int]) -> None:
        """Crash-stop the given slots (unresponsive until revived). Device-side
        scatter: only the slot indices cross the host->device boundary."""
        idx = self._slot_index(slots)
        self.faults = self.faults._replace(crashed=self.faults.crashed.at[idx].set(True))

    def revive(self, slots: Sequence[int]) -> None:
        idx = self._slot_index(slots)
        self.faults = self.faults._replace(crashed=self.faults.crashed.at[idx].set(False))

    def _stamp_fired_edges(self, idx: jnp.ndarray, edge_mask) -> None:
        """Mark (slot, ring) edges as fired at the current round (device-side
        scatter — only slot indices and the [j, k] mask cross the boundary);
        the round body's delivery machinery then applies per-cohort rx-block
        masks and delay jitter. Shared by join waves and leaves, which pass
        the ALREADY-UPLOADED bounds-checked index array (an np.asarray here
        would round-trip it back through the host)."""
        state = self.state
        if isinstance(edge_mask, np.ndarray):
            # Host-originated mask: a real upload. A device-resident mask
            # (the join wave's pred-derived bools) uploads nothing — and
            # materializing it here just to count bytes would pay exactly
            # the D2H round trip this path exists to avoid.
            self._account_h2d(edge_mask)
        em = jnp.asarray(edge_mask)  # [j, k] bool
        rdt = state.fire_round.dtype  # policy round dtype + its sentinel
        pol = compaction_policy(self.cfg)
        self.state = state._replace(
            fd_fired=state.fd_fired.at[idx].set(em),
            fire_round=state.fire_round.at[idx].set(
                jnp.where(
                    em,
                    state.round_idx.astype(rdt),
                    jnp.asarray(pol.fire_never, rdt),
                )
            ),
        )

    def initiate_leave(self, slots: Sequence[int]) -> None:
        """Graceful batched leave: the LEAVER broadcasts its own departure as
        a DOWN alert on every ring (LeaveMessage semantics,
        MembershipService.java:296-307) — no fd_threshold detection delay.
        The alert source is the leaver itself, so each slot becomes its own
        column's observer: per-cohort delivery gates on hearing the LEAVER
        (not its ring observers), exactly like the reference's self-broadcast.
        Leavers also stop responding (crashed), so they cannot vote in their
        own eviction. Implicit-invalidation observers (inval_obs) keep the
        real ring topology."""
        slots = np.asarray(slots, dtype=np.int32)
        state = self.state
        idx = self._slot_index(slots)
        self.state = state._replace(
            obs_idx=state.obs_idx.at[:, idx].set(
                jnp.broadcast_to(
                    idx[None, :], (self.cfg.k, len(slots))
                ).astype(state.obs_idx.dtype)
            )
        )
        self._stamp_fired_edges(idx, np.ones((len(slots), self.cfg.k), dtype=bool))
        # Inline crash scatter with the already-validated, already-uploaded
        # index (a self.crash(slots) call would bounds-check and upload again).
        self.faults = self.faults._replace(crashed=self.faults.crashed.at[idx].set(True))

    def set_flaky_edges(self, probe_fail: np.ndarray) -> None:
        """Arbitrary per-(subject, ring) probe failures — asymmetric/one-way
        link patterns."""
        # Cast on host first: what crosses the boundary (and what the byte
        # counter charges) is the 1-byte bool array, not the caller's dtype.
        arr = np.asarray(probe_fail, dtype=bool)
        self._account_h2d(arr)
        self.faults = self.faults._replace(probe_fail=jnp.asarray(arr))

    def stagger_fd_counts(self, rng: np.random.Generator, spread_rounds: int) -> None:
        """Randomize per-edge detection latency: failure detectors fire up to
        ``spread_rounds`` rounds apart (negative initial counters). This is
        the engine's analog of real-world detection jitter — the source of
        almost-everywhere-agreement conflicts the H/L watermarks absorb."""
        cdt = np.dtype(compaction_policy(self.cfg).counter)
        if spread_rounds >= np.iinfo(cdt).max:
            # Not an assert: python -O must not skip this — a wrapped offset
            # would silently invert the jitter direction.
            raise ValueError(
                f"spread_rounds {spread_rounds} exceeds the fd_count "
                f"envelope of the {cdt.name} compaction policy"
            )
        offsets = rng.integers(0, spread_rounds + 1, size=(self.cfg.n, self.cfg.k))
        # Cast host-side first: the byte counter charges what actually
        # uploads (the policy-dtype lane, not the rng's int64 draw).
        narrowed = (-offsets).astype(cdt)
        self._account_h2d(narrowed)
        self.state = self.state._replace(fd_count=jnp.asarray(narrowed))

    def inject_join_wave(
        self, slots: Sequence[int], check_admissible: bool = True
    ) -> None:
        """Admit a batch of joiners: their gatekeepers (ring predecessors)
        emit UP alerts on all rings at once — the batched equivalent of the
        two-phase join's phase 2 (Cluster.java:406-437).

        The UP alerts ride the SAME delivery machinery as DOWN alerts: the
        gatekeeper becomes the joiner slot's observer (`obs_idx`), the edge
        is marked fired this round, and ``_deliver_alerts`` then applies the
        per-cohort rx-block masks and delivery-delay jitter — so receivers
        diverge on join reports exactly as they do on failure reports.

        Rejoin discipline: a node returning after removal must be admitted
        through a FRESH slot (new identity lanes), never by re-admitting its
        old slot — slot identities are the engine's UUIDs, and reusing one
        would reproduce a previous configuration id (the reference rejects
        reused UUIDs outright, UUIDAlreadySeenError).

        ``check_admissible=False`` skips the [j]-bool admissibility fetch —
        the streaming pipeline's spelling (rapid_tpu/serving): that fetch is
        a host sync that would stall every enqueued wave behind it, and the
        stream's churn generator already owns the slot bookkeeping (fresh
        slots only, never reused). Callers without that host-side guarantee
        must keep the check: an inadmissible joiner silently replays an old
        configuration id."""
        slots = np.asarray(slots)
        state = self.state
        idx = self._slot_index(slots)
        if check_admissible:
            # Enforce the rejoin discipline host-side (the engine's
            # UUIDAlreadySeenError): current members, already-pending
            # joiners, and retired identity lanes are not admissible. Index
            # on device first so the ONE device->host fetch (a full tunnel
            # round trip) carries [j] bools, not the whole [n] state.
            bad = np.asarray((state.alive | state.join_pending | state.retired)[idx])
            self._account_d2h(bad.nbytes)
            if bad.any():
                raise ValueError(
                    f"slots not admissible as joiners (member/pending/retired): "
                    f"{slots[bad].tolist()}"
                )

        # Expected observers (gatekeepers) of each joiner: the alive ring
        # predecessors of its keys. Everything below is device-side
        # gather/scatter — only the slot indices cross the boundary, which
        # is what keeps a bootstrap wave from paying O(k*n) tunnel traffic.
        pred = predecessor_of_keys(
            state.key_hi, state.key_lo, state.alive,
            state.key_hi[:, idx], state.key_lo[:, idx],
            perm=state.ring_perm,  # sort-free: this sits in bootstrap's timed path
        )  # [k, j]

        # The gatekeeper IS the joiner's observer pre-admission (for both
        # alert delivery and implicit invalidation). predecessor_of_keys
        # computes at int32; the scatter narrows to the lane's policy dtype.
        pred_n = pred.astype(state.obs_idx.dtype)
        self.state = state._replace(
            join_pending=state.join_pending.at[idx].set(True),
            obs_idx=state.obs_idx.at[:, idx].set(pred_n),
            inval_obs=state.inval_obs.at[:, idx].set(pred_n),
        )
        # Mark each (joiner, ring) edge as fired now where a gatekeeper
        # exists; delivery (rx-block + jitter) happens in the round body.
        self._stamp_fired_edges(idx, (pred >= 0).T)

    def assign_cohorts(self, cohort_of: np.ndarray) -> None:
        # Host-side cast first so the transfer counter charges the bytes
        # that actually upload — the policy's cohort-index dtype (int32
        # wide, int8/int16 compact), not the caller's int64.
        arr = np.asarray(
            cohort_of, dtype=np.dtype(compaction_policy(self.cfg).cohort)
        )
        self._account_h2d(arr)
        self.state = self.state._replace(cohort_of=jnp.asarray(arr))

    def assign_cohorts_roundrobin(self) -> None:
        """Spread the N slots evenly over the C receiver cohorts — the
        sampled-divergence deployment: each cohort is an independently
        jittered receiver whose fast-round vote is shared by ~N/C members."""
        self.assign_cohorts(np.arange(self.cfg.n, dtype=np.int32) % self.cfg.c)

    def set_rx_block(self, rx_block: np.ndarray) -> None:
        """Change per-cohort receive blocking. Re-stamps every fired edge to
        the current round: the round body cond-skips delivery work once all
        fired alerts have matured (their delivered set is static while
        rx-blocks are fixed), so healing a partition mid-configuration must
        re-open delivery or newly-hearable cohorts would never receive the
        old alerts. Re-stamped alerts redeliver within ``delivery_spread``
        rounds — a re-broadcast after the topology change."""
        arr = np.asarray(rx_block, dtype=bool)  # charge the uploaded width
        self._account_h2d(arr)
        self.faults = self.faults._replace(rx_block=jnp.asarray(arr))
        self.state = self.state._replace(
            fire_round=jnp.where(
                self.state.fd_fired,
                self.state.round_idx.astype(self.state.fire_round.dtype),
                self.state.fire_round,
            )
        )

    # -- execution ------------------------------------------------------

    def _step(self, phase: str) -> StepEvents:
        """ONE body for both step spellings: only the dispatch-phase label
        differs, so a change here cannot diverge the streamed path from the
        batch path the bit-identity tests pin."""
        self.metrics.inc("engine_steps")
        self.metrics.inc("engine_convergence_steps")
        with self._dispatch(phase):
            if self.trace_ring is not None:
                self.state, self.telem, self.trace_ring, events = engine_step_trace(
                    self.cfg, self.state, self.telem, self.trace_ring, self.faults
                )
            elif self.telem is not None:
                self.state, self.telem, events = engine_step_telem(
                    self.cfg, self.state, self.telem, self.faults
                )
            else:
                self.state, events = engine_step(self.cfg, self.state, self.faults)
        return events

    def step(self) -> StepEvents:
        return self._step("step")

    def stream_step(self) -> StepEvents:
        """One ENQUEUED engine round for the streaming pipeline
        (rapid_tpu/serving): the same compiled ``engine_step`` program as
        :meth:`step` — bit-identical math — accounted under the
        ``stream_enqueue`` phase and guaranteed fetch-free, so the host
        returns as soon as JAX has queued the dispatch. The returned events
        stay device-resident (they are the stream driver's completion
        ticket); reading them here would put a host sync on the pipeline."""
        return self._step("stream_enqueue")

    def sync(self) -> int:
        """Force completion of all pending uploads/compute on the cluster
        state and return a cheap checksum (``sync_checksum_impl`` — one
        compiled dispatch, audited by the device_program gate)."""
        with self._dispatch("sync"):
            checksum = int(sync_checksum(self.state, self.faults))
        self._account_d2h(4)
        self._refresh_activity()
        return checksum

    def _refresh_activity(self) -> None:
        """Fetch the telemetry digest and refresh the host-side activity
        cache. Called ONLY from host-sync boundaries (sync / stream drain /
        fleet health scans) — the cache, not the device lanes, is what
        ``telemetry_snapshot`` reads, so scrapes never add a device fetch."""
        if self.telem is None:
            return
        # telemetry-fetch-ok: sync barrier — the driver is already paying a
        # blocking device round trip here.
        digest = np.asarray(telemetry_digest(self.telem))
        self._account_d2h(digest.nbytes)
        self._activity = engine_telemetry.activity_summary(
            digest, self.cfg.n, self.cfg.c
        )
        if self.trace_ring is not None:
            # telemetry-fetch-ok: sync barrier — same blocking round trip.
            tdigest = np.asarray(trace_digest(self.trace_ring))
            self._account_d2h(tdigest.nbytes)
            self._trace = engine_telemetry.trace_summary(tdigest, self.cfg.trace)

    @property
    def activity(self) -> Optional[dict]:
        """The last host-sync boundary's activity summary (a copy), or
        None on a telemetry=0 engine — reading it never touches the
        device."""
        return dict(self._activity) if self._activity is not None else None

    @property
    def trace(self) -> Optional[dict]:
        """The last host-sync boundary's decoded trace-ring summary (a
        copy; ``records`` oldest -> newest with global round ordinals), or
        None on a trace=0 engine — reading it never touches the device."""
        if self._trace is None:
            return None
        out = dict(self._trace)
        out["records"] = [dict(r) for r in self._trace["records"]]
        return out

    def run_until_converged(self, max_steps: int = 64) -> Tuple[int, Optional[StepEvents]]:
        """Run rounds until a view change commits; returns (rounds, events)."""
        for round_idx in range(max_steps):
            events = self.step()
            if bool(events.decided):
                return round_idx + 1, events
        return max_steps, None

    def run_to_decision(self, max_steps: int = 64) -> Tuple[int, bool, jnp.ndarray, int]:
        """Single-dispatch convergence: the whole round loop runs on device
        (lax.while_loop); returns (rounds, decided, winner_mask, n_members).
        The winner mask stays on device — every scalar observation travels in
        ONE packed fetch (a device->host fetch is a full tunnel round trip),
        including the post-cut membership so churn loops don't pay an extra
        RTT per view change."""
        if max_steps > 255:  # not an assert: python -O must not skip this
            raise ValueError(f"max_steps packs into 8 bits, got {max_steps}")
        with self._dispatch("run_to_decision"):
            if self.trace_ring is not None:
                (
                    self.state, self.telem, self.trace_ring, steps, decided,
                    winner,
                ) = run_to_decision_trace(
                    self.cfg, self.state, self.telem, self.trace_ring,
                    self.faults, jnp.int32(max_steps),
                )
            elif self.telem is not None:
                self.state, self.telem, steps, decided, winner = run_to_decision_telem(
                    self.cfg, self.state, self.telem, self.faults,
                    jnp.int32(max_steps),
                )
            else:
                self.state, steps, decided, winner = run_to_decision(
                    self.cfg, self.state, self.faults, jnp.int32(max_steps)
                )
            if self.cfg.n < (1 << 22):
                # Layout: bits 0-7 steps, bit 8 decided, bits 9-30 membership
                # — one scalar fetch total.
                packed = int(
                    steps
                    | (decided.astype(jnp.int32) << 8)
                    | (self.state.n_members << 9)
                )
                self._account_d2h(4)
                rounds = packed & 0xFF
                was_decided = bool((packed >> 8) & 1)
                members = packed >> 9
            else:
                # Membership no longer fits beside the flags in a positive
                # int32: pay a second fetch rather than return garbage.
                packed = int(steps | (decided.astype(jnp.int32) << 8))
                self._account_d2h(8)
                rounds = packed & 0xFF
                was_decided = bool(packed >> 8)
                members = int(self.state.n_members)
        self.metrics.inc("engine_convergence_steps", rounds)
        if was_decided:
            self.metrics.inc("engine_cuts_committed")
        return rounds, was_decided, winner, members

    def run_until_membership(
        self, target: int, max_steps: int = 192, max_cuts: int = 8,
        min_cuts: int = 0,
    ) -> Tuple[int, int, bool, Tuple[int, ...]]:
        """Multi-cut single-dispatch: run convergences — view changes
        applied ON DEVICE between them — until the membership reaches
        ``target``; returns (rounds, cuts_committed, resolved,
        intermediate_sizes).

        A churn that resolves in two cuts, or a bootstrap admission wave of
        several, costs ONE dispatch and ONE small fetch instead of one
        dispatch+fetch per cut — each saved pair is a full tunnel RTT
        (~69 ms on the dev tunnel, EVALUATION.md §1). The observation comes
        back as one small int32 vector (a 16+4*max_cuts-byte transfer is
        the same round trip a packed scalar is); intermediate_sizes is the
        membership after each committed cut — the paper's Table 1
        "intermediate views" instrument for free."""
        if not 0 <= target <= self.cfg.n:
            # Not an assert: python -O must not skip this.
            raise ValueError(f"target must be in [0, {self.cfg.n}]: {target}")
        with self._dispatch("run_until_membership"):
            if self.trace_ring is not None:
                (
                    self.state, self.telem, self.trace_ring, steps, cuts,
                    resolved, sizes,
                ) = run_until_membership_trace(
                    self.cfg, self.state, self.telem, self.trace_ring,
                    self.faults, jnp.int32(target), jnp.int32(max_steps),
                    int(max_cuts), jnp.int32(min_cuts),
                )
            elif self.telem is not None:
                self.state, self.telem, steps, cuts, resolved, sizes = (
                    run_until_membership_telem(
                        self.cfg, self.state, self.telem, self.faults,
                        jnp.int32(target), jnp.int32(max_steps), int(max_cuts),
                        jnp.int32(min_cuts),
                    )
                )
            else:
                self.state, steps, cuts, resolved, sizes = run_until_membership(
                    self.cfg, self.state, self.faults,
                    jnp.int32(target), jnp.int32(max_steps), int(max_cuts),
                    jnp.int32(min_cuts),
                )
            obs = np.asarray(
                jnp.concatenate(
                    [jnp.stack([steps, cuts, resolved.astype(jnp.int32)]), sizes]
                )
            )
        self._account_d2h(obs.nbytes)
        n_cuts = int(obs[1])
        self.metrics.inc("engine_convergence_steps", int(obs[0]))
        self.metrics.inc("engine_cuts_committed", n_cuts)
        return int(obs[0]), n_cuts, bool(obs[2]), tuple(obs[3 : 3 + n_cuts].tolist())

    def timed_convergence(self, max_steps: int = 64) -> Tuple[int, float]:
        """(rounds, wall_ms) for a convergence run, excluding compilation
        (callers should run one throwaway convergence first to warm the
        cache)."""
        start = time.perf_counter()
        rounds, events = self.run_until_converged(max_steps)
        jax.block_until_ready(self.state.alive)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        assert events is not None, "did not converge"
        return rounds, elapsed_ms

    # -- observers ------------------------------------------------------

    @property
    def membership_size(self) -> int:
        self._account_d2h(4)
        return int(self.state.n_members)

    @property
    def alive_mask(self) -> np.ndarray:
        mask = np.asarray(self.state.alive)
        self._account_d2h(mask.nbytes)
        return mask

    @property
    def config_epoch(self) -> int:
        self._account_d2h(4)
        return int(self.state.config_epoch)

    @property
    def config_id(self) -> int:
        self._account_d2h(8)
        return (int(self.state.config_hi) << 32) | int(self.state.config_lo)

    # -- observability (utils/exposition.py schema) ---------------------

    def health(self) -> NodeHealth:
        """Cluster-wide health of the N virtual members, in the same
        vocabulary host nodes report (utils/health.py). The engine executes
        every node's round in one fused program, so its aggregate IS the
        cluster view: churn still in flight — a crashed slot not yet evicted
        or a join wave not yet admitted — reads PROPOSING (alerts, cut
        detection, and consensus all progress each round); otherwise STABLE.
        One packed scalar fetch."""
        pending = int(
            jnp.sum(self.state.alive & self.faults.crashed, dtype=jnp.int32)
            + jnp.sum(self.state.join_pending, dtype=jnp.int32)
        )
        self._account_d2h(4)
        return NodeHealth.PROPOSING if pending else NodeHealth.STABLE

    def telemetry_snapshot(self) -> dict:
        """The engine's unified telemetry snapshot — same schema as
        ``MembershipService.telemetry_snapshot`` minus the per-message
        instruments (transport stats, flight recorder) that have no device
        analog, so one scrape pipeline serves host nodes and the engine
        alike. The ``engine`` section carries the device-tier instruments:
        process-wide compile/persistent-cache stats (engine_telemetry) and
        best-effort device memory gauges; dispatch latency histograms and
        transfer-byte counters ride the ordinary ``metrics`` section."""
        return {
            "node": f"virtual-cluster/{self.cfg.n}",
            "configuration_id": self.config_id,
            "membership_size": self.membership_size,
            "health": self.health().value,
            "config_epoch": self.config_epoch,
            "metrics": self.metrics.summary(),
            "engine": {
                "n": self.cfg.n,
                "cohorts": self.cfg.c,
                "use_pallas": self.cfg.use_pallas,
                "compile": engine_telemetry.compile_snapshot(),
                "memory": engine_telemetry.device_memory_snapshot(),
                # Streaming tier (rapid_tpu/serving): present only when a
                # StreamDriver is attached — batch-only scrapes keep their
                # series set (golden names pinned either way).
                **(
                    {"stream": self.stream.snapshot()}
                    if self.stream is not None
                    else {}
                ),
                # Supervision tier: present only when a Supervisor is
                # attached (same stable-series rule).
                **(
                    {"recovery": self.recovery.snapshot()}
                    if self.recovery is not None
                    else {}
                ),
                # Device telemetry plane (cfg.telemetry == 1): the HOST
                # CACHE, zero-minted at attach and refreshed only at sync
                # boundaries — a scrape never fetches from device.
                **(
                    {"activity": dict(self._activity)}
                    if self._activity is not None
                    else {}
                ),
                # Device round-trace ring (cfg.trace == R > 0): the same
                # host-cache discipline — decoded at sync boundaries,
                # zero-minted at attach, never fetched by a scrape.
                **(
                    {"trace": self.trace}
                    if self._trace is not None
                    else {}
                ),
            },
            "transport": {},
            "recorder": None,
        }

    def prometheus_text(self) -> str:
        return exposition.prometheus_text(self.telemetry_snapshot())
